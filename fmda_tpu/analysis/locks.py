"""Lock-discipline race detector.

The serving stack is threaded end to end — gateway pumps, bus server
accept loops, metrics scrapes, tracer rings — and its convention is one
``self._lock`` (or ``_*lock``) per shared object with every mutation of
shared state inside ``with self._lock:``.  This rule makes that
convention checkable:

An attribute is **guarded** when

- its assignment carries a ``# guarded-by: _lock`` annotation, or
- any method of the class (``__init__`` aside) *writes* it inside a
  ``with self.<lock>:`` block — if one writer needed the lock, every
  other access is a suspect until proven deliberate.

Every read or write of a guarded attribute outside a lock scope is a
finding.  Deliberate lock-free fast paths declare themselves with
``# lock-free: <reason>`` on the access line (an empty reason is inert
— suppressions must say why), or are grandfathered into the baseline
with a justification.

Scope and honesty about what static analysis can see:

- ``__init__``/``__new__`` are exempt (construction happens-before
  publication to other threads);
- methods named ``*_locked`` are callee-side contracts ("caller holds
  the lock"): their bodies are treated as guarded, and *calling* one
  outside a lock scope is itself a finding;
- lock scopes are tracked lexically, so a closure defined inside a
  ``with`` block is treated as guarded even though it may run later —
  the cheap, predictable over-approximation.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from fmda_tpu.analysis.engine import Finding, LintContext, ParsedModule, Rule

#: attribute names that hold a mutex: _lock, _big_lock, ...
LOCK_ATTR_RE = re.compile(r"^_\w*lock$")
GUARDED_BY_RE = re.compile(r"guarded-by:\s*(\w+)")
LOCK_FREE_RE = re.compile(r"lock-free:\s*(\S.*)")

#: attribute stores that never count as shared-state mutation
_EXEMPT_ATTRS = ("__dict__",)

#: method names that mutate their receiver in place — a call to
#: ``self.X.append(...)`` under the lock marks ``X`` guarded exactly
#: like ``self.X = ...`` does (most of the repo's shared state is
#: dicts/deques/lists mutated through these, not rebound)
_MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "add", "discard", "update",
    "setdefault", "sort", "reverse", "write", "writelines", "flush",
})


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for an ``self.X`` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _base_self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for ``self.X`` reached through any subscript chain
    (``self.X[k]``, ``self.X[k][j]``), else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    return _self_attr(node)


def _with_locks(node: ast.With, locks: Set[str]) -> bool:
    """True when any item of the with statement acquires a class lock."""
    for item in node.items:
        attr = _self_attr(item.context_expr)
        if attr is not None and attr in locks:
            return True
    return False


class _ClassInfo:
    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.locks: Set[str] = set()
        #: guarded attribute -> lock name that guards it
        self.guarded: Dict[str, str] = {}
        #: attrs annotated guarded explicitly (never inferred away)
        self.annotated: Set[str] = set()


def _methods(cls: ast.ClassDef):
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield item


def _exempt_method(name: str) -> bool:
    return name in ("__init__", "__new__") or name.endswith("_locked")


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    severity = "warning"
    description = ("lock-guarded attributes must be read/written inside "
                   "`with self._lock:` (escape hatch: `# lock-free: reason`)")

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        found: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                found.extend(self._check_class(module, node))
        return found

    # -- per-class passes ---------------------------------------------------

    def _check_class(self, module: ParsedModule,
                     cls: ast.ClassDef) -> List[Finding]:
        info = _ClassInfo(cls)
        self._collect_locks(info)
        if not info.locks:
            return []
        self._collect_guarded(module, info)
        if not info.guarded:
            return []
        return self._collect_violations(module, info)

    def _collect_locks(self, info: _ClassInfo) -> None:
        for meth in _methods(info.node):
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is not None and LOCK_ATTR_RE.match(attr):
                            info.locks.add(attr)

    def _collect_guarded(self, module: ParsedModule, info: _ClassInfo) -> None:
        # explicit `# guarded-by: _lock` annotations, anywhere in the class
        for meth in _methods(info.node):
            for node in ast.walk(meth):
                if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                         ast.AugAssign)):
                    continue
                comment = module.comments.get(node.lineno, "")
                m = GUARDED_BY_RE.search(comment)
                if not m:
                    continue
                lock = m.group(1)
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        info.guarded[attr] = lock
                        info.annotated.add(attr)
        # inferred: attributes written under a lock in any non-exempt method
        for meth in _methods(info.node):
            if _exempt_method(meth.name):
                continue
            self._infer_walk(meth.body, info, held=None)
        for lock in info.locks:
            info.guarded.pop(lock, None)

    def _infer_walk(self, body, info: _ClassInfo,
                    held: Optional[str]) -> None:
        for node in body:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and attr in info.locks:
                        inner = attr
                self._infer_walk(node.body, info, inner)
                continue
            if held is not None:
                for sub in ast.walk(node):
                    attr = self._stored_attr(sub)
                    if attr is not None and attr not in info.annotated:
                        info.guarded.setdefault(attr, held)
            # recurse into compound statements, keeping the held state
            for child_body in self._child_bodies(node):
                self._infer_walk(child_body, info, held)

    @staticmethod
    def _child_bodies(node: ast.AST):
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(node, attr, None)
            if sub and isinstance(sub, list):
                yield sub
        for h in getattr(node, "handlers", []) or []:
            yield h.body
        for case in getattr(node, "cases", []) or []:  # ast.Match
            yield case.body

    @staticmethod
    def _stored_attr(node: ast.AST) -> Optional[str]:
        """``X`` when this node mutates ``self.X``: a plain/aug store, a
        subscript store (``self.X[k] = ...``, ``self.X[k] += ...``,
        ``del self.X[k]``), or an in-place mutator call
        (``self.X.append(...)``)."""
        if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node)
            if attr is not None and attr not in _EXEMPT_ATTRS:
                return attr
        if isinstance(node, ast.AugAssign):
            return _base_self_attr(node.target)
        if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            return _base_self_attr(node.value)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATOR_METHODS):
            return _base_self_attr(node.func.value)
        return None

    # -- violation pass -----------------------------------------------------

    def _collect_violations(self, module: ParsedModule,
                            info: _ClassInfo) -> List[Finding]:
        found: List[Finding] = []
        seen: Set[Tuple[str, str, str, str]] = set()

        def emit(meth: str, line: int, attr: str, kind: str) -> None:
            key = (info.node.name, meth, attr, kind)
            if key in seen:
                return  # one finding per (method, attr, kind) site family
            for ln in (line, line - 1):
                if LOCK_FREE_RE.search(module.comments.get(ln, "")):
                    # declared-deliberate lock-free access; the hatch
                    # covers every same-shaped access in this method
                    seen.add(key)
                    return
            seen.add(key)
            lock = info.guarded.get(attr, "_lock")
            found.append(self.finding(
                module.rel, line,
                f"{info.node.name}.{meth}: {kind} self.{attr} outside "
                f"`with self.{lock}:` (lock-guarded attribute)"))

        def walk(body, meth: str, held: bool) -> None:
            for node in body:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    inner = held or _with_locks(node, info.locks)
                    for item in node.items:
                        self._scan_expr(item.context_expr, module, info,
                                        meth, held, emit)
                    walk(node.body, meth, inner)
                    continue
                if not held:
                    self._scan_stmt(node, module, info, meth, emit)
                for child_body in self._child_bodies(node):
                    walk(child_body, meth, held)

        for meth in _methods(info.node):
            if _exempt_method(meth.name):
                continue
            walk(meth.body, meth.name, False)
        return found

    def _scan_stmt(self, node: ast.AST, module, info, meth, emit) -> None:
        """Flag guarded-attribute touches in this statement, skipping
        nested compound bodies (the caller recurses into those with the
        right held state)."""
        skip = set()
        for child_body in self._child_bodies(node):
            for sub in child_body:
                skip.update(ast.walk(sub))
        for sub in ast.walk(node):
            if sub in skip:
                continue
            self._scan_node(sub, module, info, meth, emit)

    def _scan_expr(self, expr: ast.AST, module, info, meth, held,
                   emit) -> None:
        if held:
            return
        for sub in ast.walk(expr):
            self._scan_node(sub, module, info, meth, emit)

    def _scan_node(self, sub: ast.AST, module, info, meth, emit) -> None:
        if isinstance(sub, ast.Attribute):
            attr = _self_attr(sub)
            if attr is None:
                return
            if attr in info.guarded:
                kind = ("write to" if isinstance(
                    sub.ctx, (ast.Store, ast.Del)) else "read of")
                emit(meth, sub.lineno, attr, kind)
            elif attr.endswith("_locked") and isinstance(sub.ctx, ast.Load):
                emit(meth, sub.lineno, attr, "call to")
        elif isinstance(sub, ast.AugAssign):
            attr = _self_attr(sub.target)
            if attr is not None and attr in info.guarded:
                emit(meth, sub.lineno, attr, "write to")
