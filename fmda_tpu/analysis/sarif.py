"""SARIF 2.1.0 export for lint results (``lint --sarif FILE``).

SARIF is the interchange format CI platforms (GitHub code scanning,
Azure DevOps, VS Code SARIF viewer) render as inline annotations — one
upload per lint run and every new finding lands on the diff line it
blames, instead of living in a console log nobody scrolls.

Mapping:

- each **rule** in the run becomes a ``tool.driver.rules`` entry
  (id, description, default level);
- each **new finding** becomes a ``results`` entry with its
  ``ruleId``/``level``/``message`` and one physical location
  (``fmda_tpu/<rel>`` relative to ``SRCROOT`` — the repo root);
- **baselined** findings are exported too, with a ``suppressions``
  entry carrying the baseline justification — accepted debt stays
  visible to the scanner without failing the run (SARIF consumers
  treat suppressed results as non-blocking).

Schema stability is load-bearing (CI parses this; the test pins it):
extend, don't rename.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from fmda_tpu.analysis.engine import Finding, LintResult, Rule

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")

#: Finding.severity -> SARIF result level
_LEVELS = {"error": "error", "warning": "warning"}


def _result(finding: Finding) -> Dict[str, object]:
    return {
        "ruleId": finding.rule,
        "level": _LEVELS.get(finding.severity, "warning"),
        "message": {"text": finding.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": f"fmda_tpu/{finding.path}",
                    "uriBaseId": "SRCROOT",
                },
                "region": {"startLine": max(1, int(finding.line))},
            },
        }],
    }


def to_sarif(result: LintResult,
             rules: Sequence[Rule]) -> Dict[str, object]:
    """The full SARIF document for one lint run."""
    results: List[Dict[str, object]] = [_result(f) for f in result.new]
    for f in result.baselined:
        doc = _result(f)
        doc["suppressions"] = [{
            "kind": "external",
            "justification": "grandfathered in "
                             "fmda_tpu/analysis/baseline.json",
        }]
        results.append(doc)
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "fmda-tpu-lint",
                    "rules": [
                        {
                            "id": r.id,
                            "shortDescription": {"text": r.description},
                            "defaultConfiguration": {
                                "level": _LEVELS.get(r.severity, "warning"),
                            },
                        }
                        for r in rules
                    ],
                },
            },
            "results": results,
        }],
    }
