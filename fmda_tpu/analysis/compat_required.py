"""compat-required: version-sensitive jax spellings stay in compat.py.

The drift scanner (:mod:`fmda_tpu.analysis.drift`) catches references
that do not resolve against the *installed* jax — but that gate is
one-sided: on a host running the newer jax, the new spelling resolves
fine, lint stays green, and the port silently reintroduces the exact
version coupling ``fmda_tpu/compat.py`` exists to absorb.  This rule is
the other jaw of the vise.  It confines every spelling listed in
:data:`fmda_tpu.compat.SHIMMED_SYMBOLS` — old *and* new — to the compat
module itself: a direct use anywhere on the kernel surface (``ops/``,
``parallel/``, ``models/``) is a finding, whatever jax is installed,
so call sites can only reach the arbitrated name through the shim.

Pure AST + the symbol dict; never imports jax (``compat`` resolves its
shims lazily), so the rule runs on jax-free hosts and under
``lint --no-drift``.
"""

from __future__ import annotations

from typing import List

from fmda_tpu.analysis.drift import _AliasCollector, _RefCollector, _in_scope
from fmda_tpu.analysis.engine import Finding, LintContext, ParsedModule, Rule
from fmda_tpu.compat import SHIMMED_SYMBOLS


class CompatRequiredRule(Rule):
    id = "compat-required"
    severity = "error"
    description = ("version-sensitive jax symbols are used only through "
                   "fmda_tpu.compat on the kernel surface")

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        if not _in_scope(module.rel):
            return []
        aliases = _AliasCollector()
        aliases.visit(module.tree)
        refs = _RefCollector(aliases.aliases)
        refs.visit(module.tree)
        found: List[Finding] = []
        reported = set()
        for line, dotted in sorted(set(aliases.symbols) | set(refs.refs)):
            hit = _shimmed_prefix(dotted)
            if hit is None or hit in reported:
                continue
            reported.add(hit)  # one finding per symbol per module
            found.append(self.finding(
                module.rel, line,
                f"version-sensitive jax symbol used directly: {hit} — "
                f"import `{SHIMMED_SYMBOLS[hit]}` from fmda_tpu.compat "
                f"instead"))
        return found


def _shimmed_prefix(dotted: str) -> str | None:
    """The listed symbol ``dotted`` is or extends (maximal attribute
    chains can run past the symbol: ``jax.lax.axis_size.__doc__``)."""
    parts = dotted.split(".")
    for i in range(len(parts), 1, -1):
        prefix = ".".join(parts[:i])
        if prefix in SHIMMED_SYMBOLS:
            return prefix
    return None
