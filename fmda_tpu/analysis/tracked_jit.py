"""tracked-jit: the serving stack compiles only through the ledger.

ISSUE 17 put a compile ledger under every serving-path ``jit``
(:func:`fmda_tpu.obs.device.tracked_jit`): per-program compile events,
cost-analysis FLOPs, and the unexpected-recompile detector the SLO
engine alerts on.  That visibility erodes one convenient ``jax.jit`` at
a time — a helper jitted in a refactor here, an experiment left in
there — and every untracked site is a program whose recompiles the
fleet cannot see.  This rule is the ratchet: inside the serving scope —
``runtime/``, ``train/`` (the continuous fine-tuning loop pins
zero-recompile as a contract), and the kernel dispatch seam — any direct
``jax.jit``/``jax.pjit`` call is a finding unless the site routes
through :func:`tracked_jit` or carries the standard in-place hatch
(``# lint: ignore[tracked-jit] reason``) naming why the program is
deliberately off-ledger.  Alias-aware: ``import jax as j`` and ``from
jax import jit as J`` are still caught.

Pure AST, no imports beyond the engine — runs on jax-free hosts.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from fmda_tpu.analysis.engine import Finding, LintContext, ParsedModule, Rule

#: directory prefixes inside the package that ARE the serving stack —
#: ``train/`` joined the scope when the continuous fine-tuning loop
#: pinned zero-recompile as a contract (its step programs sit on the
#: same ledger the SLO engine watches)
SCOPE_PREFIXES = ("runtime/", "train/")

#: single modules on the same compile path
SCOPE_MODULES = ("ops/dispatch.py",)

#: the one sanctioned home for a raw ``jax.jit`` in scope (the wrapper)
WRAPPER_MODULES = ("obs/device.py",)

JIT_FUNCS = ("jit", "pjit")

#: modules whose ``jit``/``pjit`` attributes count as compile entry
#: points when imported wholesale (``import jax``, ``import jax as j``)
JIT_MODULES = ("jax", "jax.experimental.pjit")


class TrackedJitRule(Rule):
    id = "tracked-jit"
    severity = "error"
    description = ("serving-stack modules (runtime/, train/, "
                   "ops/dispatch.py) compile through "
                   "obs.device.tracked_jit, never raw jax.jit/pjit, "
                   "except at annotated off-ledger sites")

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        rel = module.rel
        in_scope = (rel.startswith(SCOPE_PREFIXES)
                    or rel in SCOPE_MODULES)
        if not in_scope or rel in WRAPPER_MODULES:
            return []
        mod_aliases: Set[str] = set()
        func_aliases: Dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in JIT_MODULES:
                        mod_aliases.add(a.asname or a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module in JIT_MODULES:
                    for a in node.names:
                        if a.name in JIT_FUNCS:
                            func_aliases[a.asname or a.name] = a.name
        if not mod_aliases and not func_aliases:
            return []
        found: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            call = None
            if (isinstance(fn, ast.Attribute) and fn.attr in JIT_FUNCS
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in mod_aliases):
                call = f"jax.{fn.attr}"
            elif isinstance(fn, ast.Name) and fn.id in func_aliases:
                call = f"jax.{func_aliases[fn.id]}"
            if call is not None:
                found.append(self.finding(
                    rel, node.lineno,
                    f"serving-stack {call}() — compile through "
                    f"fmda_tpu.obs.device.tracked_jit so the ledger sees "
                    f"the program, or annotate a deliberate off-ledger "
                    f"site with `# lint: ignore[{self.id}] reason`"))
        return found

    def finish(self, ctx: LintContext) -> List[Finding]:
        # the scope lists police their own staleness, like every other
        # module-list rule: a refactor that moves a listed file must
        # shrink the list, not silently stop checking
        found: List[Finding] = []
        for rel in SCOPE_MODULES + WRAPPER_MODULES:
            if not (ctx.package_dir / rel).is_file():
                found.append(self.finding(
                    rel, 0,
                    f"stale scope entry: {rel} does not exist",
                    severity="error"))
        return found
