"""The four pre-existing tier-1 hygiene checks, as engine rules.

These contracts were born as ad-hoc AST walks inside
``tests/test_logging_hygiene.py`` (ISSUEs 2, 4, 6, 7); the logic now
lives here so ``python -m fmda_tpu lint`` enforces them alongside the
race/purity/drift analyzers, and the pytest side shrinks to thin
wrappers asserting zero findings.  Effect is unchanged: a violation
fails tier-1 the commit it appears.

- :class:`LoggingHygieneRule` — no ``print()``, no loggers outside the
  ``fmda_tpu`` namespace (allowlist: ``cli.py``, ``utils/env.py``);
- :class:`SpanClockRule` — span-recording code never calls
  ``time.time()`` (monotonic ``perf_counter_ns`` only — an NTP step
  must not fold a trace back on itself);
- :class:`RouterJaxImportRule` — router-role fleet modules never import
  jax at module scope (a fleet router runs on a bus-only host; the
  runtime subprocess half of the contract stays in pytest);
- :class:`ChaosGuardRule` — every ``_CHAOS`` injection-point touch sits
  under an ``if _CHAOS.enabled:`` guard (disabled chaos = one branch,
  zero allocation), and the instrumented modules keep their points.

Each rule also polices its own allowlist/module-list for staleness: a
refactor that moves a listed file must shrink the list, not silently
stop checking a path that no longer exists.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from fmda_tpu.analysis.engine import Finding, LintContext, ParsedModule, Rule

#: modules whose prints are their contract, relative to the package root
PRINT_ALLOWLIST = ("cli.py", "utils/env.py")

LOGGER_NAMESPACE = "fmda_tpu"

#: span-recording code — everywhere span timestamps are minted
SPAN_CODE = ("obs/trace.py",)

#: router-role fleet modules: a fleet router runs on a bus-only host, so
#: NOTHING on its import path may pull jax in at module scope — only
#: worker.py (which embeds the serving runtime) may
ROUTER_ROLE_MODULES = (
    "fleet/__init__.py",
    "fleet/hashring.py",
    "fleet/launcher.py",
    "fleet/membership.py",
    "fleet/router.py",
    "fleet/state.py",
    "fleet/wire.py",
)

#: modules carrying compiled-in chaos injection points, with the
#: per-module floor of guarded ``if _CHAOS.enabled:`` sites each must
#: keep (serving tier: router.pump / wire.request / link exchanges /
#: worker.step; data plane: engine.step / warehouse.append /
#: feed:<topic>).  A refactor that drops a module below its floor fails
#: tier-1 the commit it lands.
CHAOS_POINT_FLOORS = {
    "fleet/router.py": 1,
    "fleet/wire.py": 1,
    "fleet/worker.py": 1,
    "stream/engine.py": 1,
    "stream/warehouse.py": 1,
    "ingest/session.py": 1,
}
CHAOS_INSTRUMENTED = tuple(CHAOS_POINT_FLOORS)

#: the chaos modules together must keep at least this many guarded points
CHAOS_MIN_POINTS = 7


def _stale_entries(rule: Rule, ctx: LintContext, rels, list_name: str
                   ) -> List[Finding]:
    found = []
    for rel in rels:
        if not (ctx.package_dir / rel).is_file():
            found.append(rule.finding(
                rel, 0, f"stale {list_name} entry: {rel} does not exist",
                severity="error"))
    return found


class LoggingHygieneRule(Rule):
    """Library code reports through the obs plane or the ``fmda_tpu``
    logger hierarchy — never ``print()`` (invisible to log collectors,
    corrupts CLI JSON output), never a foreign logger."""

    id = "logging-hygiene"
    severity = "error"
    description = ("no print() and no loggers outside the fmda_tpu "
                   "namespace in library code")

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        if module.rel in PRINT_ALLOWLIST:
            return []
        found: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "print":
                found.append(self.finding(
                    module.rel, node.lineno, "print() call"))
            is_get_logger = (
                isinstance(fn, ast.Attribute) and fn.attr == "getLogger"
            ) or (isinstance(fn, ast.Name) and fn.id == "getLogger")
            if is_get_logger:
                if not node.args:
                    found.append(self.finding(
                        module.rel, node.lineno,
                        "getLogger() with no name (the root logger is "
                        "not ours to configure)"))
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    name = arg.value
                    if name != LOGGER_NAMESPACE and not name.startswith(
                            LOGGER_NAMESPACE + "."):
                        found.append(self.finding(
                            module.rel, node.lineno,
                            f"logger {name!r} outside the "
                            f"{LOGGER_NAMESPACE!r} namespace"))
                elif isinstance(arg, ast.Name) and arg.id == "__name__":
                    pass  # module __name__ always resolves under fmda_tpu.*
                else:
                    found.append(self.finding(
                        module.rel, node.lineno,
                        "getLogger() with a dynamic name — use a literal "
                        "'fmda_tpu.*' name"))
        return found

    def finish(self, ctx: LintContext) -> List[Finding]:
        return _stale_entries(self, ctx, PRINT_ALLOWLIST, "allowlist")


class SpanClockRule(Rule):
    """Span timestamps come from ``time.perf_counter_ns`` — monotonic
    and ns-resolution, so a mid-run NTP step can never make stage
    durations negative.  ``time.time()`` in span code is a bug."""

    id = "span-wall-clock"
    severity = "error"
    description = "span-recording code must never call time.time()"

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        if module.rel not in SPAN_CODE:
            return []
        found: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "time"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in ("time", "_time")):
                found.append(self.finding(
                    module.rel, node.lineno, "time.time() call"))
            elif isinstance(fn, ast.Name) and fn.id == "time":
                found.append(self.finding(
                    module.rel, node.lineno, "bare time() call"))
        if "perf_counter_ns" not in module.text:
            found.append(self.finding(
                module.rel, 0,
                "span code lost its perf_counter_ns clock"))
        return found

    def finish(self, ctx: LintContext) -> List[Finding]:
        return _stale_entries(self, ctx, SPAN_CODE, "SPAN_CODE")


class RouterJaxImportRule(Rule):
    """AST half of the bus-only-host contract: no router-role fleet
    module imports jax (or a submodule) at module scope.  Deferred
    imports inside function bodies are the sanctioned pattern; the
    transitive-import runtime half lives in pytest (subprocess probe).
    """

    id = "router-jax-import"
    severity = "error"
    description = ("router-role fleet modules must not import jax at "
                   "module scope")

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        if module.rel not in ROUTER_ROLE_MODULES:
            return []
        found: List[Finding] = []

        def walk(body):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # deferred imports are the sanctioned pattern
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name.split(".")[0] == "jax":
                            found.append(self.finding(
                                module.rel, node.lineno,
                                f"module-scope import {alias.name}"))
                elif isinstance(node, ast.ImportFrom):
                    if (node.module or "").split(".")[0] == "jax":
                        found.append(self.finding(
                            module.rel, node.lineno,
                            f"module-scope from {node.module} import"))
                elif isinstance(node, (ast.If, ast.Try, ast.With,
                                       ast.ClassDef)):
                    for attr in ("body", "orelse", "finalbody", "handlers"):
                        sub = getattr(node, attr, None)
                        if not sub:
                            continue
                        for item in sub:
                            if isinstance(item, ast.excepthandler):
                                walk(item.body)
                        walk([s for s in sub
                              if not isinstance(s, ast.excepthandler)])

        walk(module.tree.body)
        return found

    def finish(self, ctx: LintContext) -> List[Finding]:
        return _stale_entries(
            self, ctx, ROUTER_ROLE_MODULES, "ROUTER_ROLE_MODULES")


def _is_enabled_guard(node: ast.If) -> bool:
    t = node.test
    return (isinstance(t, ast.Attribute) and t.attr == "enabled"
            and isinstance(t.value, ast.Name) and t.value.id == "_CHAOS")


class ChaosGuardRule(Rule):
    """AST contract for the never-abort chaos layer (docs/chaos.md):
    with chaos off, every compiled-in injection point is a single
    predictable branch — any ``_CHAOS`` use reachable without passing
    the ``enabled`` test is a hot-path regression."""

    id = "chaos-guard"
    severity = "error"
    description = ("every _CHAOS injection-point use sits under an "
                   "`if _CHAOS.enabled:` guard")

    def __init__(self) -> None:
        self._points: Dict[str, int] = {}

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        if module.rel not in CHAOS_INSTRUMENTED:
            return []
        found: List[Finding] = []
        points = [0]

        def walk(node, guarded):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_CHAOS"
                    for t in node.targets):
                return  # the module-scope singleton capture
            if isinstance(node, ast.If) and _is_enabled_guard(node):
                points[0] += 1
                for child in node.body:
                    walk(child, True)
                for child in node.orelse:
                    walk(child, guarded)
                return
            if isinstance(node, ast.Name) and node.id == "_CHAOS" \
                    and not guarded:
                found.append(self.finding(
                    module.rel, node.lineno,
                    "_CHAOS use outside an `if _CHAOS.enabled:` guard"))
            for child in ast.iter_child_nodes(node):
                walk(child, guarded)

        walk(module.tree, False)
        self._points[module.rel] = points[0]
        floor = CHAOS_POINT_FLOORS[module.rel]
        if points[0] < floor:
            found.append(self.finding(
                module.rel, 0,
                f"module carries {points[0]} guarded injection "
                f"point(s), floor is {floor}"))
        return found

    def finish(self, ctx: LintContext) -> List[Finding]:
        found = _stale_entries(
            self, ctx, CHAOS_INSTRUMENTED, "CHAOS_INSTRUMENTED")
        total = sum(self._points.values())
        seen = [r for r in CHAOS_INSTRUMENTED if r in self._points]
        if len(seen) == len(CHAOS_INSTRUMENTED) and total < CHAOS_MIN_POINTS:
            found.append(self.finding(
                CHAOS_INSTRUMENTED[0], 0,
                f"chaos modules carry {total} guarded injection points, "
                f"expected >= {CHAOS_MIN_POINTS} (the walk must actually "
                "see the points)"))
        self._points = {}
        ctx.reports.setdefault("chaos_points", total)
        return found
