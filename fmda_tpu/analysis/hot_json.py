"""hot-path-json: the data plane's json encode/decode stays in the codec.

ISSUE 12 replaced every hot-path JSON boundary — wire frames, bus
values, the migration state codec, the journal — with the binary codec
(:mod:`fmda_tpu.stream.codec`).  That win erodes one convenient
``json.dumps`` at a time: a counter serialized per tick here, a debug
field re-encoded per flush there, and the serialize/parse tax is back
without any single diff looking hot.  This rule is the ratchet: inside
the data-plane scope — ``fleet/``, ``runtime/``, and the bus/journal
transport modules under ``stream/`` — any ``json.dumps``/``loads``/
``dump``/``load`` call is a finding unless it sits in the codec module
itself or carries the standard in-place hatch
(``# lint: ignore[hot-path-json] reason``) naming why the site is
control-plane (the journal's human-inspectable JSONL layout, for
example).  Alias-aware: ``import json as j`` and ``from json import
dumps as d`` are still caught.

Pure AST, no imports beyond the engine — runs on jax-free hosts.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from fmda_tpu.analysis.engine import Finding, LintContext, ParsedModule, Rule

#: directory prefixes inside the package that ARE the data plane
SCOPE_PREFIXES = ("fleet/", "runtime/")

#: stream-layer transport modules on the same hot path
SCOPE_MODULES = (
    "stream/bus.py",
    "stream/native_bus.py",
    "stream/kafka_bus.py",
    "stream/journal.py",
)

#: the one sanctioned home for json on the data plane
CODEC_MODULES = ("stream/codec.py",)

JSON_FUNCS = ("dumps", "loads", "dump", "load")


class HotPathJsonRule(Rule):
    id = "hot-path-json"
    severity = "error"
    description = ("data-plane modules call json.dumps/loads only inside "
                   "the codec module or at annotated control-plane sites")

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        rel = module.rel
        in_scope = (rel.startswith(SCOPE_PREFIXES)
                    or rel in SCOPE_MODULES)
        if not in_scope or rel in CODEC_MODULES:
            return []
        mod_aliases: Set[str] = set()
        func_aliases: Dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "json":
                        mod_aliases.add(a.asname or "json")
            elif isinstance(node, ast.ImportFrom) and node.module == "json":
                for a in node.names:
                    if a.name in JSON_FUNCS:
                        func_aliases[a.asname or a.name] = a.name
        if not mod_aliases and not func_aliases:
            return []
        found: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            call = None
            if (isinstance(fn, ast.Attribute) and fn.attr in JSON_FUNCS
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in mod_aliases):
                call = f"json.{fn.attr}"
            elif isinstance(fn, ast.Name) and fn.id in func_aliases:
                call = f"json.{func_aliases[fn.id]}"
            if call is not None:
                found.append(self.finding(
                    rel, node.lineno,
                    f"data-plane {call}() — encode through "
                    f"fmda_tpu.stream.codec, or annotate a deliberate "
                    f"control-plane site with "
                    f"`# lint: ignore[{self.id}] reason`"))
        return found

    def finish(self, ctx: LintContext) -> List[Finding]:
        # the scope lists police their own staleness, like every other
        # module-list rule: a refactor that moves a listed file must
        # shrink the list, not silently stop checking
        found: List[Finding] = []
        for rel in SCOPE_MODULES + CODEC_MODULES:
            if not (ctx.package_dir / rel).is_file():
                found.append(self.finding(
                    rel, 0,
                    f"stale scope entry: {rel} does not exist",
                    severity="error"))
        return found
