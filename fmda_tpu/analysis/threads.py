"""thread-lifecycle: every spawned thread is daemonized or joined.

A non-daemon thread nobody joins keeps the interpreter alive after
``main`` returns — a hung teardown in production and an eaten timeout
in every test run (the BusServer ``accept()`` stall that once cost
tier-1 ~300 s of wall clock was exactly this class).  A *daemon* thread
is the sanctioned fire-and-forget shape; a non-daemon one is a promise
that some owner joins (or, for ``threading.Timer``, cancels) it on a
close path.  This rule checks the promise statically:

every ``threading.Thread(...)`` / ``threading.Timer(...)`` construction
in the package must either

- pass ``daemon=True`` at the constructor (or set ``<target>.daemon =
  True`` on the assigned name before ``start()``), or
- be assigned to ``self.<attr>`` in a class one of whose methods calls
  ``self.<attr>.join(...)`` / ``.cancel(...)`` — the owner's close
  path; a local variable must be joined/cancelled in the same function.

Deliberate exceptions annotate in place
(``# lint: ignore[thread-lifecycle] reason``).  Lexical, class-local
reasoning — the honest static approximation: a thread handed to
another object to join is out of scope and should say so with the
hatch.  Pure AST; jax-free.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from fmda_tpu.analysis.engine import Finding, LintContext, ParsedModule, Rule

SPAWN_CLASSES = ("Thread", "Timer")

#: methods that settle a thread's lifecycle on the owner's close path
SETTLE_METHODS = ("join", "cancel")


def _spawn_class(node: ast.Call, mod_aliases: Set[str],
                 name_aliases: Dict[str, str]) -> Optional[str]:
    """``"Thread"``/``"Timer"`` when ``node`` constructs one, resolved
    through ``import threading [as t]`` and ``from threading import
    Thread [as T]``."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in SPAWN_CLASSES \
            and isinstance(fn.value, ast.Name) \
            and fn.value.id in mod_aliases:
        return fn.attr
    if isinstance(fn, ast.Name):
        return name_aliases.get(fn.id)
    return None


def _daemon_kwarg(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def _assigned_target(parent_assign) -> Optional[ast.AST]:
    if isinstance(parent_assign, ast.AnnAssign):
        return parent_assign.target
    if parent_assign is None or len(parent_assign.targets) != 1:
        return None
    return parent_assign.targets[0]


class ThreadLifecycleRule(Rule):
    id = "thread-lifecycle"
    severity = "error"
    description = ("threading.Thread/Timer constructions are daemonized or "
                   "provably joined/cancelled on the owner's close path")

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        mod_aliases: Set[str] = set()
        #: local name -> spawn class ("Thread"/"Timer") for from-imports
        name_aliases: Dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        mod_aliases.add(a.asname or "threading")
            elif isinstance(node, ast.ImportFrom) \
                    and node.module == "threading":
                for a in node.names:
                    if a.name in SPAWN_CLASSES:
                        name_aliases[a.asname or a.name] = a.name
        if not mod_aliases and not name_aliases:
            return []
        found: List[Finding] = []
        #: Call node -> (enclosing function, enclosing class, Assign)
        context = self._spawn_context(module.tree)
        for node, (func, cls, assign) in context.items():
            spawned = _spawn_class(node, mod_aliases, name_aliases)
            if spawned is None or _daemon_kwarg(node):
                continue
            target = _assigned_target(assign)
            scope = (f"{cls.name}.{func.name}" if cls and func
                     else func.name if func else "<module>")
            settle = "/".join(SETTLE_METHODS)
            if isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" and cls is not None:
                if self._class_settles(cls, target.attr) \
                        or self._daemon_set(cls, "self", target.attr):
                    continue
                found.append(self.finding(
                    module.rel, node.lineno,
                    f"{scope}: non-daemon {spawned} stored to "
                    f"self.{target.attr} is never {settle}ed by any "
                    f"method of {cls.name} — daemonize it or settle it "
                    "on the close path"))
            elif isinstance(target, ast.Name) and func is not None:
                if self._func_settles(func, target.id) \
                        or self._daemon_set(func, None, target.id):
                    continue
                found.append(self.finding(
                    module.rel, node.lineno,
                    f"{scope}: non-daemon {spawned} bound to "
                    f"`{target.id}` is never {settle}ed in this "
                    "function — daemonize it or settle it before "
                    "returning"))
            else:
                found.append(self.finding(
                    module.rel, node.lineno,
                    f"{scope}: non-daemon {spawned} is fire-and-forget "
                    "(never bound, so nothing can ever join it) — "
                    "daemonize it"))
        return found

    # -- context / ownership resolution --------------------------------------

    @staticmethod
    def _spawn_context(tree: ast.AST) -> Dict[ast.Call, tuple]:
        """Every Call node mapped to (function, class, direct Assign)."""
        out: Dict[ast.Call, tuple] = {}

        def walk(node, func, cls, assign):
            for child in ast.iter_child_nodes(node):
                c_func, c_cls, c_assign = func, cls, assign
                if isinstance(child, ast.ClassDef):
                    c_cls, c_func = child, None
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    c_func = child
                elif isinstance(child, (ast.Assign, ast.AnnAssign)):
                    c_assign = child
                elif not isinstance(child, (ast.expr, ast.keyword)):
                    c_assign = None
                if isinstance(child, ast.Call):
                    out[child] = (c_func, c_cls, c_assign)
                walk(child, c_func, c_cls, c_assign)

        walk(tree, None, None, None)
        return out

    @staticmethod
    def _settle_calls(tree: ast.AST, base: Optional[str], attr: str) -> bool:
        """Any ``<base>.<attr>.join()``/``.cancel()`` under ``tree``
        (``base=None`` means a bare local name)."""
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in SETTLE_METHODS):
                continue
            owner = node.func.value
            if base is None:
                if isinstance(owner, ast.Name) and owner.id == attr:
                    return True
            elif isinstance(owner, ast.Attribute) and owner.attr == attr \
                    and isinstance(owner.value, ast.Name) \
                    and owner.value.id == base:
                return True
        return False

    def _class_settles(self, cls: ast.ClassDef, attr: str) -> bool:
        return self._settle_calls(cls, "self", attr)

    @staticmethod
    def _daemon_set(tree: ast.AST, base: Optional[str], attr: str) -> bool:
        """``<target>.daemon = True`` anywhere in the owner scope."""
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                    and node.targets[0].attr == "daemon"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True):
                continue
            owner = node.targets[0].value
            if base is None:
                if isinstance(owner, ast.Name) and owner.id == attr:
                    return True
            elif isinstance(owner, ast.Attribute) and owner.attr == attr \
                    and isinstance(owner.value, ast.Name) \
                    and owner.value.id == base:
                return True
        return False

    def _func_settles(self, func: ast.AST, name: str) -> bool:
        return self._settle_calls(func, None, name)
