"""counted-loss: the never-abort contract as a checked property.

The chaos and pipeline soaks enforce conservation *dynamically*:
``submitted == served + Σ counted losses`` (fmda_tpu.chaos.soak) and
``ingested == landed + Σ counted losses`` (fmda_tpu.chaos.pipeline).
But a soak only samples the paths its fault plan happens to hit — a new
``except Exception: pass`` anywhere on the data plane silently breaks
conservation until a soak trips over it.  This rule makes the
discipline static, in two parts:

**Handler accounting.**  Every ``except`` handler in the hot packages
(``fleet/``, ``runtime/``, ``stream/``, ``chaos/``, ``obs/``) must do
one of:

- **re-raise** (any ``raise`` in the handler body — converting to a
  domain error counts, the failure stays loud);
- **increment a registered counter**, directly (``metrics.count(...)``,
  ``counter.inc(...)``, ``self.errors += 1``, the ``d[k] = d.get(k,0)+1``
  tally) or via a **one-level same-module callee** that counts in its
  own body (``self._publish_control_counted(...)``) — resolved through
  the whole-program index (:mod:`fmda_tpu.analysis.program`);
- declare itself loss-free in place: ``# loss-free: <reason>`` on the
  ``except`` line or the line above.  An empty reason is inert —
  suppressions must say why, same contract as ``# lock-free:``.

**Conservation vocabulary cross-check** (the ``topics.py`` move,
applied to loss counters).  The gates declare which counters they sum —
``LOSS_COUNTERS`` in ``chaos/soak.py``, ``ROUTER_LOSS_COUNTERS`` /
``GATEWAY_LOSS_COUNTERS`` / ``QUALITY_LOSS_COUNTERS`` in
``obs/aggregate.py`` — and this rule harvests those tuples (parsed,
not imported) and checks both ways:

- a vocabulary entry **no code ever counts** is a dead gate term (the
  identity silently weakens) — finding on the declaring line;
- a **drop site** in a conservation-domain module (``fleet/router.py``
  for the fleet identity, ``runtime/gateway.py`` for the in-process
  one, ``obs/quality.py`` for the label-join capture ledger) counting
  into a loss-shaped counter the gate never sums is a
  leak in the identity — finding at the increment, unless annotated
  (``# lint: ignore[counted-loss] reason``) for counters that are
  deliberately outside it (e.g. ``routed_ticks_lost`` pre-counts ticks
  that later age into ``results_missing`` — summing both would double
  count).

Pure AST + the shared program index; jax-free.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from fmda_tpu.analysis.engine import Finding, LintContext, ParsedModule, Rule
from fmda_tpu.analysis.program import subtree_increments_counter

#: packages whose except handlers must account (the data/control plane
#: the soak gates cover)
SCOPE_PREFIXES = ("fleet/", "runtime/", "stream/", "chaos/", "obs/")

LOSS_FREE_RE = re.compile(r"loss-free:\s*(\S.*)")

#: counter names that denote a discarded unit of work
LOSS_NAME_RE = re.compile(r"lost|shed|missing|dropped|expired")

#: modules declaring the gates' loss vocabularies: rel -> constant-name
#: regex for the tuples to harvest there
VOCABULARY_MODULES = {
    "chaos/soak.py": re.compile(r"^LOSS_COUNTERS$"),
    "obs/aggregate.py": re.compile(
        r"^(ROUTER|GATEWAY|QUALITY)_LOSS_COUNTERS$"),
}

#: conservation domains: module whose counters a gate sums -> the
#: vocabulary constants that define its identity
CONSERVATION_DOMAINS = {
    "fleet/router.py": ("LOSS_COUNTERS", "ROUTER_LOSS_COUNTERS"),
    "runtime/gateway.py": ("GATEWAY_LOSS_COUNTERS",),
    "obs/quality.py": ("QUALITY_LOSS_COUNTERS",),
}


def _handler_exc_label(handler: ast.ExceptHandler) -> str:
    if handler.type is None:
        return "bare except"
    try:
        return f"except {ast.unparse(handler.type)}"
    except Exception:  # pragma: no cover - unparse is total on py>=3.9
        return "except <?>"


def _enclosing_scopes(tree: ast.AST) -> Dict[ast.AST, str]:
    """Map every node to its enclosing function qualname (dotted)."""
    scopes: Dict[ast.AST, str] = {}

    def walk(node: ast.AST, qual: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_qual = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                child_qual = f"{qual}.{child.name}" if qual else child.name
            scopes[child] = child_qual
            walk(child, child_qual)

    walk(tree, "")
    return scopes


class CountedLossRule(Rule):
    id = "counted-loss"
    severity = "warning"
    description = ("hot-path except handlers re-raise, count a registered "
                   "counter, or carry `# loss-free: reason`; loss counters "
                   "cross-check against the soak gates' vocabulary")

    def __init__(self) -> None:
        #: loss-shaped counter increments seen in conservation-domain
        #: modules: (counter, rel, line)
        self._domain_losses: List[Tuple[str, str, int]] = []

    # -- per-module: handler accounting --------------------------------------

    def check(self, module: ParsedModule, ctx: LintContext) -> List[Finding]:
        rel = module.rel
        if not rel.startswith(SCOPE_PREFIXES):
            return []
        index = ctx.index()
        scopes = _enclosing_scopes(module.tree)
        found: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if self._loss_free(module, handler.lineno):
                    continue
                if any(isinstance(sub, ast.Raise)
                       for sub in ast.walk(handler)):
                    continue
                if subtree_increments_counter(handler):
                    continue
                if index.callee_counts(rel, handler):
                    continue
                scope = scopes.get(handler) or "<module>"
                found.append(self.finding(
                    rel, handler.lineno,
                    f"{scope}: `{_handler_exc_label(handler)}` swallows "
                    "without accounting — re-raise, increment a "
                    "registered counter, or annotate "
                    "`# loss-free: reason`"))
        if rel in CONSERVATION_DOMAINS:
            self._collect_domain_losses(module)
        return found

    @staticmethod
    def _loss_free(module: ParsedModule, line: int) -> bool:
        """The ``# loss-free: reason`` hatch: on the ``except`` line
        itself, or anywhere in the contiguous block of COMMENT-ONLY
        lines directly above it (handler annotations read better
        wrapped).  Trailing comments on *code* lines stop the upward
        walk — a previous handler's same-line hatch (or a stale marker
        on the last try-body statement) must never bleed down and
        exempt the next handler."""
        if LOSS_FREE_RE.search(module.comments.get(line, "")):
            return True
        lines = module.text.splitlines()
        ln = line - 1
        while (ln in module.comments and 0 < ln <= len(lines)
               and lines[ln - 1].lstrip().startswith("#")):
            if LOSS_FREE_RE.search(module.comments[ln]):
                return True
            ln -= 1
        return False

    def _collect_domain_losses(self, module: ParsedModule) -> None:
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "count"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if LOSS_NAME_RE.search(name):
                self._domain_losses.append((name, module.rel, node.lineno))

    # -- whole-program: the vocabulary cross-check ---------------------------

    def _vocabularies(self, ctx: LintContext) -> Dict[str, Tuple[tuple, int]]:
        """``constant name -> ((counter names...), declaring line)``,
        harvested from the gate modules' tuple literals."""
        out: Dict[str, Tuple[tuple, int]] = {}
        for rel, name_re in VOCABULARY_MODULES.items():
            module = ctx.module(rel)
            if module is None:
                continue
            for node in module.tree.body:
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and name_re.match(node.targets[0].id)
                        and isinstance(node.value, (ast.Tuple, ast.List))):
                    continue
                names = tuple(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
                out[node.targets[0].id] = (names, node.lineno)
        return out

    def finish(self, ctx: LintContext) -> List[Finding]:
        index = ctx.index()
        vocabs = self._vocabularies(ctx)
        found: List[Finding] = []
        # 1) dead gate terms: summed by a gate, counted by no one
        for const, (names, line) in sorted(vocabs.items()):
            rel = next(r for r, pat in VOCABULARY_MODULES.items()
                       if pat.match(const))
            for name in names:
                if name not in index.counter_sites:
                    found.append(self.finding(
                        rel, line,
                        f"conservation vocabulary entry {name!r} "
                        f"({const}) is summed by the gate but never "
                        "counted anywhere — a dead term weakens the "
                        "identity", severity="error"))
        # 2) drop sites outside the identity (one finding per site, so
        # each deliberate exception annotates itself in place)
        for name, rel, line in self._domain_losses:
            domain_vocab: set = set()
            for const in CONSERVATION_DOMAINS.get(rel, ()):
                domain_vocab.update(vocabs.get(const, ((), 0))[0])
            if name in domain_vocab:
                continue
            found.append(self.finding(
                rel, line,
                f"drop site counts into {name!r}, which the conservation "
                "gate never sums — add it to the gate vocabulary or "
                "annotate why it is outside the identity"))
        ctx.reports["counted_loss"] = {
            "vocabulary": {c: list(v[0]) for c, v in sorted(vocabs.items())},
            # the pipeline gate's loss fields are REPORT keys over
            # engine/journal stats, not counter names — carried for the
            # docs/operators, exempt from the counter cross-checks
            "pipeline_loss_fields": list(
                self._pipeline_fields(ctx)),
            "registered_counters": sorted(index.counter_sites),
        }
        self._domain_losses = []
        return found

    @staticmethod
    def _pipeline_fields(ctx: LintContext) -> Tuple[str, ...]:
        module = ctx.module("chaos/pipeline.py")
        if module is None:
            return ()
        for node in module.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "PIPELINE_LOSS_FIELDS"
                    and isinstance(node.value, (ast.Tuple, ast.List))):
                return tuple(
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str))
        return ()
