"""Typed configuration for the fmda_tpu framework.

Re-designs the reference's flat constants module (``/root/reference/config.py``)
as frozen dataclasses while keeping its single load-bearing property: the
**config → schema codegen**.  In the reference, changing ``bid_levels`` or
``event_list`` reshapes the Kafka message schemas, the Spark streaming schemas,
the MariaDB DDL, and the training feature set (``create_database.py:29-70``,
``spark_consumer.py:241-291``).  Here the same knobs drive
:meth:`FeatureConfig.table_columns` / :meth:`FeatureConfig.x_fields`, which
every other layer (stream engine, warehouse, data pipeline, model input width,
serving) derives its shapes from.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Tuple


# ---------------------------------------------------------------------------
# Bus (message transport) — replaces the reference's Kafka topic layout
# (config.py:15: vix, volume, cot, ind, deep, predict_timestamp, prediction).
# ---------------------------------------------------------------------------

TOPIC_VIX = "vix"
TOPIC_VOLUME = "volume"
TOPIC_COT = "cot"
TOPIC_IND = "ind"
TOPIC_DEEP = "deep"
TOPIC_PREDICT_TIMESTAMP = "predict_timestamp"
TOPIC_PREDICTION = "prediction"
#: Fleet-serving results (fmda_tpu.runtime): one topic, per-session
#: consumption keyed on the message's ``session`` field.
TOPIC_FLEET_PREDICTION = "fleet_prediction"
#: Multi-host fleet control plane (fmda_tpu.fleet): worker hello/
#: heartbeat/goodbye, ownership-table announcements, migrated session
#: state.  Not in DEFAULT_TOPICS — only fleet topologies carry it
#: (fleet_topics adds it alongside the per-worker inboxes).
TOPIC_FLEET_CONTROL = "fleet_control"
#: Per-worker tick-inbox topic prefix (fmda_tpu.fleet): the router
#: publishes a worker's opens/ticks/closes/drains to
#: ``fleet_ticks_<worker_id>`` in routing order — the inbox's FIFO
#: offsets ARE the ordering guarantee the migration protocol leans on.
TOPIC_FLEET_TICKS_PREFIX = "fleet_ticks_"

DEFAULT_TOPICS: Tuple[str, ...] = (
    TOPIC_VIX,
    TOPIC_VOLUME,
    TOPIC_COT,
    TOPIC_IND,
    TOPIC_DEEP,
    TOPIC_PREDICT_TIMESTAMP,
    TOPIC_PREDICTION,
    TOPIC_FLEET_PREDICTION,
)


def fleet_worker_topic(worker_id: str) -> str:
    """The tick-inbox topic of one fleet worker."""
    return TOPIC_FLEET_TICKS_PREFIX + worker_id


def fleet_topics(worker_ids) -> Tuple[str, ...]:
    """Every extra topic a fleet topology needs on its bus: the control
    plane plus one inbox per worker (append to ``DEFAULT_TOPICS`` when
    constructing the topology's bus)."""
    return (TOPIC_FLEET_CONTROL,) + tuple(
        fleet_worker_topic(w) for w in worker_ids)


@dataclass(frozen=True)
class BusConfig:
    """Message-bus layout (ref: config.py:15 ``kafka_config``)."""

    topics: Tuple[str, ...] = DEFAULT_TOPICS
    #: Ring-buffer capacity per topic (records) for the native bus backend.
    capacity: int = 1 << 16
    #: External Kafka brokers, only used by the optional Kafka adapter.
    servers: Tuple[str, ...] = ("localhost:9092",)


# ---------------------------------------------------------------------------
# Warehouse
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class WarehouseConfig:
    """Warehouse backend (ref: MariaDB, config.py:21-28).

    The framework-owned default is an embedded SQLite database (zero external
    processes); a MySQL/MariaDB adapter with the reference's exact DDL can be
    selected with ``backend="mysql"`` when ``mysql.connector`` is installed.
    """

    backend: str = "sqlite"
    path: str = ":memory:"  # sqlite path or file
    database_name: str = "stock_data"
    table_name: str = "stock_data_joined"
    #: Write-ahead journal file for warehouse-outage survival
    #: (fmda_tpu.stream.journal.BufferedWarehouse): failed landings
    #: spill here durably and a backfill loop drains them on recovery,
    #: idempotent on timestamp.  None disables the buffer (a failed
    #: insert raises through the engine step, pre-ISSUE-10 behavior).
    journal_path: Optional[str] = None
    #: Bound on journaled rows; overflow sheds the oldest, counted.
    journal_bound: int = 65536
    #: Journal record layout: ``jsonl`` (one JSON line per row — the
    #: human-inspectable debug format) or ``binary`` (length-prefixed
    #: packed-column codec frames, fmda_tpu.stream.codec — the same
    #: layout the binary wire speaks; no text round trip on the landing
    #: hot path).  Recovery auto-detects per record, so flipping this
    #: never strands an existing journal.
    journal_format: str = "jsonl"
    # MySQL parity fields (unused by the sqlite backend)
    user: str = "admin"
    password: str = "admin"
    hostname: str = "localhost"
    port: int = 3306


# ---------------------------------------------------------------------------
# Feature configuration + schema codegen
# ---------------------------------------------------------------------------

DEFAULT_EVENT_LIST: Tuple[str, ...] = (
    "Crude Oil Inventories",
    "ISM Non-Manufacturing PMI",
    "ISM Non-Manufacturing Employment",
    "Services PMI",
    "ADP Nonfarm Employment Change",
    "Core CPI",
    "Fed Interest Rate Decision",
    "Building Permits",
    "Core Retail Sales",
    "Retail Sales",
    "JOLTs Job Openings",
    "Nonfarm Payrolls",
    "Unemployment Rate",
)

EVENT_VALUES: Tuple[str, ...] = ("Actual", "Prev_actual_diff", "Forc_actual_diff")

#: OHLCV column names as used by the reference end to end (the Alpha Vantage
#: JSON keys ``1. open`` etc. become ``1_open`` after key sanitisation,
#: getMarketData.py:240).
VOLUME_COLUMNS: Tuple[str, ...] = (
    "1_open",
    "2_high",
    "3_low",
    "4_close",
    "5_volume",
    "wick_prct",
)

COT_GROUPS: Tuple[str, ...] = ("Asset", "Leveraged")
COT_VALUES: Tuple[str, ...] = (
    "long_pos",
    "long_pos_change",
    "long_open_int",
    "short_pos",
    "short_pos_change",
    "short_open_int",
)

TARGET_COLUMNS: Tuple[str, ...] = ("up1", "up2", "down1", "down2")


def sanitize_event(event_name: str) -> str:
    """Event name → column stem (ref: config.py:58)."""
    return event_name.replace(" ", "_").replace("-", "_")


@dataclass(frozen=True)
class FeatureConfig:
    """Feature-engineering knobs (ref: config.py:31-65) + schema codegen.

    The derived-feature parameters replicate the reference's SQL views
    (create_database.py:76-190), including its quirks: the stochastic
    oscillator and ATR windows are written as ``14 PRECEDING AND CURRENT ROW``
    — i.e. **15-row** windows — while the MA views use ``period-1 PRECEDING``
    (= ``period``-row windows).
    """

    get_cot: bool = True
    get_vix: bool = True
    #: Ticker whose OHLCV volume feed is ingested, or None to disable
    #: (ref: config.py:33 ``get_stock_volume = 'SPY'``).
    get_stock_volume: Optional[str] = "SPY"

    bid_levels: int = 7
    ask_levels: int = 7

    volume_ma_periods: Tuple[int, ...] = (6, 20)
    price_ma_periods: Tuple[int, ...] = (20,)
    delta_ma_periods: Tuple[int, ...] = (12,)

    bollinger_period: int = 20
    bollinger_std: float = 2.0

    stochastic_oscillator: bool = True
    #: ``N PRECEDING`` counts — the effective rolling window is N+1 rows.
    stoch_preceding: int = 14
    atr_preceding: int = 14

    event_list: Tuple[str, ...] = DEFAULT_EVENT_LIST

    # Target construction (create_database.py:176-190)
    target_n1: float = 1.5
    target_n2: float = 3.0
    target_lead1: int = 8
    target_lead2: int = 15

    #: Stream alignment: floor timestamps to this many seconds
    #: (spark_consumer.py:111 — 5 minutes) and join feeds whose timestamps lie
    #: within ``join_tolerance_s`` after the order-book timestamp
    #: (spark_consumer.py:439-443 — 3 minutes).
    floor_s: int = 5 * 60
    join_tolerance_s: int = 3 * 60
    watermark_s: int = 5 * 60

    # -- schema codegen -----------------------------------------------------

    @property
    def event_list_repl(self) -> Tuple[str, ...]:
        return tuple(sanitize_event(e) for e in self.event_list)

    def empty_ind_message(self) -> dict:
        """Economic-indicator message template (ref: config.py:58-65)."""
        msg: dict = {"Timestamp": 0}
        for event in self.event_list_repl:
            msg[event] = {value: 0 for value in EVENT_VALUES}
        return msg

    def deep_columns(self) -> Tuple[str, ...]:
        """Order-book feature columns landed in the warehouse.

        Mirrors the reference DDL order (create_database.py:29-46): sizes for
        all levels, rebased prices for levels 1.. (level-0 rebased prices are
        identically zero and dropped, spark_consumer.py:397-400), then the
        microstructure scalars and calendar one-hots.
        """
        cols = []
        cols += [f"bid_{i}_size" for i in range(self.bid_levels)]
        cols += [f"bid_{i}" for i in range(1, self.bid_levels)]
        cols += [f"ask_{i}_size" for i in range(self.ask_levels)]
        cols += [f"ask_{i}" for i in range(1, self.ask_levels)]
        cols += [
            "bids_ord_WA",
            "asks_ord_WA",
            "vol_imbalance",
            "delta",
            "micro_price",
            "spread",
            "session_start",
            "day_1",
            "day_2",
            "day_3",
            "day_4",
            "week_1",
            "week_2",
            "week_3",
            "week_4",
        ]
        return tuple(cols)

    def vix_columns(self) -> Tuple[str, ...]:
        return ("VIX",) if self.get_vix else ()

    def volume_columns(self) -> Tuple[str, ...]:
        return VOLUME_COLUMNS if self.get_stock_volume else ()

    def cot_columns(self) -> Tuple[str, ...]:
        if not self.get_cot:
            return ()
        return tuple(f"{g}_{v}" for g in COT_GROUPS for v in COT_VALUES)

    def ind_columns(self) -> Tuple[str, ...]:
        return tuple(
            f"{event}_{value}"
            for event in self.event_list_repl
            for value in EVENT_VALUES
        )

    def table_columns(self) -> Tuple[str, ...]:
        """All feature columns of the joined warehouse table, in DDL order
        (create_database.py:69-70), excluding ID and Timestamp."""
        return (
            self.deep_columns()
            + self.vix_columns()
            + self.volume_columns()
            + self.cot_columns()
            + self.ind_columns()
        )

    def derived_columns(self) -> Tuple[str, ...]:
        """Windowed-indicator columns (the reference's SQL views), in the
        order the reference's ``join_statement`` concatenates them
        (create_database.py:240-241: BB, vol_MA, price_MA, delta_MA, stoch,
        ATR, price_change).

        Every OHLC-derived view requires the volume feed; with
        ``get_stock_volume`` disabled only the book-derived ``delta_MA``
        survives (the reference would simply crash building its views
        without the OHLCV columns — here the schema narrows instead).
        """
        has_ohlc = bool(self.get_stock_volume)
        cols = []
        if has_ohlc and self.bollinger_period and self.bollinger_std:
            cols += ["upper_BB_dist", "lower_BB_dist"]
        if has_ohlc:
            cols += [f"vol_MA{p}" for p in self.volume_ma_periods]
            cols += [f"price_MA{p}" for p in self.price_ma_periods]
        cols += [f"delta_MA{p}" for p in self.delta_ma_periods]
        if has_ohlc and self.stochastic_oscillator:
            cols += ["stoch"]
        if has_ohlc:
            cols += ["ATR", "price_change"]
        return tuple(cols)

    @property
    def max_lookback(self) -> int:
        """Longest trailing frame any derived view needs (rows)."""
        frames = [2]  # LAG(close, 1) needs 2 rows
        if self.get_stock_volume:
            if self.bollinger_period and self.bollinger_std:
                frames.append(self.bollinger_period)
            frames.extend(self.volume_ma_periods)
            frames.extend(self.price_ma_periods)
            if self.stochastic_oscillator:
                frames.append(self.stoch_preceding + 1)
            frames.append(self.atr_preceding + 1)
        frames.extend(self.delta_ma_periods)
        return max(frames)

    @property
    def max_lead(self) -> int:
        """Longest LEAD the target view uses (rows)."""
        return max(self.target_lead1, self.target_lead2)

    def x_fields(self) -> Tuple[str, ...]:
        """The model's input-feature schema: table columns followed by derived
        columns — the column set of the reference's ``join_statement``
        (create_database.py:240-258; 108 features with default config)."""
        return self.table_columns() + self.derived_columns()

    @property
    def n_features(self) -> int:
        return len(self.x_fields())


# ---------------------------------------------------------------------------
# Model / training / parallelism
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ModelConfig:
    """BiGRU hyperparameters (ref: biGRU_model.py:32; notebook cell 29).

    ``n_features=None`` means "derive from the feature schema" — resolved by
    :class:`FrameworkConfig` so the model width can never silently diverge
    from what the data pipeline emits.
    """

    hidden_size: int = 32
    n_features: Optional[int] = None
    output_size: int = len(TARGET_COLUMNS)
    n_layers: int = 1
    dropout: float = 0.5
    spatial_dropout: bool = True
    bidirectional: bool = True
    #: Sequence-core family: "gru" (the reference's model), "lstm" (same
    #: head/protocol over fmda_tpu.ops.lstm — the torch user's one-line
    #: nn.GRU -> nn.LSTM swap), "attn" (temporal transformer encoder
    #: over fmda_tpu.ops.attention, the ring-shardable long-context
    #: core), or "ssm" (gated linear recurrence over fmda_tpu.ops.ssm —
    #: trains in the parallel associative-scan mode, serves from a
    #: constant-size O(1) cache with no ring and no per-tick matmul;
    #: docs/runtime.md "The SSM cell family").
    cell: str = "gru"
    #: Attention heads for cell="attn"; must divide hidden_size.
    n_heads: int = 4
    #: Causal (streaming-safe) attention for cell="attn"; the default
    #: mirrors the reference's bidirectional window encoder.
    attn_causal: bool = False
    #: Residual/internal dropout for cell="attn" encoder blocks; None
    #: (the default) falls back to ``dropout``.  Separate knob because
    #: the protocol's dropout=0.5 is the INPUT spatial dropout
    #: (biGRU_model.py:87-94) — the reference's 1-layer GRU core itself
    #: carries no dropout, so 0.5 on every transformer residual
    #: over-regularises the attn family relative to its siblings.  The
    #: family-shootout sweep measured 0.1 as the winner
    #: (RESULTS_FAMILIES.md: test accuracy 0.237 vs 0.193 at 0.5, best
    #: val + backtest edge; 0.0 scores higher on raw test accuracy but
    #: halves the backtest edge) — the shootout/experiment configs set
    #: it explicitly (experiments/family_shootout.py --attn-dropout).
    attn_dropout: Optional[float] = None
    #: cell="ssm": initial per-channel zero-input state-decay range —
    #: each channel's learned decay offset ``a_base`` is initialised so
    #: ``sigmoid(a_base)`` is uniform in this range (the LRU-style
    #: long-memory ring init: channels start spread from "remember ~10
    #: ticks" to "remember ~1000").
    ssm_decay_range: Tuple[float, float] = (0.9, 0.999)
    #: cell="ssm": initial (fast, slow) head-EMA decay rates — the
    #: family's O(1) replacement for the ring head's max/mean window
    #: pools; per-channel and learned from these starting points.  The
    #: default is the shootout sweep's winner (RESULTS_FAMILIES.md: test
    #: accuracy 0.226 vs 0.207 at (0.5, 0.95); the slower fast-EMA
    #: keeps the head's short-horizon pool from tracking tick noise).
    ssm_ema_init: Tuple[float, float] = (0.6, 0.98)
    #: Compute dtype for the GRU/head; params are kept in float32.
    dtype: str = "float32"
    #: Use the fused Pallas scan cell on TPU (falls back to lax.scan
    #: elsewhere).  True means "kernel where it fits": selection is
    #: additionally gated per shape on the kernel's VMEM feasibility
    #: (fmda_tpu.ops.pallas_gru.kernel_supported) — at MXU-wide hidden
    #: sizes the model auto-selects lax.scan, whose per-step matmul is
    #: MXU-shaped there anyway.  Default off: the flagship default path
    #: must be the one exercised everywhere; bench.py and TPU-gated tests
    #: opt in explicitly (ADVICE r1 — flip the default once the kernel
    #: has a TPU CI job).
    use_pallas: bool = False
    #: Rematerialise the recurrence in backward (jax.checkpoint): trades
    #: recompute FLOPs for HBM — enable for long-context windows.
    remat: bool = False


@dataclass(frozen=True)
class TrainConfig:
    """Training-harness hyperparameters (ref: notebook cells 11/29)."""

    batch_size: int = 2
    window: int = 30
    chunk_size: int = 100
    learning_rate: float = 1e-3
    epochs: int = 25
    clip: float = 50.0
    val_size: float = 0.1
    test_size: float = 0.1
    fbeta_beta: float = 0.5
    prob_threshold: float = 0.5
    seed: int = 0
    checkpoint_dir: str = "checkpoints"
    #: Microbatch gradient-accumulation factor K.  The batch is split
    #: into K microbatches scanned into one donated optimizer update —
    #: the same algebra as the full batch (per-microbatch loss *sums*
    #: and mask counts are accumulated and normalized once at the end),
    #: equal up to float32 re-association (docs/training.md).  Must
    #: divide ``batch_size``.  1 = the seed step, bit-identical.
    accum_steps: int = 1
    #: Input-pipeline prefetch depth: how many composed+transferred
    #: batches may be in flight ahead of the device step.  Host window
    #: gather/normalization of chunk k+1 overlaps device compute of
    #: chunk k behind a bounded queue; stalls surface as the
    #: ``train_input_stall_seconds`` histogram.  1 still overlaps by a
    #: single batch; 0 disables the background thread (synchronous).
    prefetch_depth: int = 2
    #: Per-chunk normalized-window cache capacity in chunks (LRU).
    #: Epochs >= 2 reuse the gathered windows instead of re-fetching,
    #: re-normalizing and re-gathering every pass.  Host RAM bound is
    #: ``cache_chunks * chunk_size * window * n_features * 4`` bytes.
    #: 0 disables caching (the seed behavior).
    cache_chunks: int = 64
    #: Continuous fine-tuning (``ContinuousTrainer``): fresh rows that
    #: must land in the warehouse before a fine-tune round fires.
    continuous_min_rows: int = 256
    #: Sliding history window (rows) each round trains over.
    continuous_window_rows: int = 2048
    #: Epochs per fine-tune round (warm-started from the last round).
    continuous_epochs: int = 1
    #: Consecutive empty tail polls before the follow reader concludes
    #: the warehouse has quiesced and the loop drains and exits.
    continuous_follow_polls: int = 8
    #: Wall seconds between empty tail polls (tests inject a waiter
    #: instead — no wall sleeps in tier-1).
    continuous_poll_s: float = 1.0

    def __post_init__(self) -> None:
        if self.accum_steps < 1:
            raise ValueError(
                f"train.accum_steps must be >= 1, got {self.accum_steps}")
        if self.batch_size % self.accum_steps != 0:
            raise ValueError(
                f"train.accum_steps ({self.accum_steps}) must divide "
                f"train.batch_size ({self.batch_size}): microbatches are "
                f"equal fixed-shape slices")
        if self.prefetch_depth < 0 or self.cache_chunks < 0:
            raise ValueError(
                f"train.prefetch_depth/cache_chunks must be >= 0, got "
                f"{self.prefetch_depth}/{self.cache_chunks}")
        if (self.continuous_min_rows < 1 or self.continuous_window_rows < 1
                or self.continuous_epochs < 1
                or self.continuous_follow_polls < 1):
            raise ValueError(
                "train.continuous_min_rows/continuous_window_rows/"
                "continuous_epochs/continuous_follow_polls must be >= 1")
        if self.continuous_poll_s <= 0:
            raise ValueError(
                f"train.continuous_poll_s must be > 0, got "
                f"{self.continuous_poll_s}")


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout for pjit/shard_map parallelism (net-new vs the
    single-machine reference; SURVEY.md §2 parallelism inventory)."""

    #: Data-parallel axis size; -1 means "all remaining devices".
    dp: int = -1
    #: Sequence-parallel axis size (long-context recurrent scan sharding).
    sp: int = 1
    #: Expected process (host/slice) count.  >1 = multi-host: the mesh
    #: spans every process's devices with dp crossing the host boundary
    #: (gradient all-reduce rides DCN between slices, ICI within) and sp
    #: kept inside one host.  Validated against jax.process_count() at
    #: mesh build so a mis-launched job fails loudly, not wrongly.
    processes: int = 1
    dp_axis: str = "dp"
    sp_axis: str = "sp"


@dataclass(frozen=True)
class EngineConfig:
    """Streaming-engine runtime knobs (the role Spark's runtime config
    plays for the reference's consumer)."""

    #: "python" or "native" — the C++ interval-join scheduler
    #: (native/joincore.cpp); falls back to the (bit-identical) python
    #: path with a warning if the toolchain is absent.
    join_backend: str = "python"
    #: Durable-state write cadence in steps (1 = every step; N amortises
    #: over replay churn, idempotent re-landing covers the crash window).
    checkpoint_every: int = 1
    #: Engine state file (offsets + in-flight join state); None disables.
    checkpoint_path: Optional[str] = None
    #: Degraded-mode join deadline (stream-time seconds): a side stream
    #: whose watermark trails the newest book tick by more than this
    #: stops blocking the join — rows emit with the stream's last-known
    #: (or absent) values, counted per topic, and the ``feed_degraded``
    #: health check flips until the feed recovers.  None keeps the
    #: strict inner-join stall.  Keep it below
    #: ``watermark_s + 2*join_tolerance_s`` (660 s at the default
    #: feature config) or waiting ticks can lose their healthy matches
    #: to watermark eviction (a counted drop) before the ghost arrives.
    staleness_deadline_s: Optional[int] = None


#: Fleet-runtime defaults shared by RuntimeConfig and the direct
#: constructors (BatcherConfig, FleetGateway) so bench/test-style direct
#: constructions can't drift from the config defaults.
DEFAULT_BUCKET_SIZES: Tuple[int, ...] = (8, 32, 64, 128)
DEFAULT_MAX_LINGER_S: float = 0.002
DEFAULT_QUEUE_BOUND: int = 1024


@dataclass(frozen=True)
class RuntimeConfig:
    """Fleet-serving runtime knobs (fmda_tpu.runtime; docs/runtime.md).

    Net-new vs the reference (its serving is one hand-run predict.py per
    process) — these size the multi-tenant gateway → micro-batcher →
    session-pool path.
    """

    #: Max concurrent sessions (slots in the pooled state tree).
    capacity: int = 128
    #: Ascending padded micro-batch sizes; each is ONE compiled XLA
    #: program, replayed forever (keep the set small).  64 is in the
    #: default set because it is the documented default fleet size —
    #: without it a 64-session flush pads to 128 and half the batched
    #: step is wasted lanes.
    bucket_sizes: Tuple[int, ...] = DEFAULT_BUCKET_SIZES
    #: Max time (ms) the oldest queued tick may linger before a flush is
    #: forced — the latency half of the batching trade.
    max_linger_ms: float = DEFAULT_MAX_LINGER_S * 1e3
    #: Bound on queued ticks; overload sheds the oldest, counted.
    queue_bound: int = DEFAULT_QUEUE_BOUND
    #: Pooled-head trailing window of the carried streaming state.
    window: int = 30
    #: Flush pipelining in the gateway: 1 = one-deep overlap (flush k's
    #: host transfer + publish run while flush k+1 dispatches — the
    #: default hot path), 0 = strictly serial flushes (the A/B reference;
    #: results are bit-identical either way, tests assert it).
    pipeline_depth: int = 1
    #: Shard the slot axis of the pool's state tree across the dp axis of
    #: the device mesh (config.mesh) so fleet capacity scales with chip
    #: count.  Off by default: on one device the unsharded path is taken
    #: regardless (bit-identical), and multi-chip serving is an explicit
    #: deployment decision.
    shard_pool: bool = False
    #: Latency-SLO gate for `serve-fleet` and the `runtime_fleet_smoke`
    #: bench phase: p99 of the submit→publish ("total") histogram must
    #: stay under this bound (ms) on a quiet host.  None disables the
    #: gate; `--slo-soft` reports the verdict without failing.
    slo_p99_ms: Optional[float] = None

    # -- the batched Predictor path (window-re-scan serving on the fleet
    # runtime: fmda_tpu.runtime.predictor_pool; docs/runtime.md) --------

    #: Padded micro-batch sizes for the batched Predictor's jitted
    #: (B, window, F) forward — one compiled program each.  Smaller set
    #: than the carried-state fleet's: each window forward is
    #: O(window·F) device work, so padding waste is costlier.
    predictor_bucket_sizes: Tuple[int, ...] = (8, 32, 64)
    #: Max time (ms) the oldest queued signal may linger before a flush.
    predictor_max_linger_ms: float = DEFAULT_MAX_LINGER_S * 1e3
    #: Bound on queued signals; overload sheds the oldest, counted.
    predictor_queue_bound: int = DEFAULT_QUEUE_BOUND
    #: Model input window for the batched Predictor; None = `window`.
    predictor_window: Optional[int] = None
    #: Keep a device-resident ring of the stream's newest `window`
    #: feature rows: consecutive signals re-send only the new rows and
    #: the (B, window, F) gather happens on device.  Off by default —
    #: it assumes in-order landing (an out-of-order row's derived-view
    #: recompute would not reach rows already on device).
    predictor_ring: bool = False


@dataclass(frozen=True)
class FleetTopologyConfig:
    """Multi-host serving topology knobs (fmda_tpu.fleet;
    docs/multihost.md).

    Net-new vs the reference and vs the single-process fleet runtime:
    N worker processes each own a contiguous slot-range of the session
    hash space (each embedding the PR-1 FleetGateway/SessionPool), a
    router hashes session → owner and drives membership + migration over
    the cross-process bus (a BusServer-served NativeBus locally, Kafka
    in prod).
    """

    #: Worker-process count the local launcher spawns (`serve-fleet
    #: --role local`); membership itself is dynamic — workers may join
    #: and leave a running router at any time.
    n_workers: int = 2
    #: Worker ids are ``<worker_prefix><index>`` (w0, w1, ...) for the
    #: launcher; hand-started workers may use any id.
    worker_prefix: str = "w"
    #: Bus-server bind address for the local cross-process transport
    #: (the router hosts the bus; workers connect with SocketBus).
    host: str = "127.0.0.1"
    #: 0 = ephemeral (the launcher reads the bound port off the server).
    port: int = 0
    #: Worker heartbeat cadence on the control topic.
    heartbeat_interval_s: float = 0.5
    #: Router declares a worker dead after this long without a
    #: heartbeat (measured on the router's own clock at receipt, so
    #: cross-process clock skew cannot mis-kill a healthy worker).
    #: Deliberately ~20x the interval: a worker mid-drain under a deep
    #: backlog beats late, and a false death costs carried state.
    heartbeat_timeout_s: float = 10.0
    #: Size of the session hash space the ownership table partitions
    #: into contiguous per-worker ranges.
    hash_space: int = 1 << 16
    #: Bound on ticks the router buffers per migrating session while its
    #: state is in flight between owners; overflow sheds the oldest,
    #: counted (``migration_buffer_shed``) — same never-silent contract
    #: as the gateway queue.
    migration_buffer_bound: int = 4096
    #: Max inbox records a worker consumes per step (bounds one socket
    #: read's frame size; the backlog simply spans more steps).
    worker_poll_max_records: int = 512
    #: Router backpressure bound: once this many routed ticks are
    #: unanswered, ``saturated`` turns on and well-behaved producers
    #: pace themselves — otherwise an unbounded inbox backlog outruns
    #: the bus's retention and ticks silently age off the topic.
    max_inflight_ticks: int = 4096
    #: Age (router clock) after which an unanswered tick is declared
    #: lost (``results_missing``) — e.g. it rode into a worker that
    #: died undrained.
    result_timeout_s: float = 60.0
    #: Byte arena per topic for the router-hosted NativeBus — sized for
    #: deep tick backlogs (a ~700B tick message × max_inflight_ticks ×
    #: workers fits with wide margin).
    bus_arena_bytes: int = 1 << 26
    #: How long a shared-bus worker retries a dead broker before exiting
    #: cleanly (counted, rc 0 — the never-abort contract).  A
    #: worker-hosted-bus worker never exits on control loss: its data
    #: plane is local, so it keeps serving and re-dials instead.
    bus_error_grace_s: float = 10.0
    #: Control-plane re-dial cadence while the router/broker is
    #: unreachable (split topology; reconnect re-hellos with the session
    #: report, which is how a restarted router adopts the sessions).
    control_retry_s: float = 1.0
    #: Frame encoding on every SocketBus link (docs/multihost.md "Wire
    #: format v2"): ``auto`` negotiates the binary codec at connect and
    #: falls back to JSON against a peer that does not speak it (mixed-
    #: version fleets interoperate); ``binary`` insists (still falls
    #: back, loudly); ``json`` pins the pre-v2 text frames — the
    #: rollback switch.
    wire_format: str = "auto"


@dataclass(frozen=True)
class ObservabilityConfig:
    """Observability-plane knobs (fmda_tpu.obs; docs/observability.md).

    Net-new vs the reference (its only "telemetry" is print statements):
    one process-wide metrics registry + JSONL event ring, with an
    optional Prometheus scrape endpoint.
    """

    #: Switch for the app's plane: False hands out no-op instruments to
    #: the engine/bus/warehouse, registers no collectors, and starts no
    #: endpoint — those hot paths keep only one attribute call.
    #: Module-level instrumentation with no Application handle (ingest
    #: transports, trainer step timings) reports to the process-default
    #: registry regardless; its cost is one lock-guarded update per
    #: event, measured inside the noise floor (bench obs_overhead).
    enabled: bool = True
    #: Serve ``/metrics``+``/healthz``+``/snapshot`` over HTTP.  Off by
    #: default so tests and one-shot CLI runs never bind a port; daemons
    #: opt in (or pass ``serve-fleet --metrics-port``).
    endpoint_enabled: bool = False
    host: str = "127.0.0.1"
    #: 0 = ephemeral (the bound port is logged and on the handle).
    port: int = 9100
    #: Bounded event-ring capacity (oldest events fall off).
    events_capacity: int = 2048
    #: Mirror events to this JSONL file; None = ring only.
    events_path: Optional[str] = None
    #: ``/healthz`` turns degraded when the newest completed app tick is
    #: older than this (startup grace: healthy until the first tick).
    max_tick_age_s: float = 900.0


@dataclass(frozen=True)
class SLOConfig:
    """Fleet service-level objectives + telemetry knobs (fmda_tpu.obs:
    tsdb/aggregate/slo/recorder; docs/observability.md "Fleet
    aggregation, SLOs, and the flight recorder").

    Declarative objectives evaluated as **multi-window burn rates**: an
    alert fires when both the fast (~5 m) and slow (~1 h) windows burn
    error budget faster than ``burn_threshold``, and clears as soon as
    the fast window recovers.  Evaluation is pull-based — one fold of
    heartbeat stats + scrape snapshots per ``interval_s``, never on the
    tick hot path.
    """

    #: Master switch for router-side fleet telemetry (the store, the
    #: aggregator, SLO evaluation, and the flight recorder).
    enabled: bool = True
    #: Time-series sample grid + SLO evaluation cadence (seconds).
    interval_s: float = 5.0
    #: History the store retains per series (ring capacity =
    #: retention_s / interval_s bins).
    retention_s: float = 7200.0
    #: Cadence for scraping worker ``/snapshot`` endpoints (announced
    #: in heartbeats); heartbeat stats fold in every ``interval_s``.
    scrape_interval_s: float = 10.0
    #: Burn-rate windows (seconds): fast trips quickly on a cliff,
    #: slow keeps a brief blip from paging.
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    #: Burn rate (budget consumption multiple) at which an alert fires.
    burn_threshold: float = 2.0
    #: Latency objective: at most ``latency_budget`` of served ticks may
    #: exceed ``latency_p99_ms`` end to end.  None disables.
    latency_p99_ms: Optional[float] = 250.0
    latency_budget: float = 0.05
    #: Loss objective: counted losses / (served + lost) stays under this.
    loss_budget: float = 0.001
    #: Journal objective: warehouse journal backlog above this depth is
    #: budget burn (``journal_budget`` of samples may exceed it).
    journal_depth: int = 1024
    journal_budget: float = 0.1
    #: Degraded-feed objective: minutes per slow window any side feed
    #: may serve ghost rows before the alert fires.
    degraded_feed_budget_minutes: float = 5.0
    #: Recompile objective: unexpected XLA recompiles after warmup are
    #: judged as a raw count per window — a budget below 1 means a
    #: single recompile burns past ``burn_threshold`` (zero is the
    #: steady-state contract; fmda_tpu.obs.device).
    recompile_budget: float = 0.5
    #: Memory-leak objective: fraction of samples the device memory
    #: monitor's monotonic-growth heuristic may be raised.
    memory_leak_budget: float = 0.05
    #: Quality objectives (fmda_tpu.obs.quality's label-join evaluator
    #: writes the series; None-until-reported — a fleet without the
    #: quality plane never fires these).  Accuracy: exact-match misses
    #: over joined predictions stay under this fraction.
    quality_accuracy_budget: float = 0.35
    #: Per-label F-beta floor: fraction of sampled intervals where ANY
    #: (version, label) F-beta gauge sits below ``quality_fbeta_floor``.
    quality_fbeta_floor: float = 0.05
    quality_fbeta_budget: float = 0.25
    #: Drift: fraction of sampled intervals where the worst PSI
    #: (feature or prediction) exceeds ``quality_drift_psi`` (0.25 is
    #: the classic "action required" PSI threshold).
    quality_drift_psi: float = 0.25
    quality_drift_budget: float = 0.1
    #: Flight-recorder bundle directory; None disables postmortems.
    postmortem_dir: Optional[str] = None
    #: Rotated bundle count (oldest deleted past this).
    postmortem_keep: int = 4
    #: Debounce between bundles for one trigger reason (seconds).
    postmortem_min_interval_s: float = 60.0


@dataclass(frozen=True)
class QualityConfig:
    """Online model-quality plane knobs (fmda_tpu.obs.quality;
    docs/observability.md "Model quality").

    The label-join evaluator captures published predictions into a
    bounded ring and joins them — on a cadence, off the tick path —
    against warehouse targets once enough future rows have landed
    (``FeatureConfig.max_lead`` rows after a prediction's own row).
    Streaming subset-accuracy / Hamming / per-label F-beta accumulate
    per ``weights_version``; a PSI drift monitor scores live features
    and predictions against the training-time reference profile saved
    beside the checkpoint (``quality_profile.json``).
    """

    #: Master switch for the quality plane (capture + join + drift).
    enabled: bool = True
    #: Capture-ring capacity; overflow evicts the oldest prediction as
    #: a counted ``quality_captures_shed`` loss, never unbounded.
    capture_capacity: int = 4096
    #: Label-join cadence (seconds; virtual seconds under replay).
    join_interval_s: float = 5.0
    #: Probability threshold for label decisions (predictions arrive as
    #: probabilities — sigmoid already applied by the serving pool).
    prob_threshold: float = 0.5
    #: F-beta beta (0.5 = precision-weighted, the trainer's choice).
    fbeta: float = 0.5
    #: A capture still unjoinable after this many consecutive join
    #: rounds (row shed, session gone, beyond retention) ages out as a
    #: counted ``quality_join_expired`` loss — round-counted, so replay
    #: runs expire deterministically with no wall clock involved.
    max_join_attempts: int = 8
    #: Reference-profile quantile bins (built at train time).
    drift_bins: int = 10
    #: Drift scores stay None (never reported) below this many observed
    #: rows — PSI over a handful of rows is noise, not signal.
    drift_min_samples: int = 64
    #: Reference-profile path; None = ``quality_profile.json`` beside
    #: the checkpoint in use.
    profile_path: Optional[str] = None
    #: Hot-swap guardrail (fmda_tpu.eval.shadow): a candidate may score
    #: at most this much *below* the incumbent's shadow accuracy.
    swap_margin: float = 0.02
    #: Shadow-scoring replay size: rounds x sessions of recent
    #: warehoused history per side.
    swap_eval_rounds: int = 48
    swap_eval_sessions: int = 4

    def __post_init__(self) -> None:
        if self.capture_capacity < 1:
            raise ValueError(
                f"capture_capacity must be >= 1, got {self.capture_capacity}")
        if self.join_interval_s <= 0:
            raise ValueError(
                f"join_interval_s must be > 0, got {self.join_interval_s}")
        if not 0.0 < self.prob_threshold < 1.0:
            raise ValueError(
                f"prob_threshold must be in (0, 1), got "
                f"{self.prob_threshold}")
        if self.max_join_attempts < 1:
            raise ValueError(
                f"max_join_attempts must be >= 1, got "
                f"{self.max_join_attempts}")
        if self.drift_bins < 2:
            raise ValueError(
                f"drift_bins must be >= 2, got {self.drift_bins}")
        if self.swap_margin < 0:
            raise ValueError(
                f"swap_margin must be >= 0, got {self.swap_margin}")
        if self.swap_eval_rounds < 1 or self.swap_eval_sessions < 1:
            raise ValueError(
                "swap_eval_rounds and swap_eval_sessions must be >= 1, "
                f"got {self.swap_eval_rounds} x {self.swap_eval_sessions}")


@dataclass(frozen=True)
class TracingConfig:
    """End-to-end tick tracing knobs (fmda_tpu.obs.trace;
    docs/observability.md "Tracing a tick").

    Off by default: disabled tracing costs one branch on every hot path
    (submit, flush, bus publish, engine step).  Enabled tracing records
    spans into a bounded in-memory ring, exported as Chrome/Perfetto
    trace_event JSON (``/trace``, ``python -m fmda_tpu trace``,
    ``serve-fleet --trace-out``).
    """

    #: Master switch for the process tracer.
    enabled: bool = False
    #: Fraction of trace roots sampled in [0, 1].  1.0 traces every tick
    #: (forensics runs); production fleets run ~0.01 — the
    #: ``trace_overhead`` bench phase holds 1% sampling under the same
    #: <2% hot-loop budget as the metrics plane.
    sample_rate: float = 1.0
    #: Span-ring capacity; overflow evicts the oldest spans, so a
    #: long-running daemon keeps the newest traces and bounded memory.
    max_spans: int = 16384


@dataclass(frozen=True)
class ProfilingConfig:
    """Device & compiler observability knobs (fmda_tpu.obs.device /
    fmda_tpu.obs.pyprof; docs/observability.md "Device & compiler
    telemetry").

    The compile ledger itself is on by default everywhere — a tracked
    jit call with the ledger enabled costs two cache-size reads and one
    short lock window (``device_obs_overhead`` gates the whole plane
    under 2% of the fleet hot loop).  ``cost_analysis`` re-lowers each
    program once per compile to read FLOPs/bytes, so it is a
    *deployment* default (serving hosts want MFU; unit tests do not
    want doubled compile time — the module-level default is off and
    ``configure_device_obs`` applies this section at serve time).
    """

    #: Master switch for the ledger + memory monitor.
    enabled: bool = True
    #: Probe ``.lower().compile().cost_analysis()`` per compile (via
    #: fmda_tpu.compat) for per-program FLOPs / bytes-accessed → MFU.
    cost_analysis: bool = True
    #: Run the continuous host sampling profiler (``/profile``,
    #: flight-recorder ``profile.folded``).
    host_profiler: bool = False
    #: Host-profiler sampling period (milliseconds).
    profile_interval_ms: float = 10.0
    #: Bounded distinct-stack table; overflow folds into ``<other>``.
    profile_max_stacks: int = 4096
    #: Device memory sampling cadence (seconds).
    memory_interval_s: float = 5.0
    #: Consecutive strictly-growing samples before the leak heuristic
    #: raises ``device_memory_leak_suspected``.
    memory_leak_window: int = 12


@dataclass(frozen=True)
class ChaosConfig:
    """Fault-injection knobs (fmda_tpu.chaos; docs/chaos.md).

    Off by default: with ``enabled=False`` nothing is injected and every
    compiled-in injection point costs exactly one branch (the tier-1 AST
    check pins this).  The rate knobs parameterise
    :meth:`~fmda_tpu.chaos.plan.FaultPlan.generate` when no explicit
    ``--chaos-plan`` file is given — the plan is a pure function of
    ``seed`` and these counts, so a run is its own reproduction recipe.
    """

    #: Master switch for the process chaos runtime.
    enabled: bool = False
    #: Seed the generated fault plan derives from.
    seed: int = 0
    #: Worker processes killed (and revived ``revive_after`` steps
    #: later) per soak.
    worker_kills: int = 1
    #: Virtual steps a killed worker stays down before its replacement
    #: spawns.
    revive_after: int = 8
    #: Router kill/takeover events per soak (each exercises the
    #: registry-rebuild failover path).
    router_restarts: int = 1
    #: Router→worker data-link partition windows per soak.
    link_partitions: int = 1
    #: Control-bus outage windows per soak (the router keeps pumping its
    #: links while its own bus is down — counted, never fatal).
    bus_blips: int = 1
    #: Injected per-op delay events per soak.
    delays: int = 2
    #: Sleep per delayed op (seconds).
    delay_s: float = 0.02
    #: Fault-free steps at both ends of the schedule: a clean warm-up,
    #: and the post-chaos window the "ticks served after the last
    #: fault" gate measures in.
    settle_steps: int = 5

    # -- data-plane soak knobs (fmda_tpu.chaos.pipeline; the fleet soak
    # above ignores these) ---------------------------------------------

    #: Side-feed outage windows per pipeline soak (degraded-mode joins).
    feed_outages: int = 1
    #: Virtual steps a feed stays down.
    feed_outage_steps: int = 8
    #: Warehouse-unreachable windows per pipeline soak (journal spill).
    warehouse_outages: int = 1
    #: Virtual steps the warehouse stays down.
    warehouse_outage_steps: int = 4
    #: Engine kill/restore cycles per pipeline soak.
    engine_kills: int = 1
    #: Virtual steps the engine stays dead before its restore.
    engine_kill_steps: int = 2


@dataclass(frozen=True)
class ControlConfig:
    """Adaptive control plane knobs (fmda_tpu.control; docs/control.md).

    Three closed loops run beside the router, all reading the telemetry
    plane (``[slo]``'s windowed p99 / burn rates) and writing decisions
    to the EventLog: the **batching controller** (tunes gateway linger
    and bucket cap against the latency objective), **per-tenant QoS**
    (weighted admission + counted per-class shedding in front of the
    gateway queue), and the **elastic autoscaler** (spawns workers on
    sustained burn, retires them through the zero-loss drain/export/
    replay migration on sustained idle).  ``enabled=False`` removes
    every loop: the serving path is exactly the static fleet.
    """

    #: Master switch for the control plane (``serve-fleet
    #: --no-controller`` overrides per run for A/B).
    enabled: bool = True
    #: Decision cadence (seconds between control evaluations).
    interval_s: float = 1.0
    #: Last-N decision ring surfaced by ``/control`` and ``status``.
    decisions_keep: int = 64

    # -- batching controller --------------------------------------------
    #: Enable the linger/bucket feedback loop.
    batching: bool = True
    #: p99 target (ms) the loop steers toward; None derives it from
    #: ``slo.latency_p99_ms``.
    target_p99_ms: Optional[float] = None
    #: Hysteresis deadband as a fraction of target: no move while p99
    #: sits inside [(1-h)·target, (1+h)·target].
    hysteresis: float = 0.25
    #: Bounded step per decision (ms of linger) — the loop never jumps.
    linger_step_ms: float = 0.25
    #: Linger clamp (ms).  The controller explores inside these walls.
    min_linger_ms: float = 0.0
    max_linger_ms: float = 8.0

    # -- per-tenant QoS -------------------------------------------------
    #: Priority classes, highest first.  Parallel tuples: ``weights``
    #: set each class's fair share of the gateway queue (WFQ), and
    #: ``quota_frac`` caps each class's queued ticks at that fraction
    #: of ``runtime.queue_bound`` (over-quota submits shed the class's
    #: OWN oldest tick, counted ``quota_shed``).  Empty = QoS off
    #: (global oldest-drop, exactly the pre-control gateway).
    tenant_classes: Tuple[str, ...] = ()
    tenant_weights: Tuple[float, ...] = ()
    tenant_quota_frac: Tuple[float, ...] = ()
    #: Class assigned to sessions opened without a tenant label.
    default_class: str = "standard"

    # -- elastic autoscaler ---------------------------------------------
    #: Enable the worker-count loop (needs a spawn-capable actuator —
    #: the local launcher topology; a bare router run leaves it off).
    autoscale: bool = True
    min_workers: int = 1
    max_workers: int = 8
    #: Scale up when the latency objective's fast burn rate holds at or
    #: above this for ``up_sustain_s`` seconds.
    scale_up_burn: float = 1.0
    up_sustain_s: float = 3.0
    #: Scale down when p99 holds below ``scale_down_frac``·target (and
    #: no burn) for ``down_sustain_s`` seconds.
    scale_down_frac: float = 0.3
    down_sustain_s: float = 10.0
    #: Minimum seconds between scaling moves (either direction).
    cooldown_s: float = 5.0

    def __post_init__(self) -> None:
        n = len(self.tenant_classes)
        if len(self.tenant_weights) != n or len(self.tenant_quota_frac) != n:
            raise ValueError(
                "tenant_classes/tenant_weights/tenant_quota_frac must be "
                f"parallel tuples, got lengths {n}/"
                f"{len(self.tenant_weights)}/{len(self.tenant_quota_frac)}")
        if any(w <= 0 for w in self.tenant_weights):
            raise ValueError("tenant_weights must be positive")
        if self.min_workers < 1 or self.max_workers < self.min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{self.min_workers}/{self.max_workers}")
        if self.min_linger_ms < 0 or self.max_linger_ms < self.min_linger_ms:
            raise ValueError(
                f"need 0 <= min_linger_ms <= max_linger_ms, got "
                f"{self.min_linger_ms}/{self.max_linger_ms}")


@dataclass(frozen=True)
class ReplayConfig:
    """Historical-replay knobs (fmda_tpu.replay; docs/replay.md).

    A replay run backfills history through the **unmodified** serving
    path at max speed on a virtual clock (the rows' own timestamps —
    never the host clock; the ``virtual-clock`` lint rule pins that).
    These knobs pick the history source and bound the run; the serving
    side needs nothing — replay sessions are ordinary gateway sessions.
    """

    #: History source: ``"synthetic"`` (seeded generator — bit-identical
    #: re-iteration, no warehouse needed) or ``"warehouse"`` (bulk
    #: chunked reads via ``Warehouse.iter_row_chunks``).
    source: str = "synthetic"
    #: Tickers (= replay sessions) the backfill drives.
    n_tickers: int = 8
    #: Rounds served when ``source="synthetic"``.
    n_rounds: int = 256
    #: Seed for the synthetic generator and tenant assignment.
    seed: int = 0
    #: Fraction of tickers active per synthetic round (1.0 = lockstep,
    #: the composition the bit-identity gate requires).
    duty: float = 1.0
    #: Virtual seconds between synthetic rounds (the virtual clock's
    #: step; also the implied live cadence replay deletes).
    step_s: float = 60.0
    #: Warehouse row-range bounds (timestamp strings; None = unbounded)
    #: when ``source="warehouse"``.
    start_ts: Optional[str] = None
    end_ts: Optional[str] = None
    #: Rows per keyset-paginated warehouse read.
    chunk: int = 4096
    #: Wire dialect blocks round-trip through before serving: None
    #: (in-process), ``"binary"`` or ``"json"`` — identity must hold on
    #: all three (solo gateways only; a fleet router encodes per link).
    wire_dialect: Optional[str] = None

    def __post_init__(self) -> None:
        if self.source not in ("synthetic", "warehouse"):
            raise ValueError(
                f"replay.source must be 'synthetic' or 'warehouse', "
                f"got {self.source!r}")
        if self.wire_dialect not in (None, "binary", "json"):
            raise ValueError(
                f"replay.wire_dialect must be null, 'binary' or 'json', "
                f"got {self.wire_dialect!r}")
        if self.n_tickers < 1 or self.n_rounds < 1 or self.chunk < 1:
            raise ValueError(
                f"replay.n_tickers/n_rounds/chunk must be >= 1, got "
                f"{self.n_tickers}/{self.n_rounds}/{self.chunk}")
        if not 0.0 < self.duty <= 1.0:
            raise ValueError(
                f"replay.duty must be in (0, 1], got {self.duty}")


@dataclass(frozen=True)
class SessionConfig:
    """Ingestion-session driver knobs (ref: producer.py:257-263)."""

    freq_s: int = 300
    source: str = "IEX"
    symbol: str = "spy"
    countries: Tuple[str, ...] = ("United States",)
    importance: Tuple[str, ...] = ("1", "2", "3")
    cot_subject: str = "S&P 500 STOCK INDEX"
    timezone: str = "US/Eastern"


@dataclass(frozen=True)
class FrameworkConfig:
    """Top-level aggregate configuration."""

    features: FeatureConfig = field(default_factory=FeatureConfig)
    bus: BusConfig = field(default_factory=BusConfig)
    warehouse: WarehouseConfig = field(default_factory=WarehouseConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    session: SessionConfig = field(default_factory=SessionConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    fleet: FleetTopologyConfig = field(default_factory=FleetTopologyConfig)
    observability: ObservabilityConfig = field(
        default_factory=ObservabilityConfig)
    slo: SLOConfig = field(default_factory=SLOConfig)
    quality: QualityConfig = field(default_factory=QualityConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    profiling: ProfilingConfig = field(default_factory=ProfilingConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    control: ControlConfig = field(default_factory=ControlConfig)
    replay: ReplayConfig = field(default_factory=ReplayConfig)

    def __post_init__(self) -> None:
        if self.model.n_features is None:
            synced = dataclasses.replace(
                self.model, n_features=self.features.n_features
            )
            object.__setattr__(self, "model", synced)


def default_config() -> FrameworkConfig:
    return FrameworkConfig()


# ---------------------------------------------------------------------------
# Serialization: the whole config tree round-trips through JSON, so a
# deployment is one reviewable file (the reference's "edit config.py and the
# pipeline reshapes" property, config.py:31-65, without code edits).
# ---------------------------------------------------------------------------

_SECTIONS = {
    "features": FeatureConfig,
    "bus": BusConfig,
    "warehouse": WarehouseConfig,
    "engine": EngineConfig,
    "model": ModelConfig,
    "train": TrainConfig,
    "mesh": MeshConfig,
    "session": SessionConfig,
    "runtime": RuntimeConfig,
    "fleet": FleetTopologyConfig,
    "observability": ObservabilityConfig,
    "slo": SLOConfig,
    "quality": QualityConfig,
    "tracing": TracingConfig,
    "profiling": ProfilingConfig,
    "chaos": ChaosConfig,
    "control": ControlConfig,
    "replay": ReplayConfig,
}


def config_to_dict(cfg: FrameworkConfig) -> dict:
    """Nested plain-dict form (tuples become lists; JSON-ready).

    ``model.n_features`` is written as null: it is state *derived* from
    the feature schema (resolved by ``FrameworkConfig.__post_init__``),
    and persisting the resolved value would freeze it while an edited
    features section reshapes everything else."""
    d = dataclasses.asdict(cfg)
    d["model"]["n_features"] = None
    return d


def config_from_dict(data: dict) -> FrameworkConfig:
    """Rebuild a FrameworkConfig from (possibly partial) nested dicts.

    Unknown sections or keys raise — a typo'd config must fail loudly, not
    silently fall back to defaults.  JSON lists are coerced back to the
    tuples the frozen dataclasses expect.
    """
    sections = _SECTIONS
    unknown = set(data) - set(sections)
    if unknown:
        raise ValueError(f"unknown config sections: {sorted(unknown)}")
    kwargs = {}
    for name, cls in sections.items():
        if name not in data:
            continue
        section = data[name]
        field_names = {f.name for f in dataclasses.fields(cls)}
        bad = set(section) - field_names
        if bad:
            raise ValueError(f"unknown keys in [{name}]: {sorted(bad)}")
        coerced = {
            k: tuple(v) if isinstance(v, list) else v
            for k, v in section.items()
        }
        kwargs[name] = cls(**coerced)
    return FrameworkConfig(**kwargs)


def save_config(cfg: FrameworkConfig, path: str) -> str:
    import json

    with open(path, "w") as fh:
        json.dump(config_to_dict(cfg), fh, indent=2)
        fh.write("\n")
    return path


def load_config(path: str) -> FrameworkConfig:
    import json

    with open(path) as fh:
        return config_from_dict(json.load(fh))
