"""Application: the framework's single composition root.

The reference has no entry point — five scripts started by hand in the
right order against externally administered Kafka/Spark/MariaDB processes
(README.md:186-292).  :class:`Application` builds the whole stack from one
:class:`~fmda_tpu.config.FrameworkConfig`:

    app = Application(FrameworkConfig())
    app.attach_session(iex=..., alpha_vantage=..., calendar=...)  # L1
    app.run_ticks(...)            # acquire -> join -> land -> signal
    state, history, ds = app.train()                              # L5 train
    app.attach_predictor_from_checkpoint(ckpt, window=30)         # L5 serve

Backends are swappable: the bus defaults to the native C++ ring buffer
(falls back to the Python bus without a toolchain), the warehouse to
embedded SQLite; Kafka/MariaDB adapters slot in for deployment parity.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from fmda_tpu.config import FrameworkConfig
from fmda_tpu.stream.bus import InProcessBus, MessageBus
from fmda_tpu.stream.engine import StreamEngine
from fmda_tpu.stream.warehouse import Warehouse

log = logging.getLogger("fmda_tpu")


def default_bus(config: FrameworkConfig) -> MessageBus:
    """Native C++ ring-buffer bus when buildable, Python bus otherwise."""
    try:
        from fmda_tpu.stream.native_bus import NativeBus, native_available

        if native_available():
            return NativeBus(
                config.bus.topics, max_records=config.bus.capacity
            )
    except Exception as e:  # noqa: BLE001 — fall back, never fail startup
        log.warning("native bus unavailable (%s); using InProcessBus", e)
    return InProcessBus(config.bus.topics, capacity=config.bus.capacity)


class Application:
    """Composition root wiring bus + warehouse + engine (+ session/serving)."""

    def __init__(
        self,
        config: Optional[FrameworkConfig] = None,
        *,
        bus: Optional[MessageBus] = None,
        warehouse: Optional[Warehouse] = None,
        engine_checkpoint: Optional[str] = None,
    ) -> None:
        from fmda_tpu.obs import Observability

        self.config = config or FrameworkConfig()
        tc = self.config.tracing
        if tc.enabled:
            # the process-default tracer is a singleton mutated in place,
            # so components that captured it at import stay live; an app
            # config never *disables* a tracer another component enabled
            from fmda_tpu.obs.trace import configure_tracing

            configure_tracing(
                enabled=True, sample_rate=tc.sample_rate,
                capacity=tc.max_spans)
        #: The app's observability plane (fmda_tpu.obs): metrics registry,
        #: event log, health checks, optional scrape endpoint.  Feeds
        #: :attr:`stats` / :attr:`stage_timings` and docs/observability.md.
        self.observability = Observability(self.config.observability)
        reg = self.observability.registry
        self.bus = bus if bus is not None else default_bus(self.config)
        self.warehouse = (
            warehouse
            if warehouse is not None
            else Warehouse(self.config.features, self.config.warehouse)
        )
        wc = self.config.warehouse
        if wc.journal_path and warehouse is None:
            # warehouse-outage survival: failed landings spill to a
            # durable journal and backfill on recovery (an injected
            # warehouse keeps its own durability story)
            from fmda_tpu.stream.journal import BufferedWarehouse

            self.warehouse = BufferedWarehouse(
                self.warehouse, wc.journal_path, bound=wc.journal_bound,
                fmt=wc.journal_format)
        ec = self.config.engine
        self.engine = StreamEngine(
            self.bus,
            self.warehouse,
            self.config.features,
            checkpoint_path=(
                engine_checkpoint if engine_checkpoint is not None
                else ec.checkpoint_path
            ),
            checkpoint_every=ec.checkpoint_every,
            join_backend=ec.join_backend,
            staleness_deadline_s=ec.staleness_deadline_s,
            metrics=reg if reg.enabled else None,
        )
        self.session = None
        self.predictors: List = []
        self.fleet = None
        self.observability.track_app(self)
        if self.config.observability.endpoint_enabled:
            self.observability.start_server()

    # -- L1: acquisition ------------------------------------------------------

    def attach_session(self, **clients) -> "SessionDriver":
        """Create the ingestion session driver; keyword args are the client
        objects accepted by :class:`~fmda_tpu.ingest.session.SessionDriver`
        (iex, alpha_vantage, calendar, indicator_scraper, vix_scraper,
        cot_scraper, now_fn, sleep_fn)."""
        from fmda_tpu.ingest.session import SessionDriver

        self.session = SessionDriver(self.bus, self.config.session, **clients)
        return self.session

    # -- L5: serving ----------------------------------------------------------

    def attach_predictor_from_checkpoint(
        self, checkpoint_path: str, *, window: int, **kwargs
    ):
        """Window-re-scan predictor bound to this app's bus + warehouse."""
        from fmda_tpu.serve.predictor import Predictor

        predictor = Predictor.from_checkpoint(
            checkpoint_path,
            self.bus,
            self.warehouse,
            self.config.model,
            window=window,
            **kwargs,
        )
        self.predictors.append(predictor)
        return predictor

    def attach_predictor_fleet(
        self, model_cfg, params, norm_params, **gateway_kwargs
    ):
        """Batched window-re-scan serving (fmda_tpu.runtime
        .predictor_pool) on this app's bus + warehouse, sized by the
        ``config.runtime`` ``predictor_*`` knobs: predict-timestamp
        signals coalesce into bucketed ``(B, window, F)`` jitted
        forwards — the Predictor path as a fleet citizen.  The gateway
        joins :attr:`predictors`, so :meth:`run_tick` polls it exactly
        like a solo predictor."""
        from fmda_tpu.runtime import (
            BatcherConfig, PredictorGateway, PredictorPool,
        )

        rc = self.config.runtime
        window = (rc.predictor_window if rc.predictor_window is not None
                  else rc.window)
        pool = PredictorPool(
            model_cfg, params, norm_params, window=window,
            use_ring=rc.predictor_ring)
        gateway_kwargs.setdefault(
            "batcher_config",
            BatcherConfig(bucket_sizes=tuple(rc.predictor_bucket_sizes),
                          max_linger_s=rc.predictor_max_linger_ms / 1e3))
        gateway_kwargs.setdefault("queue_bound", rc.predictor_queue_bound)
        gateway_kwargs.setdefault("pipeline_depth", rc.pipeline_depth)
        gateway_kwargs.setdefault(
            "threshold", self.config.train.prob_threshold)
        gateway = PredictorGateway(
            pool, self.bus, self.warehouse, **gateway_kwargs)
        self.predictors.append(gateway)
        self.observability.track_predictor_fleet(gateway)
        return gateway

    def attach_predictor_fleet_from_checkpoint(
        self, checkpoint_path: str, model_cfg=None, **gateway_kwargs
    ):
        """:meth:`attach_predictor_fleet` from a training checkpoint
        (params + norm stats in one tree, like the solo
        :meth:`attach_predictor_from_checkpoint`)."""
        from fmda_tpu.train.checkpoint import restore_checkpoint

        tree, norm = restore_checkpoint(checkpoint_path)
        if norm is None:
            raise ValueError(
                f"checkpoint {checkpoint_path} has no normalization stats")
        return self.attach_predictor_fleet(
            model_cfg if model_cfg is not None else self.config.model,
            tree["params"], norm, **gateway_kwargs)

    def attach_streaming_predictor(self, core, **kwargs):
        """Carried-state predictor: O(1)/tick with a StreamingBiGRU core
        (unidirectional), O(window)/tick with the bidirectional core."""
        from fmda_tpu.serve.streaming import StreamingPredictor

        predictor = StreamingPredictor(self.bus, self.warehouse, core, **kwargs)
        self.predictors.append(predictor)
        return predictor

    def attach_fleet(self, model_cfg, params, **gateway_kwargs):
        """Multi-tenant serving runtime (fmda_tpu.runtime) on this app's
        bus, sized by ``config.runtime``: slot pool + deadline-aware
        micro-batcher + admission-controlled gateway.  ``model_cfg`` must
        be a unidirectional recurrent config (the batched carried-state
        kernels); kwargs override the gateway's defaults."""
        from fmda_tpu.runtime import BatcherConfig, FleetGateway, SessionPool

        rc = self.config.runtime
        mesh = None
        if rc.shard_pool:
            # slot axis sharded over the dp axis of the configured mesh;
            # a 1-device mesh degrades to the (bit-identical) unsharded
            # pool inside SessionPool
            from fmda_tpu.parallel.mesh import build_mesh

            mesh = build_mesh(self.config.mesh)
        pool = SessionPool(
            model_cfg, params, capacity=rc.capacity, window=rc.window,
            mesh=mesh, shard_axis=self.config.mesh.dp_axis)
        gateway_kwargs.setdefault(
            "batcher_config",
            BatcherConfig(bucket_sizes=tuple(rc.bucket_sizes),
                          max_linger_s=rc.max_linger_ms / 1e3))
        gateway_kwargs.setdefault("queue_bound", rc.queue_bound)
        gateway_kwargs.setdefault("pipeline_depth", rc.pipeline_depth)
        # same decision threshold as the solo serving paths (cmd_serve
        # wires train.prob_threshold into Predictor/StreamingPredictor)
        gateway_kwargs.setdefault(
            "threshold", self.config.train.prob_threshold)
        self.fleet = FleetGateway(pool, self.bus, **gateway_kwargs)
        self.observability.track_fleet(self.fleet)
        return self.fleet

    # -- the loop -------------------------------------------------------------

    def run_tick(self) -> Dict[str, int]:
        """One full cycle: acquire (if a session is attached) -> engine
        micro-batch -> serve all attached predictors."""
        if self.session is not None:
            self.session.run_tick()
        emitted = self.engine.step()
        served = 0
        for predictor in self.predictors:
            served += len(predictor.poll())
        self.observability.tick()
        return {"emitted": emitted, "served": served}

    def run_ticks(self, n: int) -> Dict[str, int]:
        totals = {"emitted": 0, "served": 0}
        for _ in range(n):
            out = self.run_tick()
            totals["emitted"] += out["emitted"]
            totals["served"] += out["served"]
        return totals

    # -- L5: training ---------------------------------------------------------

    def train(self, *, weight=None, pos_weight=None, mesh=None, **fit_kwargs):
        """Train the configured model on this app's warehouse."""
        from fmda_tpu.train.trainer import Trainer, imbalance_weights_from_source

        if weight is None and pos_weight is None:
            weight, pos_weight = imbalance_weights_from_source(self.warehouse)
        trainer = Trainer(
            self.config.model,
            self.config.train,
            weight=weight,
            pos_weight=pos_weight,
            mesh=mesh,
        )
        return trainer.fit(
            self.warehouse,
            bid_levels=self.config.features.bid_levels,
            ask_levels=self.config.features.ask_levels,
            **fit_kwargs,
        )

    def run_forever(
        self,
        *,
        interval_s: float = 1.0,
        max_restarts: int = 5,
        sleep_fn=None,
        should_stop=None,
    ) -> None:
        """Supervised serving loop: tick, sleep, repeat.

        A crashing tick is logged and retried with exponential backoff up to
        ``max_restarts`` consecutive failures (then re-raised) — the
        elastic-recovery story the reference lacks (SURVEY.md §5: its only
        recovery is a single 15s retry).  The engine checkpoint (if
        configured) makes restarts resume exactly.
        """
        import time as _time

        sleep_fn = sleep_fn or _time.sleep
        failures = 0
        while not (should_stop is not None and should_stop()):
            try:
                self.run_tick()
                failures = 0
                sleep_fn(interval_s)
            except Exception as e:
                failures += 1
                self.observability.events.emit(
                    "app.tick_error", error=repr(e)[:500],
                    consecutive=failures,
                )
                log.exception(
                    "tick failed (%d consecutive); %s",
                    failures,
                    "giving up" if failures > max_restarts else "backing off",
                )
                if failures > max_restarts:
                    raise
                sleep_fn(min(interval_s * (2**failures), 60.0))

    def close(self) -> None:
        """Release the observability plane (scrape endpoint thread, the
        events JSONL file handle).  The bus/warehouse are left to their
        owners — they may be injected and shared; ``warehouse.close()``
        is explicit for the common single-owner case."""
        self.observability.close()

    @property
    def stats(self) -> Dict[str, object]:
        """Engine + warehouse counters, plus the attached fleet's runtime
        metrics when one exists (counters/gauges/latency summaries were
        previously reachable only through the gateway object itself)."""
        s: Dict[str, object] = {
            **self.engine.stats, "warehouse_rows": len(self.warehouse)
        }
        if self.fleet is not None:
            s["fleet"] = self.fleet.metrics.summary()
        return s

    @property
    def stage_timings(self) -> Dict[str, Dict[str, float]]:
        """Host-side wall clock per pipeline stage — the engine's
        ingest/join/land/signal stages, plus the fleet gateway's
        device/publish stages (prefixed ``fleet.``) when one is attached
        (SURVEY.md §5: the observability the reference never had)."""
        timings = dict(self.engine.timer.summary())
        if self.fleet is not None:
            for name, stats in self.fleet.metrics.timer.summary().items():
                timings[f"fleet.{name}"] = stats
        return timings
