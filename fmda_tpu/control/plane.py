"""ControlPlane: the closed-loop composition root beside the router.

One object owns the control loops and their cadence: it reads the
telemetry plane (``FleetTelemetry``'s windowed p99 + SLO burn rates),
runs the :class:`~fmda_tpu.control.controller.BatchingController` and
:class:`~fmda_tpu.control.autoscale.Autoscaler` decisions, and applies
them — batching retunes broadcast to every worker through the router
(``{"kind": "retune"}`` inbox messages; an in-process gateway is tuned
directly), scaling through the actuator.  Every decision lands in a
bounded ring surfaced by ``/control`` and ``python -m fmda_tpu status``
plus the shared EventLog, so the loop's history reads back next to the
faults and alerts it reacted to.

The plane is deliberately *advisory-only on the hot path*: the serving
loop calls :meth:`maybe_tick` (one clock read when not due, exactly the
telemetry cadence discipline) and nothing here ever blocks a tick.
jax-free: float compares, dict plumbing, inbox messages.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from fmda_tpu.control.autoscale import Autoscaler
from fmda_tpu.control.controller import BatchingController
from fmda_tpu.control.qos import QosPolicy

#: counter prefixes the per-tenant status section aggregates from
#: worker heartbeat stats / gateway metrics (dynamic per-class names —
#: the conservation vocabularies carry the aggregate counters instead)
TENANT_COUNTER_PREFIXES = ("admitted_class_", "shed_class_")


class ControlPlane:
    """Batching + autoscale loops on one cadence, one decision ring."""

    def __init__(
        self,
        cfg,
        *,
        telemetry=None,
        router=None,
        gateway=None,
        actuator=None,
        slo_cfg=None,
        initial_linger_ms: Optional[float] = None,
        bucket_sizes=(),
        signals_fn: Optional[Callable[[float], dict]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.cfg = cfg
        self.telemetry = telemetry
        self.router = router
        self.gateway = gateway
        self.clock = clock
        self._signals_fn = signals_fn
        self._last_tick: Optional[float] = None
        self.decisions = deque(maxlen=max(1, cfg.decisions_keep))
        events = telemetry.events if telemetry is not None else None

        target = cfg.target_p99_ms
        if target is None and slo_cfg is not None:
            target = slo_cfg.latency_p99_ms
        if target is None and telemetry is not None:
            target = telemetry.cfg.latency_p99_ms
        #: resolved p99 objective (ms); falls back to the SLOConfig
        #: default when nothing is configured — the loop must not run
        #: targetless
        self.target_p99_ms = float(target) if target else 250.0

        self.qos = QosPolicy.from_config(cfg)
        self.batching: Optional[BatchingController] = None
        if cfg.batching:
            self.batching = BatchingController(
                target_p99_ms=self.target_p99_ms,
                linger_ms=(initial_linger_ms if initial_linger_ms
                           is not None else cfg.max_linger_ms / 2.0),
                bucket_sizes=tuple(bucket_sizes),
                hysteresis=cfg.hysteresis,
                linger_step_ms=cfg.linger_step_ms,
                min_linger_ms=cfg.min_linger_ms,
                max_linger_ms=cfg.max_linger_ms,
                events=events,
            )
        self.autoscaler: Optional[Autoscaler] = None
        if cfg.autoscale and actuator is not None:
            self.autoscaler = Autoscaler(
                actuator,
                min_workers=cfg.min_workers,
                max_workers=cfg.max_workers,
                target_p99_ms=self.target_p99_ms,
                scale_up_burn=cfg.scale_up_burn,
                up_sustain_s=cfg.up_sustain_s,
                scale_down_frac=cfg.scale_down_frac,
                down_sustain_s=cfg.down_sustain_s,
                cooldown_s=cfg.cooldown_s,
                events=events,
            )
        if self.qos is not None and gateway is not None:
            gateway.attach_qos(self.qos)

    # -- cadence ------------------------------------------------------------

    def maybe_tick(self, now: Optional[float] = None) -> bool:
        """Run the loops when a full interval elapsed; one clock read
        otherwise — safe to call every pump."""
        now = self.clock() if now is None else now
        if (self._last_tick is not None
                and now - self._last_tick < self.cfg.interval_s):
            return False
        self.tick(now)
        return True

    def tick(self, now: Optional[float] = None) -> list:
        """One unconditional control evaluation; returns the decisions
        made (possibly empty)."""
        now = self.clock() if now is None else now
        self._last_tick = now
        signals = self.signals(now)
        made = []
        if self.batching is not None:
            decision = self.batching.decide(signals.get("p99_ms"), now)
            if decision is not None:
                self._apply_retune()
                made.append(decision)
        if self.autoscaler is not None:
            decision = self.autoscaler.decide(signals, now)
            if decision is not None:
                made.append(decision)
        self.decisions.extend(made)
        return made

    # -- signals ------------------------------------------------------------

    def signals(self, now: Optional[float] = None) -> dict:
        """The loops' inputs: fast-window p99 (None while idle) and the
        latency objective's fast burn rate.  An injected ``signals_fn``
        replaces the telemetry read (deterministic tests)."""
        now = self.clock() if now is None else now
        if self._signals_fn is not None:
            return self._signals_fn(now)
        if self.telemetry is None:
            return {"p99_ms": None, "burn_fast": 0.0}
        from fmda_tpu.obs.slo import SERIES_E2E

        hist = self.telemetry.store.window_histogram(
            SERIES_E2E, window_s=self.telemetry.cfg.fast_window_s, now=now)
        p99_ms = hist.percentile(99) * 1e3 if hist.n else None
        alert = self.telemetry.slo.alerts()["alerts"].get("latency_p99")
        burn = alert["burn_fast"] if alert else 0.0
        return {"p99_ms": p99_ms, "burn_fast": burn}

    # -- actuation ----------------------------------------------------------

    def _apply_retune(self) -> None:
        """Push the batching controller's knobs at the fleet: a retune
        broadcast through the router (each worker swaps its batcher
        config — frozen configs make the swap atomic), and/or a direct
        swap on an in-process gateway."""
        ctrl = self.batching
        if ctrl is None:
            return
        if self.router is not None:
            self.router.broadcast_retune(
                max_linger_ms=ctrl.linger_ms, bucket_cap=ctrl.bucket_cap)
        if self.gateway is not None:
            self.gateway.retune(
                max_linger_ms=ctrl.linger_ms, bucket_cap=ctrl.bucket_cap)

    # -- export -------------------------------------------------------------

    def status(self) -> dict:
        """The ``/control`` document: loop modes, knobs, worker count,
        per-tenant admit/shed aggregates, and the last-N decisions."""
        doc: dict = {
            "enabled": True,
            "interval_s": self.cfg.interval_s,
            "target_p99_ms": self.target_p99_ms,
            "decisions": list(self.decisions),
        }
        if self.batching is not None:
            doc["batching"] = self.batching.status()
        if self.autoscaler is not None:
            doc["autoscale"] = self.autoscaler.status()
        if self.qos is not None:
            doc["qos"] = self.qos.snapshot()
            tenants = self._tenant_counters()
            if tenants:
                doc["tenants"] = tenants
        return doc

    def _tenant_counters(self) -> dict:
        """Per-class admit/shed totals, summed across the fleet: from
        worker heartbeat stats (multi-host) and/or the in-process
        gateway's counters."""
        total: dict = {}

        def fold(counters) -> None:
            for name, value in counters.items():
                if name.startswith(TENANT_COUNTER_PREFIXES):
                    total[name] = total.get(name, 0) + int(value)

        if self.router is not None:
            for stats in self.router.worker_stats().values():
                fold(stats.get("tenant_counters", {}))
        if self.gateway is not None:
            fold(self.gateway.metrics.counters)
        return total
