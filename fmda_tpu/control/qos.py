"""Per-tenant QoS: weighted fair shedding + quotas for the gateway queue.

One gateway queue serves every tenant; under overload *someone's* tick
must go.  Global oldest-drop (the pre-control gateway) lets one noisy
tenant starve everyone — the classic shared-queue failure.  The policy
here is WFQ in drop form: each priority class owns a **weight** (its
fair share of the queue) and a **quota** (a hard cap on its queued
ticks).  Admission is work-conserving — a tick is only ever refused
when the queue is contended — and the victim of a forced drop is always
the class most over its *normalized* share (``queued / weight``, the
WFQ virtual-time ordering).  Two consequences the tests pin:

- **starvation-freedom**: a class at or under its fair share is never
  shed while any class sits over its share, no matter the priorities;
- **bounded damage**: a class flooding past its quota sheds its OWN
  oldest tick (counted ``quota_shed``), so its overflow never evicts a
  well-behaved tenant's traffic.

Deliberately jax-free and state-light (two dicts): the gateway calls
:meth:`classify` per submit and :meth:`pick_victim` only on the rare
contended path.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence, Tuple


class QosPolicy:
    """Weighted tenant classes over one bounded queue.

    ``classes``/``weights``/``quota_frac`` are parallel (highest
    priority first, by convention).  A tenant label not in ``classes``
    maps to ``default_class``; a ``default_class`` missing from the
    class list is appended with weight 1 and an uncapped quota, so an
    unlabeled session always has a lane.
    """

    def __init__(
        self,
        classes: Sequence[str],
        weights: Sequence[float],
        quota_frac: Sequence[float],
        *,
        default_class: str = "standard",
    ) -> None:
        if len(classes) != len(weights) or len(classes) != len(quota_frac):
            raise ValueError(
                f"classes/weights/quota_frac must be parallel, got "
                f"{len(classes)}/{len(weights)}/{len(quota_frac)}")
        if not classes:
            raise ValueError("need at least one class (or no policy at all)")
        if len(set(classes)) != len(classes):
            raise ValueError(f"duplicate class names: {list(classes)}")
        classes = list(classes)
        weights = list(weights)
        quota_frac = list(quota_frac)
        if default_class not in classes:
            classes.append(default_class)
            weights.append(1.0)
            quota_frac.append(1.0)
        for w in weights:
            if w <= 0:
                raise ValueError(f"weights must be positive: {weights}")
        for q in quota_frac:
            if not 0.0 < q <= 1.0:
                raise ValueError(
                    f"quota_frac must be in (0, 1]: {quota_frac}")
        self.classes: Tuple[str, ...] = tuple(classes)
        self.default_class = default_class
        self._weight: Dict[str, float] = dict(zip(classes, weights))
        self._quota_frac: Dict[str, float] = dict(zip(classes, quota_frac))
        #: deterministic tie-break: later (= lower-priority) classes
        #: shed first when normalized shares are exactly equal
        self._rank = {c: i for i, c in enumerate(self.classes)}

    @classmethod
    def from_config(cls, cfg) -> Optional["QosPolicy"]:
        """Build from a :class:`~fmda_tpu.config.ControlConfig`; None
        when no tenant classes are configured (QoS off — the gateway
        keeps its global oldest-drop)."""
        if not cfg.tenant_classes:
            return None
        return cls(cfg.tenant_classes, cfg.tenant_weights,
                   cfg.tenant_quota_frac, default_class=cfg.default_class)

    # -- classification -----------------------------------------------------

    def classify(self, tenant: Optional[str]) -> str:
        """The priority class of a tenant label (default for unknown/
        unlabeled — an unconfigured tenant must not error the hot path)."""
        if tenant is not None and tenant in self._weight:
            return tenant
        return self.default_class

    def weight(self, cls_name: str) -> float:
        return self._weight.get(cls_name, 1.0)

    def quota(self, cls_name: str, queue_bound: int) -> int:
        """Max queued ticks the class may hold (>= 1 so a class is
        never statically locked out)."""
        frac = self._quota_frac.get(cls_name, 1.0)
        return max(1, int(frac * queue_bound))

    # -- the WFQ drop decision ----------------------------------------------

    def pick_victim(self, queued: Mapping[str, int]) -> Optional[str]:
        """The class a forced drop should come from: the one most over
        its normalized fair share (``queued / weight`` — WFQ virtual
        time), lower priority losing ties.  None when nothing is
        queued."""
        best = None
        best_key = None
        for cls_name, n in queued.items():
            if n <= 0:
                continue
            key = (n / self._weight.get(cls_name, 1.0),
                   self._rank.get(cls_name, len(self._rank)))
            if best_key is None or key > best_key:
                best, best_key = cls_name, key
        return best

    # -- export -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/control`` document's QoS section."""
        return {
            "classes": [
                {
                    "name": c,
                    "weight": self._weight[c],
                    "quota_frac": self._quota_frac[c],
                }
                for c in self.classes
            ],
            "default_class": self.default_class,
        }
