"""Capacity model: sweep sessions × arrival rate → max sustainable load.

"How many tickers at what tick rate can this host serve inside the
SLO?" is the question every deployment sizing starts from, and the
control plane's scaling thresholds are only as good as the answer.
This sweep measures it empirically: for each (sessions, duty) cell a
fresh gateway serves a seeded synthetic load, and the cell is
*sustainable* when the measured p99 meets the objective with zero
sheds and every submitted tick served.  The output is one JSON
artifact (``schema`` pinned — downstream tooling parses it) listing
the grid, the max sustainable cell, and a fixed-vs-adaptive linger A/B
that shows the batching controller earning its keep on the same load.

jax-free by injection: callers supply ``gateway_factory(n_sessions)``
returning a :class:`~fmda_tpu.runtime.gateway.FleetGateway`-shaped
object (the bench phase builds real pools; the schema tests inject a
deterministic fake), so importing this module never touches the
accelerator stack.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import numpy as np

from fmda_tpu.control.controller import BatchingController

#: bump on any shape change; tests pin it together with the top-level keys
CAPACITY_SCHEMA = "fmda.control.capacity/1"

#: top-level artifact keys (pinned by tests/test_control.py)
CAPACITY_KEYS = (
    "schema", "slo_p99_ms", "rounds", "grid", "max_sustainable",
    "controller_ab",
)

#: per-cell keys (pinned alongside)
CELL_KEYS = (
    "sessions", "duty", "submitted", "served", "shed", "p99_ms",
    "ticks_per_s", "ok",
)


def _drive(
    gateway,
    n_sessions: int,
    duty: float,
    rounds: int,
    rng,
    *,
    on_round: Optional[Callable[[int], None]] = None,
) -> dict:
    """One load cell: open sessions, run seeded duty-cycled rounds,
    drain, report the cell measurements."""
    nf = getattr(gateway, "n_features", None)
    if nf is None:
        nf = gateway.pool.cfg.n_features
    sids = [f"C{i:04d}" for i in range(n_sessions)]
    for sid in sids:
        gateway.open_session(sid)
    submitted = 0
    t0 = time.perf_counter()
    for r in range(rounds):
        ticks = rng.random(n_sessions) < duty
        for i, sid in enumerate(sids):
            if ticks[i]:
                gateway.submit(
                    sid, rng.normal(size=nf).astype(np.float32))
                submitted += 1
        gateway.pump()
        if on_round is not None:
            on_round(r)
    gateway.drain()
    elapsed = max(time.perf_counter() - t0, 1e-9)
    for sid in sids:
        gateway.close_session(sid)
    counters = dict(gateway.metrics.counters)
    hist = gateway.metrics.histograms["total"]
    from fmda_tpu.obs.aggregate import GATEWAY_LOSS_COUNTERS

    shed = sum(counters.get(k, 0) for k in GATEWAY_LOSS_COUNTERS)
    return {
        "sessions": n_sessions,
        "duty": duty,
        "submitted": submitted,
        "served": counters.get("ticks_served", 0),
        "shed": shed,
        "p99_ms": round(hist.percentile(99) * 1e3, 3) if hist.n else None,
        "ticks_per_s": round(submitted / elapsed, 1),
    }


def run_capacity_model(
    gateway_factory: Callable[[int], object],
    *,
    slo_p99_ms: float,
    session_grid: Sequence[int] = (8, 16, 32),
    duty_grid: Sequence[float] = (0.25, 0.5, 1.0),
    rounds: int = 60,
    seed: int = 0,
    controller_ab: bool = True,
    ab_target_frac: float = 0.5,
) -> dict:
    """The full sweep → artifact dict (see module docstring).

    ``gateway_factory(n_sessions)`` must return a fresh gateway (own
    metrics) per call; each cell runs on its own so no queue state or
    histogram bleeds across cells."""
    grid = []
    for n_sessions in session_grid:
        for duty in duty_grid:
            rng = np.random.default_rng(seed)
            gw = gateway_factory(n_sessions)
            cell = _drive(gw, n_sessions, duty, rounds, rng)
            cell["ok"] = bool(
                cell["shed"] == 0
                and cell["served"] == cell["submitted"]
                and (cell["p99_ms"] is None
                     or cell["p99_ms"] <= slo_p99_ms))
            grid.append(cell)
    sustainable = [c for c in grid if c["ok"] and c["submitted"]]
    best = (max(sustainable, key=lambda c: c["ticks_per_s"])
            if sustainable else None)
    out = {
        "schema": CAPACITY_SCHEMA,
        "slo_p99_ms": slo_p99_ms,
        "rounds": rounds,
        "grid": grid,
        "max_sustainable": best,
        "controller_ab": None,
    }
    if controller_ab:
        # A/B at the LIGHTEST cell — the linger-bound regime.  At full
        # duty the buckets fill instantly and linger never binds, so no
        # controller could move the needle there; under a trickle the
        # fixed linger IS the tail latency, and cutting it is exactly
        # how the batching controller earns its keep.  Protocol: the
        # adaptive arm first converges on a warmup gateway (steering
        # toward ``ab_target_frac`` of the fixed-linger p99), then a
        # fresh gateway starts from the converged settings and the
        # measured histogram covers only steady-state ticks — a fair
        # fixed-vs-converged comparison, not one polluted by the
        # pre-convergence ramp.
        n_ab = min(session_grid)
        duty_ab = min(duty_grid)
        rng = np.random.default_rng(seed)
        fixed = _drive(gateway_factory(n_ab), n_ab, duty_ab, rounds, rng)
        target = None
        adaptive = None
        decisions = 0
        converged = None
        if fixed["p99_ms"]:
            target = max(fixed["p99_ms"] * ab_target_frac, 0.05)
            warm = gateway_factory(n_ab)
            linger0 = warm.batcher.config.max_linger_s * 1e3
            ctrl = BatchingController(
                target_p99_ms=target, linger_ms=linger0,
                bucket_sizes=warm.batcher.config.bucket_sizes,
                min_linger_ms=0.0,
                max_linger_ms=max(linger0, 1.0),
                linger_step_ms=max(linger0 / 4.0, 0.05))

            def steer_on(gw) -> Callable[[int], None]:
                def steer(r: int) -> None:
                    nonlocal decisions
                    if r % 5 != 4:
                        return
                    hist = gw.metrics.histograms["total"]
                    p99 = hist.percentile(99) * 1e3 if hist.n else None
                    if ctrl.decide(p99, float(r)) is not None:
                        decisions += 1
                        gw.retune(max_linger_ms=ctrl.linger_ms,
                                  bucket_cap=ctrl.bucket_cap)
                return steer

            rng = np.random.default_rng(seed)
            _drive(warm, n_ab, duty_ab, rounds, rng,
                   on_round=steer_on(warm))
            gw = gateway_factory(n_ab)
            gw.retune(max_linger_ms=ctrl.linger_ms,
                      bucket_cap=ctrl.bucket_cap)
            converged = {"linger_ms": round(ctrl.linger_ms, 4),
                         "bucket_cap": ctrl.bucket_cap}
            rng = np.random.default_rng(seed)
            adaptive = _drive(gw, n_ab, duty_ab, rounds, rng,
                              on_round=steer_on(gw))
        out["controller_ab"] = {
            "sessions": n_ab,
            "duty": duty_ab,
            "target_p99_ms": target,
            "fixed_p99_ms": fixed["p99_ms"],
            "adaptive_p99_ms": adaptive["p99_ms"] if adaptive else None,
            "converged": converged,
            "decisions": decisions,
            "improved": bool(
                adaptive and fixed["p99_ms"] and adaptive["p99_ms"]
                and adaptive["p99_ms"] < fixed["p99_ms"]),
        }
    return out
