"""The elastic soak: a market-open spike through the autoscaler, gated.

``run_elastic_soak`` launches the real spawned-worker topology at
``min_workers``, runs a three-phase load — calm warmup, market-open
spike (every session ticking in bursts), cool-down — with the
:class:`~fmda_tpu.control.plane.ControlPlane`'s autoscaler live: the
spike's latency burn must spawn a worker (sessions rebalance onto it
via live migration), and the cool-down's idle must retire it again
through :meth:`FleetRouter.request_leave` — the drain → export →
replay migration, so the scale-down loses zero sessions and zero
ticks.  The report hard-gates the chaos soak's never-abort contract on
the way:

- ``exit_ok`` / ``unaccounted_zero`` / ``no_unexpected_results`` —
  the accounting identity (submitted == served + counted losses) holds
  through both scaling moves;
- ``scaled_up`` / ``scaled_down`` — the loop actually moved, both
  directions, and the fleet ended back at ``min_workers``;
- ``zero_session_loss`` — no session lost carried state to either
  migration wave;
- ``post_scale_all_served`` — after the scale-down, probe ticks to
  every session are served by the shrunk fleet (migrated-back sessions
  serve for real, not merely import);
- with ``compare_fixed=True`` the identical seeded schedule replays
  through a fixed ``min_workers`` fleet and every clean session must be
  **bit-identical** — elasticity may move sessions, never change them.
  Bucket size is pinned to 1 (flush composition must not perturb XLA
  reduction order), exactly the chaos soak's discipline.

The latency target is *calibrated*, not configured: the warmup phase
measures this host's baseline p99 and the objective is set a fixed
multiple above it, so the spike burns budget and the cool-down clears
it on fast and slow hosts alike.  Router-role code: numpy + stdlib, no
jax (the workers own the accelerator math in their processes).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Dict, Optional

import numpy as np

from fmda_tpu.chaos.soak import LOSS_COUNTERS, Norm, _identity_verdict
from fmda_tpu.config import FrameworkConfig
from fmda_tpu.control.autoscale import LocalFleetActuator
from fmda_tpu.control.plane import ControlPlane
from fmda_tpu.obs.slo import SERIES_E2E

log = logging.getLogger("fmda_tpu.control")

#: tenant labels cycled over the soak's sessions — QoS stays detached
#: here (no policy at the workers), but every label must survive open →
#: migrate → report → readopt verbatim (the report asserts it)
SOAK_TENANTS = ("gold", "standard", "bronze")


def run_elastic_soak(
    *,
    n_sessions: int = 8,
    hidden: int = 8,
    seed: int = 0,
    window: int = 8,
    min_workers: int = 1,
    max_workers: int = 2,
    warmup_rounds: int = 30,
    base_duty: float = 0.2,
    spike_batch: int = 4,
    spike_timeout_s: float = 90.0,
    drop_timeout_s: float = 120.0,
    probe_rounds: int = 3,
    target_mult: float = 4.0,
    compare_fixed: bool = True,
    config: Optional[FrameworkConfig] = None,
    wait_timeout_s: float = 240.0,
    sleep_fn: Callable[[float], None] = time.sleep,
) -> dict:
    """Run the soak; returns the gated report (see the module doc).

    The spike and cool phases are wall-clock-bounded (worker spawn cost
    is real), but every round's rng consumption is schedule-pure — the
    adaptive run records its actual round counts and the fixed
    reference replays them exactly, so the bit-identity comparison sees
    two runs of one schedule."""
    config = _elastic_config(config)
    adaptive = _run_topology(
        None, elastic=True, config=config, n_sessions=n_sessions,
        hidden=hidden, seed=seed, window=window,
        min_workers=min_workers, max_workers=max_workers,
        warmup_rounds=warmup_rounds, base_duty=base_duty,
        spike_batch=spike_batch, spike_timeout_s=spike_timeout_s,
        drop_timeout_s=drop_timeout_s, probe_rounds=probe_rounds,
        target_mult=target_mult, wait_timeout_s=wait_timeout_s,
        sleep_fn=sleep_fn)
    report = _gate_report(adaptive, min_workers)
    if compare_fixed:
        reference = _run_topology(
            adaptive["schedule"], elastic=False, config=config,
            n_sessions=n_sessions, hidden=hidden, seed=seed,
            window=window, min_workers=min_workers,
            max_workers=max_workers, warmup_rounds=warmup_rounds,
            base_duty=base_duty, spike_batch=spike_batch,
            spike_timeout_s=spike_timeout_s,
            drop_timeout_s=drop_timeout_s, probe_rounds=probe_rounds,
            target_mult=target_mult, wait_timeout_s=wait_timeout_s,
            sleep_fn=sleep_fn)
        report["identity"] = _identity_verdict(adaptive, reference)
        report["gates"]["identity_ok"] = report["identity"]["ok"]
    report["gates_ok"] = all(report["gates"].values())
    return report


def _elastic_config(config: Optional[FrameworkConfig]) -> FrameworkConfig:
    """The soak posture: fast failure detection, tight linger (bucket-1
    flushes), generous queue bound (the spike is a latency test, not a
    shed test — sheds would break the router-side accounting identity)."""
    config = config or FrameworkConfig()
    return dataclasses.replace(
        config,
        fleet=dataclasses.replace(
            config.fleet,
            heartbeat_interval_s=0.2,
            heartbeat_timeout_s=5.0,
            result_timeout_s=10.0,
            control_retry_s=0.3,
        ),
        runtime=dataclasses.replace(
            config.runtime, max_linger_ms=0.5, queue_bound=4096),
        slo=dataclasses.replace(
            config.slo,
            interval_s=min(config.slo.interval_s, 0.25),
            scrape_interval_s=min(config.slo.scrape_interval_s, 1.0),
            fast_window_s=min(config.slo.fast_window_s, 2.0),
            slow_window_s=min(config.slo.slow_window_s, 8.0),
        ),
    )


def _run_topology(
    schedule: Optional[Dict[str, int]],
    *,
    elastic: bool,
    config: FrameworkConfig,
    n_sessions: int,
    hidden: int,
    seed: int,
    window: int,
    min_workers: int,
    max_workers: int,
    warmup_rounds: int,
    base_duty: float,
    spike_batch: int,
    spike_timeout_s: float,
    drop_timeout_s: float,
    probe_rounds: int,
    target_mult: float,
    wait_timeout_s: float,
    sleep_fn: Callable[[float], None],
) -> dict:
    from fmda_tpu.fleet.launcher import launch_local_fleet
    from fmda_tpu.obs.aggregate import FleetTelemetry

    telemetry = FleetTelemetry(config.slo) if elastic else None
    topo = launch_local_fleet(
        n_workers=min_workers, config=config, hidden=hidden, seed=seed,
        capacity_per_worker=max(4, n_sessions),
        bucket_sizes=(1,), window=window,
        wait_timeout_s=wait_timeout_s)
    router = topo.router
    plane: Optional[ControlPlane] = None
    rng = np.random.default_rng(seed)
    feats = config.features.n_features
    sids = [f"E{i:03d}" for i in range(n_sessions)]
    tenants = {sid: SOAK_TENANTS[i % len(SOAK_TENANTS)]
               for i, sid in enumerate(sids)}
    mins = rng.normal(0.0, 1.0, (n_sessions, feats)).astype(np.float32)
    maxs = mins + rng.uniform(1.0, 5.0, (n_sessions, feats)).astype(
        np.float32)
    walk = rng.normal(size=(n_sessions, feats)).astype(np.float32)
    seq_to_idx: Dict[str, Dict[int, int]] = {s: {} for s in sids}
    results: Dict[str, Dict[int, np.ndarray]] = {s: {} for s in sids}
    submitted: Dict[str, int] = {s: 0 for s in sids}
    post_served: Dict[str, int] = {s: 0 for s in sids}
    submit_failures: Dict[str, int] = {}
    unexpected = 0
    max_live = min_workers
    counting_probes = False
    ran: Dict[str, int] = {}
    target_p99_ms = None
    try:
        for i, sid in enumerate(sids):
            router.open_session(sid, Norm(mins[i], maxs[i]),
                                tenant=tenants[sid])

        def absorb() -> None:
            nonlocal unexpected, max_live
            for res in router.pump():
                idx = seq_to_idx.get(res.session_id, {}).get(res.seq)
                if idx is None or idx in results[res.session_id]:
                    unexpected += 1
                    continue
                results[res.session_id][idx] = np.asarray(
                    res.probabilities, np.float32)
                if counting_probes:
                    post_served[res.session_id] += 1
            max_live = max(max_live, len(router.membership.live()))
            if telemetry is not None:
                telemetry.maybe_collect(router)
            if plane is not None:
                plane.maybe_tick()

        def submit_tick(i: int) -> None:
            sid = sids[i]
            waited = 0.0
            while router.saturated and waited < 5.0:
                absorb()
                sleep_fn(0.002)
                waited += 0.002
            try:
                seq = router.submit(sid, walk[i])
            except KeyError:
                submit_failures[sid] = submit_failures.get(sid, 0) + 1
                return
            seq_to_idx[sid][seq] = submitted[sid]
            submitted[sid] += 1

        def do_round(reps: int, duty: float, pace_s: float) -> None:
            # rng consumption is a pure function of (reps, duty) — the
            # reference run replays the identical stream per round
            ticking = rng.random(n_sessions) < duty
            for _ in range(reps):
                deltas = rng.normal(
                    scale=0.1, size=(n_sessions, feats)).astype(
                        np.float32)
                walk[ticking] += deltas[ticking]
                for i in np.flatnonzero(ticking):
                    submit_tick(int(i))
            absorb()
            if pace_s:
                sleep_fn(pace_s)

        # -- warmup: measure this host's baseline p99 -------------------
        for _ in range(warmup_rounds):
            do_round(1, base_duty, 0.02)
        ran["warmup"] = warmup_rounds
        # calibration must read a POPULATED window: the scrape cadence
        # lags the first rounds, and a target derived from an empty
        # histogram would sit far under the pacing-dominated baseline —
        # burn would pin at max and the fleet could never look idle
        # again.  Extra rounds are schedule-pure (the reference replays
        # the recorded count); only the elastic run decides when to stop.
        cal = 0
        budget = schedule["calibrate"] if schedule is not None else None
        deadline = time.monotonic() + 20.0
        while True:
            if budget is not None:
                if cal >= budget:
                    break
            else:
                hist = telemetry.store.window_histogram(
                    SERIES_E2E, window_s=config.slo.slow_window_s,
                    now=telemetry.clock())
                if hist.n >= 20 or time.monotonic() > deadline:
                    break
            do_round(1, base_duty, 0.02)
            cal += 1
        ran["calibrate"] = cal
        if elastic:
            hist = telemetry.store.window_histogram(
                SERIES_E2E, window_s=config.slo.slow_window_s,
                now=telemetry.clock())
            base_ms = hist.percentile(99) * 1e3 if hist.n else 1.0
            target_p99_ms = min(max(target_mult * base_ms, 2.0), 200.0)
            ctrl_cfg = dataclasses.replace(
                config.control,
                batching=False, autoscale=True,
                target_p99_ms=target_p99_ms,
                interval_s=0.25,
                min_workers=min_workers, max_workers=max_workers,
                scale_up_burn=2.0, up_sustain_s=0.75,
                scale_down_frac=0.5, down_sustain_s=2.0,
                cooldown_s=1.5)
            plane = ControlPlane(
                ctrl_cfg, telemetry=telemetry, router=router,
                actuator=LocalFleetActuator(topo),
                slo_cfg=dataclasses.replace(
                    config.slo, latency_p99_ms=target_p99_ms))
            # the SLO engine judges burn against the calibrated target
            telemetry.slo.cfg = dataclasses.replace(
                telemetry.slo.cfg, latency_p99_ms=target_p99_ms)

        # -- market-open spike: every session, spike_batch deep ---------
        spike = 0
        deadline = time.monotonic() + spike_timeout_s
        budget = schedule["spike"] if schedule is not None else None
        while True:
            if budget is not None:
                if spike >= budget:
                    break
            elif (len(router.membership.live()) > min_workers
                  or time.monotonic() > deadline):
                break
            do_round(spike_batch, 1.0, 0.0)
            spike += 1
        ran["spike"] = spike

        # -- cool-down: idle until the fleet shrinks back ---------------
        cool = 0
        deadline = time.monotonic() + drop_timeout_s
        budget = schedule["cool"] if schedule is not None else None
        while True:
            if budget is not None:
                if cool >= budget:
                    break
            elif (len(router.membership.live()) <= min_workers
                  and cool >= 10) or time.monotonic() > deadline:
                break
            do_round(1, base_duty, 0.03)
            cool += 1
        ran["cool"] = cool

        # -- settle + probes through the (shrunk) fleet ------------------
        settle_deadline = time.monotonic() + 30.0
        while router.outstanding_ticks \
                and time.monotonic() < settle_deadline:
            absorb()
            sleep_fn(0.01)
        counting_probes = True
        for _ in range(probe_rounds):
            do_round(1, 1.01, 0.02)  # duty > 1: every session probes
        ran["probes"] = probe_rounds
        settle_deadline = time.monotonic() + 30.0
        while router.outstanding_ticks \
                and time.monotonic() < settle_deadline:
            absorb()
            sleep_fn(0.01)
        tainted = set(router.lost_state_sessions)
        tenant_intact = all(
            router.session_tenant(sid) == tenants[sid] for sid in sids
            if sid in router.open_session_ids())
        counters = dict(router.metrics.counters)
        worker_stats = dict(router.worker_stats())
        final_live = len(router.membership.live())
        decisions = list(plane.decisions) if plane is not None else []
    finally:
        try:
            topo.shutdown()
        except Exception:  # noqa: BLE001 — loss-free: teardown failure
            # must not mask the run's own verdict; gates have evidence
            log.exception("elastic soak teardown failed")
    return {
        "schedule": ran,
        "sessions": sids,
        "submitted": submitted,
        "submit_failures": submit_failures,
        "results": results,
        "post_served": post_served,
        "unexpected_results": unexpected,
        "seq_reused": [],  # no takeover path: wire seqs never reused
        "counters": counters,
        "worker_stats": worker_stats,
        "tainted": sorted(tainted),
        "tenant_intact": tenant_intact,
        "target_p99_ms": target_p99_ms,
        "max_live": max_live,
        "final_live": final_live,
        "decisions": decisions,
    }


def _gate_report(run: dict, min_workers: int) -> dict:
    counters = run["counters"]
    n_submitted = sum(run["submitted"].values())
    n_served = sum(len(v) for v in run["results"].values())
    losses = sum(counters.get(k, 0) for k in LOSS_COUNTERS)
    unaccounted = n_submitted - n_served - losses
    post_quiet = [s for s, n in run["post_served"].items() if n == 0]
    actions = [d["action"] for d in run["decisions"]]
    # elastic scaling must never pay a compile mid-traffic: migrated-in
    # sessions land on already-traced buckets, so every worker's
    # post-warmup recompile count stays zero (ISSUE 17 ledger contract)
    recompiles = sum(
        int(s.get("recompiles_after_warmup", 0) or 0)
        for s in run["worker_stats"].values())
    gates = {
        "exit_ok": True,  # reaching here at all is gate zero
        "unaccounted_zero": unaccounted == 0,
        "no_unexpected_results": run["unexpected_results"] == 0,
        "no_recompiles_after_warmup": recompiles == 0,
        "scaled_up": ("scale_up" in actions
                      and run["max_live"] > min_workers),
        "scaled_down": ("scale_down" in actions
                        and run["final_live"] == min_workers),
        "zero_session_loss": (
            not run["tainted"]
            and counters.get("sessions_lost_state", 0) == 0
            and run["tenant_intact"]),
        "post_scale_all_served": not post_quiet,
    }
    return {
        "schedule": run["schedule"],
        "ticks_submitted": n_submitted,
        "ticks_served": n_served,
        "losses": {k: counters.get(k, 0) for k in LOSS_COUNTERS
                   if counters.get(k, 0)},
        "unaccounted": unaccounted,
        "target_p99_ms": run["target_p99_ms"],
        "max_live": run["max_live"],
        "final_live": run["final_live"],
        "decisions": run["decisions"],
        "post_scale_quiet_sessions": post_quiet,
        "submit_failures": run["submit_failures"],
        "recompiles_after_warmup": recompiles,
        "gates": gates,
    }
