"""Elastic autoscaler: worker count follows sustained burn, loses nothing.

The scaling *decision* is ordinary control theory — scale up when the
latency objective's fast burn rate holds above threshold for a sustained
window, scale down when the fleet idles well under target for longer,
with a cooldown so the two never chatter.  What makes it safe is the
*actuation*: joins ride the fleet's ordinary hello→rebalance path (a new
worker is indistinguishable from a chaos revive), and retirement is
:meth:`FleetRouter.request_leave` — the drain → export → replay live
migration, so a scale-down moves every session bit-exactly and loses
zero ticks (the elastic soak's never-abort gates hold this).

The **actuator protocol** keeps the loop topology-agnostic — anything
with these three methods can be scaled:

- ``n_workers() -> int`` — live, non-leaving worker count;
- ``spawn_worker() -> Optional[str]`` — add one (None = can't);
- ``retire_worker() -> Optional[str]`` — begin one graceful leave.

:class:`LocalFleetActuator` drives the local launcher topology
(``fmda_tpu.fleet.launcher``); the tests drive in-process workers with
a ~20-line actuator.  jax-free throughout.
"""

from __future__ import annotations

from typing import Optional


class LocalFleetActuator:
    """Actuator over a :class:`~fmda_tpu.fleet.launcher.LocalFleet`:
    spawn = launch one more worker process into the topology, retire =
    ask the router for a graceful leave of the highest-numbered live
    worker (deterministic; the migration machinery makes any choice
    safe)."""

    def __init__(self, topo) -> None:
        self.topo = topo
        #: spawned but not yet in membership — counted toward
        #: ``n_workers`` so a slow join (process start + accelerator
        #: init) can't make the loop spawn the same capacity twice
        self._pending: list = []

    def n_workers(self) -> int:
        # live() already excludes leaving workers: a worker mid-retire
        # must not count, or the loop would retire a second one
        live = self.topo.router.membership.live()
        self._pending = [w for w in self._pending if w not in live]
        return len(live) + len(self._pending)

    def spawn_worker(self) -> Optional[str]:
        wid = self.topo.add_worker()
        if wid is not None:
            self._pending.append(wid)
        return wid

    def retire_worker(self) -> Optional[str]:
        live = self.topo.router.membership.live()
        if len(live) < 2:
            # never drain the last live worker — its sessions would
            # orphan with nowhere to migrate
            return None
        wid = live[-1]
        if not self.topo.router.request_leave(wid):
            return None
        return wid


class Autoscaler:
    """Sustained-signal worker-count loop with cooldown hysteresis."""

    def __init__(
        self,
        actuator,
        *,
        min_workers: int = 1,
        max_workers: int = 8,
        target_p99_ms: float = 250.0,
        scale_up_burn: float = 1.0,
        up_sustain_s: float = 3.0,
        scale_down_frac: float = 0.3,
        down_sustain_s: float = 10.0,
        cooldown_s: float = 5.0,
        events=None,
    ) -> None:
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"{min_workers}/{max_workers}")
        self.actuator = actuator
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.target_p99_ms = float(target_p99_ms)
        self.scale_up_burn = float(scale_up_burn)
        self.up_sustain_s = float(up_sustain_s)
        self.scale_down_frac = float(scale_down_frac)
        self.down_sustain_s = float(down_sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.events = events
        self.mode = "hold"
        #: first instant the pressure signal went (and stayed) high/low;
        #: None while the signal sits in between — sustain windows
        #: restart whenever the signal leaves its regime
        self._high_since: Optional[float] = None
        self._low_since: Optional[float] = None
        self._last_move: Optional[float] = None

    # -- the decision -------------------------------------------------------

    def decide(self, signals: dict, now: float) -> Optional[dict]:
        """One evaluation over the telemetry signals — ``burn_fast``
        (latency objective, fast window) and ``p99_ms`` (None = idle).
        Returns the decision record when a scaling move happened."""
        burn = float(signals.get("burn_fast", 0.0) or 0.0)
        p99_ms = signals.get("p99_ms")
        high = burn >= self.scale_up_burn
        low = (not high) and (
            p99_ms is None
            or p99_ms < self.scale_down_frac * self.target_p99_ms)
        self._high_since = (
            (self._high_since if self._high_since is not None else now)
            if high else None)
        self._low_since = (
            (self._low_since if self._low_since is not None else now)
            if low else None)
        self.mode = "high" if high else ("low" if low else "hold")

        if self._cooling(now):
            return None
        n = self.actuator.n_workers()
        if (high and n < self.max_workers
                and now - self._high_since >= self.up_sustain_s):
            wid = self.actuator.spawn_worker()
            if wid is None:
                return None
            return self._moved("scale_up", wid, now, burn, p99_ms)
        if (low and n > self.min_workers
                and now - self._low_since >= self.down_sustain_s):
            wid = self.actuator.retire_worker()
            if wid is None:
                return None
            return self._moved("scale_down", wid, now, burn, p99_ms)
        return None

    def _cooling(self, now: float) -> bool:
        return (self._last_move is not None
                and now - self._last_move < self.cooldown_s)

    def _moved(self, action: str, wid: str, now: float,
               burn: float, p99_ms) -> dict:
        self._last_move = now
        # both sustain windows restart: the fleet the signal measured
        # no longer exists
        self._high_since = None
        self._low_since = None
        decision = {
            "t": now,
            "loop": "autoscale",
            "action": action,
            "worker": wid,
            "n_workers": self.actuator.n_workers(),
            "burn_fast": round(burn, 4),
            "p99_ms": None if p99_ms is None else round(p99_ms, 3),
        }
        if self.events is not None:
            self.events.emit("control.autoscale", **decision)
        return decision

    # -- export -------------------------------------------------------------

    def status(self) -> dict:
        return {
            "mode": self.mode,
            "workers": self.actuator.n_workers(),
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "scale_up_burn": self.scale_up_burn,
            "cooldown_s": self.cooldown_s,
        }
