"""SLO-feedback batching controller: steer linger/bucket toward the p99.

The micro-batcher trades latency for MXU efficiency with two knobs —
``max_linger_s`` (how long the oldest tick may wait for company) and
the effective bucket cap (how large a flush may grow).  Static values
are wrong twice a day: at the open they burn the latency budget, at the
close they pad half-empty buckets.  This loop closes them against the
live ``fleet_e2e_p99_ms`` (the telemetry plane's fast-window exact p99)
vs the ``[slo]`` latency objective:

- p99 **above** the deadband → latency is burning: cut linger by one
  bounded step; at the linger floor, halve the bucket cap (smaller
  flushes leave the queue sooner).
- p99 **below** the deadband → latency budget to spend: restore the
  bucket cap first (throughput is cheaper than waiting), then grow
  linger one step.
- inside the deadband (``hysteresis`` × target, both sides) → hold.
  The deadband plus bounded steps is what keeps the loop from
  oscillating: a move changes p99 by roughly one step's worth, which
  lands inside the band instead of overshooting to the other wall.

Every move is an EventLog record (``control.batching``) and a decision
dict in the plane's ring — a controller that can't show its work is
untrustable at 3am.  Deliberately jax-free: decisions are float
compares on telemetry reads.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple


class BatchingController:
    """Hysteresis + bounded-step feedback from p99 to batching knobs."""

    def __init__(
        self,
        *,
        target_p99_ms: float,
        linger_ms: float,
        bucket_sizes: Tuple[int, ...] = (),
        hysteresis: float = 0.25,
        linger_step_ms: float = 0.25,
        min_linger_ms: float = 0.0,
        max_linger_ms: float = 8.0,
        events=None,
    ) -> None:
        if target_p99_ms <= 0:
            raise ValueError(f"target_p99_ms must be > 0: {target_p99_ms}")
        self.target_p99_ms = float(target_p99_ms)
        self.hysteresis = float(hysteresis)
        self.linger_step_ms = float(linger_step_ms)
        self.min_linger_ms = float(min_linger_ms)
        self.max_linger_ms = float(max_linger_ms)
        #: ascending compiled bucket set; the cap only ever selects a
        #: member (a novel size would compile on the tick path)
        self.bucket_sizes = tuple(bucket_sizes)
        self.linger_ms = min(max(float(linger_ms), self.min_linger_ms),
                             self.max_linger_ms)
        #: None = uncapped (largest bucket); otherwise one of
        #: ``bucket_sizes``
        self.bucket_cap: Optional[int] = None
        self.mode = "hold"
        self.events = events

    # -- the decision -------------------------------------------------------

    def decide(self, p99_ms: Optional[float], now: float) -> Optional[dict]:
        """One evaluation: returns the decision record for a move, None
        for hold/idle.  ``p99_ms`` None means no served ticks in the
        window — an idle fleet must not creep its knobs around."""
        if p99_ms is None:
            self.mode = "idle"
            return None
        hi = self.target_p99_ms * (1.0 + self.hysteresis)
        lo = self.target_p99_ms * (1.0 - self.hysteresis)
        action = None
        if p99_ms > hi:
            self.mode = "shrink"
            action = self._shrink()
        elif p99_ms < lo:
            self.mode = "grow"
            action = self._grow()
        else:
            self.mode = "hold"
        if action is None:
            return None
        decision = {
            "t": now,
            "loop": "batching",
            "action": action,
            "p99_ms": round(p99_ms, 3),
            "target_p99_ms": self.target_p99_ms,
            "linger_ms": round(self.linger_ms, 4),
            "bucket_cap": self.bucket_cap,
        }
        if self.events is not None:
            self.events.emit("control.batching", **decision)
        return decision

    def _shrink(self) -> Optional[str]:
        """Over target: linger down one step, then bucket cap down."""
        if self.linger_ms > self.min_linger_ms:
            self.linger_ms = max(
                self.min_linger_ms, self.linger_ms - self.linger_step_ms)
            return "linger_down"
        smaller = self._cap_neighbor(-1)
        if smaller is not None:
            self.bucket_cap = smaller
            return "bucket_down"
        return None  # pinned at the floor: nothing left to give

    def _grow(self) -> Optional[str]:
        """Under target: bucket cap back up first, then linger up."""
        larger = self._cap_neighbor(+1)
        if larger is not None:
            self.bucket_cap = (
                None if larger == self.bucket_sizes[-1] else larger)
            return "bucket_up"
        if self.linger_ms < self.max_linger_ms:
            self.linger_ms = min(
                self.max_linger_ms, self.linger_ms + self.linger_step_ms)
            return "linger_up"
        return None  # pinned at the ceiling

    def _cap_neighbor(self, step: int) -> Optional[int]:
        """The next bucket size in ``step`` direction from the current
        cap; None at the end of the ladder (or with no ladder at all)."""
        if not self.bucket_sizes:
            return None
        cur = (self.bucket_cap if self.bucket_cap is not None
               else self.bucket_sizes[-1])
        try:
            idx = self.bucket_sizes.index(cur)
        except ValueError:
            return None
        idx += step
        if idx < 0 or idx >= len(self.bucket_sizes):
            return None
        return self.bucket_sizes[idx]

    # -- export -------------------------------------------------------------

    def status(self) -> dict:
        return {
            "mode": self.mode,
            "target_p99_ms": self.target_p99_ms,
            "linger_ms": round(self.linger_ms, 4),
            "bucket_cap": self.bucket_cap,
            "deadband_ms": [
                round(self.target_p99_ms * (1.0 - self.hysteresis), 3),
                round(self.target_p99_ms * (1.0 + self.hysteresis), 3),
            ],
        }
