"""fmda_tpu.control — the adaptive control plane beside the router.

Three closed loops read the telemetry plane (``FleetTelemetry``'s
windowed exact p99s and SLO burn rates) and act on the serving fleet:

- :class:`~fmda_tpu.control.controller.BatchingController` steers the
  gateway's linger/bucket knobs toward the ``[slo]`` p99 objective
  (hysteresis deadband + bounded steps; retunes broadcast through the
  router's inbox protocol);
- :class:`~fmda_tpu.control.qos.QosPolicy` makes admission weighted:
  sessions carry a tenant class, and under overload the gateway sheds
  by WFQ fair share with per-class quotas (counted ``quota_shed``)
  instead of global oldest-drop;
- :class:`~fmda_tpu.control.autoscale.Autoscaler` grows the fleet on
  sustained burn and shrinks it on idle through the zero-loss live
  migration (``FleetRouter.request_leave``).

:class:`~fmda_tpu.control.plane.ControlPlane` composes them on one
cadence with a decision ring (``/control``, ``python -m fmda_tpu
status``); :mod:`~fmda_tpu.control.capacity` sweeps sessions × arrival
rate into the capacity-model artifact, and
:mod:`~fmda_tpu.control.elastic` gates a market-open spike through the
autoscaler under the chaos soak's never-abort contract.

Router-role code throughout: numpy + stdlib, no jax on this import
path (the lint gate pins it).  Architecture: docs/control.md.
"""

from fmda_tpu.control.autoscale import Autoscaler, LocalFleetActuator
from fmda_tpu.control.controller import BatchingController
from fmda_tpu.control.plane import ControlPlane
from fmda_tpu.control.qos import QosPolicy

__all__ = [
    "Autoscaler",
    "BatchingController",
    "CAPACITY_SCHEMA",
    "ControlPlane",
    "LocalFleetActuator",
    "QosPolicy",
    "run_capacity_model",
    "run_elastic_soak",
]


def __getattr__(name):  # PEP 562 — soak/bench entry points load lazily
    if name == "run_elastic_soak":
        from fmda_tpu.control.elastic import run_elastic_soak

        return run_elastic_soak
    if name in ("run_capacity_model", "CAPACITY_SCHEMA"):
        from fmda_tpu.control import capacity

        return getattr(capacity, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
