"""Command-line entry points: ``python -m fmda_tpu <command>``.

The reference is operated by hand-running five scripts in order
(producer.py, spark_consumer.py, create_database.py, the training
notebook, predict.py — reference README.md:186-292); here the same
operations are subcommands over one file-backed warehouse:

- ``demo``      synthetic end-to-end proof: corpus → warehouse → train →
                backtest (no network, no accelerator requirements);
- ``ingest``    replay or live-feed a session into a warehouse file;
- ``train``     chunked training over a warehouse file → Orbax checkpoint;
- ``backtest``  serving-equivalent scoring + signal-quality table;
- ``serve``     the prediction daemon (push-triggered, no sleep-15);
- ``status``    pretty-print an observability snapshot (metrics registry
                + health checks), either from a locally built app or
                scraped from a running ``/snapshot`` endpoint;
- ``trace``     inspect recorded tick traces (per-stage latency
                attribution) from a ``--trace-out`` file or a running
                ``/trace`` endpoint;
- ``lint``      framework-aware static analysis over the package
                (lock discipline, jit purity, JAX API drift as a
                zero-baseline hard gate, compat-shim confinement,
                topic cross-checks, hygiene rules); exit 0 = clean
                against the baseline, 1 = new findings, 2 = usage
                error.

Every command is a thin composition of the public library API — anything
the CLI does is one import away in a notebook.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _config(args):
    """FrameworkConfig from --config (JSON), or the defaults."""
    from fmda_tpu.config import FrameworkConfig, load_config

    path = getattr(args, "config", None)
    return load_config(path) if path else FrameworkConfig()


def _ensure_backend(args) -> None:
    """Never hang on a wedged accelerator (the round-1 entry-point failure
    mode, shared with bench.py/__graft_entry__).

    ``--platform cpu`` forces the host platform outright (a config update
    beats the env var: the accelerator plugin's sitecustomize overrides
    ``JAX_PLATFORMS`` at interpreter start). ``--platform auto`` (default)
    probes the ambient backend in a throwaway subprocess with a timeout
    and falls back to CPU, loudly, when the probe fails; ``ambient``
    skips the probe (trust the environment, fastest startup).
    """
    platform = getattr(args, "platform", "auto")
    if platform == "ambient":
        return
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return
    if jax.config.jax_platforms == "cpu":
        # already pinned to the host platform (e.g. a test harness or an
        # embedding application did config.update) — nothing to probe
        return
    from fmda_tpu.utils.env import probe_backend

    probe = probe_backend(getattr(args, "probe_timeout_s", 120.0))
    if "error" in probe:
        print(
            f"backend probe failed ({probe['error']}); forcing CPU",
            file=sys.stderr,
        )
        jax.config.update("jax_platforms", "cpu")


def _ckpt_dir(args, cfg) -> str:
    """--checkpoint-dir if passed, else the config's train.checkpoint_dir."""
    return (args.checkpoint_dir if args.checkpoint_dir is not None
            else cfg.train.checkpoint_dir)


def _warehouse(path: str, cfg):
    import dataclasses

    from fmda_tpu.stream import Warehouse

    return Warehouse(
        cfg.features, dataclasses.replace(cfg.warehouse, path=path))


def cmd_demo(args) -> int:
    _ensure_backend(args)
    from fmda_tpu.data.synthetic import SyntheticMarketConfig, build_corpus

    cfg = _config(args)
    # absent flags fall back to the config file when one is given, else to
    # quick demo defaults
    epochs = args.epochs if args.epochs is not None else (
        cfg.train.epochs if args.config else 2)
    batch_size = args.batch_size if args.batch_size is not None else (
        cfg.train.batch_size if args.config else 32)
    seed = args.seed if args.seed is not None else cfg.train.seed
    wh, stats = build_corpus(
        cfg.features, SyntheticMarketConfig(seed=seed, n_days=args.days))
    print(f"corpus: {len(wh)} rows ({stats})")
    ckpt = _train(wh, cfg, epochs=epochs, batch_size=batch_size,
                  checkpoint_dir=_ckpt_dir(args, cfg), seed=seed)
    if ckpt is None:
        return 2
    # score exactly the checkpoint this demo just trained, never whatever
    # happens to be newest in a shared checkpoint dir
    return _backtest(wh, cfg, ckpt, window=cfg.train.window,
                     threshold=cfg.train.prob_threshold)


def cmd_ingest(args) -> int:
    import dataclasses

    from fmda_tpu.app import Application
    from fmda_tpu.data.synthetic import (
        SyntheticMarketConfig, synthetic_session_messages,
    )

    cfg = _config(args)
    # CLI overrides fold into the config; one composition root builds
    # bus + warehouse + engine exactly as the library API would
    engine_overrides = {
        k: v for k, v in dict(
            checkpoint_path=args.engine_checkpoint,
            checkpoint_every=args.checkpoint_every,
        ).items() if v is not None
    }
    cfg = dataclasses.replace(
        cfg,
        warehouse=dataclasses.replace(cfg.warehouse, path=args.warehouse),
        engine=dataclasses.replace(cfg.engine, **engine_overrides),
    )
    fc = cfg.features
    app = Application(cfg)
    wh, bus, engine = app.warehouse, app.bus, app.engine
    if args.synthetic_days:
        for topic, msg in synthetic_session_messages(
                fc, SyntheticMarketConfig(seed=args.seed,
                                          n_days=args.synthetic_days)):
            bus.publish(topic, msg)
        engine.step()
    elif args.replay:
        ticks = _replay_session(args, cfg, bus)
        print(f"replayed {ticks} session tick(s)", file=sys.stderr)
        if ticks == 0:
            print("0 ticks replayed — check --replay-start against the "
                  "recording's market-calendar date", file=sys.stderr)
            return 2
        engine.step()
    else:
        print("pass --synthetic-days or --replay (a RecordingTransport "
              "fixture file); live ingestion attaches a SessionDriver via "
              "the Application API (docs/OPERATIONS.md §2)", file=sys.stderr)
        return 2
    print(f"warehouse {args.warehouse}: {len(wh)} rows; engine {engine.stats}")
    return 0


def _replay_session(args, cfg, bus) -> int:
    """Re-run a recorded session (RecordingTransport file) through the real
    acquisition layer: same clients/scrapers, responses served back in
    recorded order, clock simulated at the configured cadence."""
    import datetime as dt

    from fmda_tpu.ingest import (
        AlphaVantageClient, COTScraper, EconomicCalendarScraper, IEXClient,
        RecordingTransport, SessionDriver, SessionReplayTransport,
        TradierCalendarClient, VIXScraper,
    )

    transport = SessionReplayTransport(
        RecordingTransport.load_fixtures(args.replay))
    clock = {"now": dt.datetime.strptime(
        args.replay_start, "%Y-%m-%d %H:%M:%S")}

    def now_fn():
        return clock["now"]

    def fast_sleep(s):
        clock["now"] += dt.timedelta(seconds=s)

    sc = cfg.session
    driver = SessionDriver(
        bus, sc,
        iex=IEXClient("replay", transport),
        alpha_vantage=AlphaVantageClient("replay", transport),
        calendar=TradierCalendarClient("replay", transport),
        indicator_scraper=EconomicCalendarScraper(
            cfg.features, transport=transport),
        vix_scraper=VIXScraper(transport),
        cot_scraper=COTScraper(sc.cot_subject, transport),
        now_fn=now_fn, sleep_fn=fast_sleep,
    )
    ticks = driver.run_session(max_ticks=args.ticks or None)
    if transport.misses:
        # the replay ran under a config whose feeds/cadence differ from
        # the recording — the per-feed warnings above say which ticks,
        # this says which endpoints
        print("recording has no responses for: "
              + ", ".join(sorted(set(transport.misses))), file=sys.stderr)
    return ticks


def _save_quality_profile(wh, cfg, ckpt, *, max_rows: int = 4096) -> None:
    """Persist the training-time reference profile beside the checkpoint
    so the live drift monitor (fmda_tpu.obs.quality) has a baseline to
    PSI-score production traffic against.  Best-effort: a profile that
    cannot be built (degenerate data) must not fail training."""
    from fmda_tpu.eval.drift import (
        build_profile, profile_path_for, save_profile)

    try:
        n = len(wh)
        ids = list(range(max(1, n - max_rows + 1), n + 1))
        rows = wh.fetch(ids)
        targets = wh.fetch_targets(ids) if n > cfg.features.max_lead else None
        profile = build_profile(
            rows, targets, bins=cfg.quality.drift_bins,
            columns=list(wh.x_fields))
        path = save_profile(profile_path_for(ckpt), profile)
        print(f"drift reference profile: {path}")
    except (ValueError, IndexError, OSError) as e:
        print(f"drift reference profile not written: {e}", file=sys.stderr)


def _train(wh, cfg, *, epochs, batch_size, checkpoint_dir, seed):
    """Shared by ``train`` and ``demo``; returns the checkpoint path, or
    None (after printing why) when training cannot run."""
    import dataclasses

    import jax

    from fmda_tpu.train import Trainer, save_checkpoint
    from fmda_tpu.train.trainer import imbalance_weights_from_source

    if len(wh) == 0:
        print("warehouse is empty — run ingest first", file=sys.stderr)
        return None
    fc = cfg.features
    model_cfg = dataclasses.replace(cfg.model, n_features=len(wh.x_fields))
    # explicitly-passed CLI flags override the config file; absent flags
    # (None) leave the config's values in force
    overrides = {k: v for k, v in
                 dict(batch_size=batch_size, epochs=epochs, seed=seed).items()
                 if v is not None}
    train_cfg = dataclasses.replace(cfg.train, **overrides)
    weight, pos_weight = imbalance_weights_from_source(wh)
    trainer = Trainer(model_cfg, train_cfg, weight=weight,
                      pos_weight=pos_weight)
    state, history, dataset = trainer.fit(
        wh, bid_levels=fc.bid_levels, ask_levels=fc.ask_levels)
    ckpt = save_checkpoint(checkpoint_dir, state, dataset.final_norm_params)
    _save_quality_profile(wh, cfg, ckpt)
    last = history["train"][-1]
    print(f"trained {len(history['train'])} epochs: "
          f"loss={last.loss:.4f} acc={last.accuracy:.4f} "
          f"(backend={jax.default_backend()})")
    print(f"checkpoint: {ckpt}")
    return ckpt


def _continuous_train(wh, cfg, *, checkpoint_dir, max_rounds, seed):
    """``train --continuous``: the standalone continuous fine-tuning
    loop — tail the warehouse, fine-tune on the sliding window, write
    versioned checkpoints (+ drift profiles).  No fleet attached here;
    ``serve-fleet --continuous-train`` is the in-process serving
    variant that also hot-swaps."""
    import dataclasses

    from fmda_tpu.train.continuous import ContinuousTrainer

    if len(wh) == 0:
        print("warehouse is empty — run ingest first", file=sys.stderr)
        return None
    fc = cfg.features
    model_cfg = dataclasses.replace(cfg.model, n_features=len(wh.x_fields))
    train_cfg = (dataclasses.replace(cfg.train, seed=seed)
                 if seed is not None else cfg.train)
    ct = ContinuousTrainer(
        wh, model_cfg, train_cfg,
        checkpoint_dir=checkpoint_dir,
        bid_levels=fc.bid_levels, ask_levels=fc.ask_levels,
        drift_bins=cfg.quality.drift_bins, target_lead=fc.max_lead,
    )
    out = ct.run(max_rounds=max_rounds)
    print(f"continuous train: {out['rounds']} round(s), "
          f"{out['rows_seen']} rows seen, "
          f"{len(out['checkpoints'])} checkpoint(s), "
          f"recompiles={out['trainer_unexpected_recompiles']}")
    for ckpt in out["checkpoints"]:
        print(f"checkpoint: {ckpt}")
    return out


def cmd_train(args) -> int:
    _ensure_backend(args)
    cfg = _config(args)
    if args.continuous:
        out = _continuous_train(
            _warehouse(args.warehouse, cfg), cfg,
            checkpoint_dir=_ckpt_dir(args, cfg),
            max_rounds=args.max_rounds, seed=args.seed,
        )
        return 0 if out and out["rounds"] > 0 else 2
    ckpt = _train(
        _warehouse(args.warehouse, cfg), cfg, epochs=args.epochs,
        batch_size=args.batch_size, checkpoint_dir=_ckpt_dir(args, cfg),
        seed=args.seed,
    )
    return 0 if ckpt else 2


def _backtest(wh, cfg, ckpt: str, *, window: int, threshold: float) -> int:
    import dataclasses

    from fmda_tpu.serve import backtest_from_checkpoint, trading_summary

    result = backtest_from_checkpoint(
        wh, ckpt, dataclasses.replace(cfg.model, n_features=len(wh.x_fields)),
        window=window, threshold=threshold)
    m = result.metrics
    print(f"backtest over {len(result.probabilities)} rows: "
          f"accuracy={float(m.accuracy):.3f} hamming={float(m.hamming):.3f}")
    print(f"{'label':>8} {'signals':>8} {'hits':>6} {'precision':>10} "
          f"{'recall':>7} {'edge':>7}")
    for label, s in trading_summary(result).items():
        print(f"{label:>8} {s.signals:>8} {s.hits:>6} {s.precision:>10.3f} "
              f"{s.recall:>7.3f} {s.edge:>+7.3f}")
    return 0


def cmd_backtest(args) -> int:
    _ensure_backend(args)
    from fmda_tpu.train.checkpoint import latest_checkpoint

    cfg = _config(args)
    ckpt = args.checkpoint or latest_checkpoint(_ckpt_dir(args, cfg))
    if ckpt is None:
        print("no checkpoint found", file=sys.stderr)
        return 2
    return _backtest(
        _warehouse(args.warehouse, cfg), cfg, ckpt,
        window=(args.window if args.window is not None
                else cfg.train.window),
        threshold=(args.threshold if args.threshold is not None
                   else cfg.train.prob_threshold),
    )


def cmd_serve(args) -> int:
    """Tail-follow the warehouse file: another process (ingest) appends
    rows to the same SQLite file; each new row is served through the
    push-triggered predictor (signals synthesised locally — the shared
    medium between processes is the warehouse, like the reference's
    MariaDB between Spark and predict.py, minus the sleep-15 race)."""
    _ensure_backend(args)
    import time

    import dataclasses

    from fmda_tpu.app import default_bus
    from fmda_tpu.config import TOPIC_PREDICT_TIMESTAMP
    from fmda_tpu.serve import Predictor
    from fmda_tpu.train.checkpoint import latest_checkpoint

    cfg = _config(args)
    window = args.window if args.window is not None else cfg.train.window
    threshold = (args.threshold if args.threshold is not None
                 else cfg.train.prob_threshold)
    wh = _warehouse(args.warehouse, cfg)
    ckpt = args.checkpoint or latest_checkpoint(_ckpt_dir(args, cfg))
    if ckpt is None:
        print("no checkpoint found", file=sys.stderr)
        return 2
    bus = default_bus(cfg)
    predictor = Predictor.from_checkpoint(
        ckpt, bus, wh,
        dataclasses.replace(cfg.model, n_features=len(wh.x_fields)),
        window=window, threshold=threshold,
        from_end=False, max_staleness_s=None)
    served = 0
    last_pos = window - 1 if args.from_start else len(wh)
    deadline = time.monotonic() + args.duration_s if args.duration_s else None
    while True:
        # the cursor is the last row *position* fetched (dense ordinals,
        # immune to autoincrement gaps — warehouse.timestamps_after); a
        # concurrent ingest commit between reads can only appear in the
        # NEXT poll, never twice (rows are append-only)
        new_rows = wh.timestamps_after(last_pos)
        if new_rows:
            for _, ts in new_rows:
                bus.publish(TOPIC_PREDICT_TIMESTAMP, {"Timestamp": ts})
            last_pos = new_rows[-1][0]
            for p in predictor.poll():
                served += 1
                print(json.dumps({
                    "timestamp": p.timestamp,
                    "probabilities": [
                        round(float(v), 4) for v in p.probabilities],
                    "labels": list(p.labels),
                }), flush=True)
        if args.once or (deadline is not None
                         and time.monotonic() >= deadline):
            break
        time.sleep(args.poll_interval_s)
    print(f"served {served} predictions", file=sys.stderr)
    return 0


def _fleet_worker_model(args, cfg):
    """The worker-role model stack: a randomly-initialised
    unidirectional carrier from the shared seed — deterministic, so
    every worker process of one topology serves identical params."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from fmda_tpu.models import build_model

    model_cfg = dataclasses.replace(
        cfg.model, bidirectional=False, dropout=0.0,
        hidden_size=args.hidden, n_features=cfg.features.n_features,
        cell=cfg.model.cell if cfg.model.cell != "attn" else "gru")
    window = args.window if args.window is not None else cfg.runtime.window
    params = build_model(model_cfg).init(
        {"params": jax.random.PRNGKey(args.seed)},
        jnp.zeros((1, window, model_cfg.n_features)))["params"]
    return model_cfg, params


def _fleet_wire_override(args, cfg):
    """Fold the cross-role serve-fleet switches into cfg: binary-wire
    rollback (``--wire-format`` -> [fleet]) and the carried-state cell
    family A/B knob (``--cell``, falling back to ``FMDA_FLEET_CELL`` ->
    [model] cell) — both must work from the command line alone, on
    every role, so a GRU-vs-SSM ticks/s comparison at equal --hidden
    is two invocations of the same command."""
    import dataclasses

    if getattr(args, "wire_format", None):
        cfg = dataclasses.replace(
            cfg, fleet=dataclasses.replace(
                cfg.fleet, wire_format=args.wire_format))
    cell = getattr(args, "cell", None) or os.environ.get("FMDA_FLEET_CELL")
    if cell:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, cell=cell))
    return cfg


def _fleet_runtime_overrides(args, cfg):
    """Fold the shared serve-fleet batching flags into cfg.runtime."""
    import dataclasses

    cfg = _fleet_wire_override(args, cfg)

    bucket_sizes = (tuple(int(b) for b in args.bucket_sizes.split(","))
                    if args.bucket_sizes else None)
    overrides = {
        k: v for k, v in dict(
            capacity=max(args.sessions, cfg.runtime.capacity),
            max_linger_ms=args.max_linger_ms,
            queue_bound=args.queue_bound,
            window=args.window,
            bucket_sizes=bucket_sizes,
            pipeline_depth=(0 if args.serial else None),
            slo_p99_ms=args.slo_p99_ms,
        ).items() if v is not None
    }
    return dataclasses.replace(
        cfg, runtime=dataclasses.replace(cfg.runtime, **overrides))


def _maybe_write_trace(args, out: dict) -> None:
    """Shared --trace/--trace-out tail for every serve-fleet role."""
    if not (args.trace or args.trace_out):
        return
    from fmda_tpu.obs.trace import default_tracer

    tracer = default_tracer()
    out["tracing"] = {
        "traces_finished": tracer.traces_finished,
        "spans_buffered": len(tracer.spans()),
        "e2e": tracer.e2e.summary(),
    }
    if args.trace_out:
        with open(args.trace_out, "w") as fh:
            json.dump(tracer.chrome(), fh)
        out["tracing"]["file"] = args.trace_out


def _cmd_fleet_worker(args) -> int:
    """serve-fleet --role worker: one slot-range owner in a multi-host
    topology (docs/multihost.md).  Connects a SocketBus to the router's
    bus server, joins via hello, and serves its inbox until the router
    says stop (or the --duration-s safety valve fires)."""
    if not args.worker_id or not args.connect:
        print("--role worker needs --worker-id and --connect HOST:PORT",
              file=sys.stderr)
        return 2
    _ensure_backend(args)
    cfg = _fleet_runtime_overrides(args, _config(args))
    if args.trace or args.trace_out:
        from fmda_tpu.obs.trace import configure_tracing

        configure_tracing(enabled=True, sample_rate=args.trace_sample)
    # apply [profiling] BEFORE the worker builds its pools, so the
    # precompile burst is ledger-tracked under the deployment's
    # cost-analysis setting and the host profiler (if opted in) covers
    # the whole serve
    from fmda_tpu.obs.device import configure_device_obs

    configure_device_obs(cfg.profiling)
    from fmda_tpu.config import TOPIC_FLEET_PREDICTION, fleet_worker_topic
    from fmda_tpu.fleet.wire import BusServer, SocketBus
    from fmda_tpu.fleet.worker import FleetWorker
    from fmda_tpu.obs import Observability
    from fmda_tpu.stream.bus import InProcessBus

    model_cfg, params = _fleet_worker_model(args, cfg)
    wire_format = cfg.fleet.wire_format
    bus = SocketBus.connect(args.connect, wire_format=wire_format)
    data_bus = None
    data_server = None
    data_address = None
    if not args.shared_bus:
        # worker-hosted data plane (default): this process serves its
        # own inbox + results bus; the router links to it directly, so
        # the serving hot loop never crosses a socket
        data_bus = InProcessBus(
            (fleet_worker_topic(args.worker_id), TOPIC_FLEET_PREDICTION))
        data_server = BusServer(
            data_bus, host=cfg.fleet.host,
            wire_format=wire_format).start()
        data_address = data_server.address
    # split-topology workers re-dial the control bus after a router/
    # broker restart (the data plane is local, serving never stops);
    # shared-bus workers exit cleanly after the grace instead — their
    # whole transport is the one broker
    reconnect = (None if args.shared_bus
                 else (lambda: SocketBus.connect(
                     args.connect, wire_format=wire_format)))
    qos = None
    if cfg.control.enabled and cfg.control.tenant_classes:
        from fmda_tpu.control.qos import QosPolicy

        qos = QosPolicy.from_config(cfg.control)
    worker = FleetWorker(
        args.worker_id, bus, model_cfg, params,
        config=cfg.fleet, runtime=cfg.runtime, capacity=args.sessions,
        data_bus=data_bus, data_address=data_address,
        reconnect_fn=reconnect, qos=qos)
    # per-process observability: every series this worker exports
    # carries a `process` label, so a fleet-wide scrape never collides
    obs = Observability(cfg.observability, process=args.worker_id)
    obs.track_fleet(worker.gateway)
    bus.bind_metrics(obs.registry)
    if args.metrics_port is not None:
        server = obs.start_server(port=args.metrics_port)
        # announce the scrape endpoint in every liveness message: the
        # router's fleet aggregator (fmda_tpu.obs.aggregate) scrapes
        # exactly the addresses heartbeats carry
        worker.heartbeater.announce["metrics"] = server.url
        print(f"worker {args.worker_id} metrics: {server.url}/metrics",
              file=sys.stderr)
    try:
        stats = worker.run(
            duration_s=args.duration_s if args.duration_s else None)
    finally:
        obs.close()
        if data_server is not None:
            data_server.stop()
        bus.close()
    out = {"worker": args.worker_id, "stats": stats,
           **worker.metrics.summary()}
    _maybe_write_trace(args, out)
    print(json.dumps(out, indent=2))
    return 0


def _cmd_fleet_broker(args) -> int:
    """serve-fleet --role broker: host the topology's bus + bus server
    and nothing else — the local stand-in for a Kafka broker.  The
    router, workers, and loadgen each keep their own process (and GIL);
    every bus op crosses a socket to here.  Runs until killed or
    --duration-s elapses."""
    import time

    from fmda_tpu.config import DEFAULT_TOPICS, fleet_topics
    from fmda_tpu.fleet.launcher import _build_local_bus
    from fmda_tpu.fleet.wire import BusServer

    # the broker is one connection-serving thread per client, all doing
    # short JSON/frame work: the default 5ms GIL switch interval turns
    # every request into multi-ms queueing delay under concurrency —
    # drop it so round-trip latency tracks actual work
    sys.setswitchinterval(0.0005)
    cfg = _fleet_wire_override(args, _config(args))
    n = args.workers if args.workers is not None else cfg.fleet.n_workers
    worker_ids = [f"{cfg.fleet.worker_prefix}{i}" for i in range(n)]
    topics = tuple(DEFAULT_TOPICS) + fleet_topics(worker_ids)
    bus = _build_local_bus(cfg, topics)
    port = args.listen if args.listen is not None else cfg.fleet.port
    server = BusServer(bus, host=cfg.fleet.host, port=port,
                       wire_format=cfg.fleet.wire_format).start()
    # the one line launchers parse to find the ephemeral port
    print(f"BROKER {server.address}", flush=True)
    deadline = (time.monotonic() + args.duration_s
                if args.duration_s else None)
    try:
        while deadline is None or time.monotonic() < deadline:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


def _fleet_telemetry(args, cfg):
    """Router-side fleet telemetry (store + aggregator + SLO engine +
    flight recorder — fmda_tpu.obs.aggregate) for --role router/local,
    or None when the ``[slo]`` section disables it.  ``--postmortem-dir``
    overrides the config so the flight recorder works from the command
    line alone."""
    if not cfg.slo.enabled:
        return None
    import dataclasses

    from fmda_tpu.obs.aggregate import FleetTelemetry

    slo_cfg = cfg.slo
    postmortem = getattr(args, "postmortem_dir", None)
    if postmortem:
        slo_cfg = dataclasses.replace(slo_cfg, postmortem_dir=postmortem)
    return FleetTelemetry(slo_cfg)


def _control_plane(args, cfg, telemetry, *, router=None, actuator=None,
                   initial_linger_ms=None, bucket_sizes=None):
    """The adaptive control plane for --role router/local (fmda_tpu
    .control; docs/control.md): on whenever fleet telemetry is — the
    loops read its signals — unless the ``[control]`` section or
    ``--no-controller`` opts out.  Attached to the telemetry so its
    decision ring serves on ``/control``."""
    if telemetry is None or not cfg.control.enabled:
        return None
    if getattr(args, "no_controller", False):
        return None
    from fmda_tpu.control import ControlPlane

    plane = ControlPlane(
        cfg.control, telemetry=telemetry, router=router,
        actuator=actuator, slo_cfg=cfg.slo,
        initial_linger_ms=(initial_linger_ms if initial_linger_ms
                           is not None else cfg.runtime.max_linger_ms),
        bucket_sizes=tuple(bucket_sizes if bucket_sizes is not None
                           else cfg.runtime.bucket_sizes))
    telemetry.attach_controller(plane)
    return plane


def _tenant_mix(args):
    """Parse ``--tenant-mix gold:1,standard:4`` into the loadgen's
    parallel (classes, weights) tuples; ((), ()) when unset."""
    spec = getattr(args, "tenant_mix", None)
    if not spec:
        return (), ()
    classes, weights = [], []
    for part in spec.split(","):
        name, _, w = part.partition(":")
        if not name.strip():
            raise SystemExit(f"bad --tenant-mix entry: {part!r}")
        classes.append(name.strip())
        try:
            weights.append(float(w) if w else 1.0)
        except ValueError:
            raise SystemExit(
                f"bad --tenant-mix weight in {part!r} "
                "(want CLASS or CLASS:WEIGHT)") from None
    return tuple(classes), tuple(weights)


def _cmd_fleet_router(args) -> int:
    """serve-fleet --role router: the routing/membership/migration
    control loop on a bus-only host (no jax on this code path).  With
    ``--connect`` it joins an existing broker's bus (the production
    shape: broker, router, and workers each their own process/host);
    with ``--listen`` it hosts the bus + bus server itself (a two-tier
    topology for small fleets)."""
    import time

    from fmda_tpu.fleet.router import FleetRouter

    cfg = _fleet_wire_override(args, _config(args))
    if args.trace or args.trace_out:
        from fmda_tpu.obs.trace import configure_tracing

        configure_tracing(enabled=True, sample_rate=args.trace_sample)
    server = None
    if args.connect:
        from fmda_tpu.fleet.wire import SocketBus

        bus = SocketBus.connect(
            args.connect, wire_format=cfg.fleet.wire_format)
        fleet_cfg = cfg.fleet
    else:
        import dataclasses

        from fmda_tpu.config import DEFAULT_TOPICS, fleet_topics
        from fmda_tpu.fleet.launcher import _build_local_bus
        from fmda_tpu.fleet.wire import BusServer

        n = (args.workers if args.workers is not None
             else cfg.fleet.n_workers)
        worker_ids = [f"{cfg.fleet.worker_prefix}{i}" for i in range(n)]
        topics = tuple(DEFAULT_TOPICS) + fleet_topics(worker_ids)
        bus = _build_local_bus(cfg, topics)
        fleet_cfg = dataclasses.replace(
            cfg.fleet,
            port=args.listen if args.listen is not None
            else cfg.fleet.port)
        server = BusServer(bus, host=fleet_cfg.host,
                           port=fleet_cfg.port,
                           wire_format=fleet_cfg.wire_format).start()
        print(f"router bus server on {server.address}; start workers "
              f"with: python -m fmda_tpu serve-fleet --role worker "
              f"--connect {server.address} --worker-id w<N>",
              file=sys.stderr)
    router = FleetRouter(bus, fleet_cfg, n_features=cfg.features.n_features)
    telemetry = _fleet_telemetry(args, cfg)
    plane = _control_plane(args, cfg, telemetry, router=router)
    tele_server = None
    if telemetry is not None and args.metrics_port is not None:
        # the router's OWN scrape surface: fleet-level series
        # (/query?series=&window=), the SLO alert document (/alerts),
        # and an SLO-aware /healthz `status --endpoint` exits 1 on
        tele_server = telemetry.start_server(port=args.metrics_port)
        print(f"router telemetry: {tele_server.url}/metrics "
              f"(query, alerts, healthz)", file=sys.stderr)
    deadline = (time.monotonic() + args.duration_s
                if args.duration_s else None)
    try:
        while deadline is None or time.monotonic() < deadline:
            router.pump()
            if telemetry is not None:
                # cadence-gated fold (one clock read when not due) —
                # aggregation stays off the routing hot path
                telemetry.maybe_collect(router)
            if plane is not None:
                plane.maybe_tick()
            time.sleep(0.005)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop_workers()
        # keep pumping briefly so the workers' drain + goodbye (final
        # stats) make it into the printed summary — stop_workers only
        # SENDS the stop; the goodbyes land on the control topic after
        # the workers drain (LocalFleet.shutdown does the same)
        grace = time.monotonic() + 5.0
        try:
            while router.membership.workers and time.monotonic() < grace:
                router.pump()
                time.sleep(0.02)
        except (ConnectionError, OSError):
            pass
        if telemetry is not None:
            telemetry.close()
        if tele_server is not None:
            tele_server.stop()
        if server is not None:
            server.stop()
    out = router.summary()
    out["n_features"] = router.n_features
    if telemetry is not None:
        out["alerts"] = telemetry.alerts()["firing"]
    if plane is not None:
        out["control"] = plane.status()
    _maybe_write_trace(args, out)
    print(json.dumps(out, indent=2, default=str))
    return 0


def _cmd_fleet_chaos(args, cfg) -> int:
    """serve-fleet --role local --chaos-plan: run the chaos soak — the
    full topology under a fault plan (kill/revive workers, router
    takeover, bus blips, link partitions), hard-gating the never-abort
    contract (docs/chaos.md).  Exit 1 iff a gate fails."""
    from fmda_tpu.chaos.plan import FaultPlan, plan_from_config
    from fmda_tpu.chaos.soak import run_chaos_soak

    n = args.workers if args.workers is not None else cfg.fleet.n_workers
    worker_ids = [f"{cfg.fleet.worker_prefix}{i}" for i in range(n)]
    if args.chaos_plan == "generate":
        plan = plan_from_config(
            cfg.chaos, worker_ids, n_steps=args.ticks)
    else:
        plan = FaultPlan.load(args.chaos_plan)
    out = run_chaos_soak(
        plan,
        n_workers=n,
        n_sessions=args.sessions,
        hidden=args.hidden,
        seed=args.seed,
        duty=args.duty,
        slow_fraction=args.slow_fraction,
        slow_duty=args.slow_duty,
        burst_every=args.burst_every,
        compare_unfaulted=not args.chaos_no_reference,
        config=cfg,
    )
    print(json.dumps(out, indent=2, default=str))
    return 0 if out["gates_ok"] else 1


def cmd_chaos_pipeline(args) -> int:
    """chaos-pipeline: the data-plane chaos soak — synthetic feeds →
    join engine → journaled warehouse → predictor, in-process, under a
    seeded fault plan (feed outage, warehouse outage, engine kill),
    hard-gating the never-abort contract for the whole pipeline
    (docs/chaos.md "Data-plane faults").  Exit 1 iff a gate fails."""
    from fmda_tpu.chaos.pipeline import (
        generate_pipeline_plan,
        run_pipeline_soak,
    )
    from fmda_tpu.chaos.plan import FaultPlan

    cfg = _config(args)
    cc = cfg.chaos
    seed = args.seed if args.seed is not None else cc.seed
    if args.plan:
        plan = FaultPlan.load(args.plan)
    else:
        plan = generate_pipeline_plan(
            seed, args.rounds,
            feed_outages=cc.feed_outages,
            feed_outage_steps=cc.feed_outage_steps,
            warehouse_outages=cc.warehouse_outages,
            warehouse_outage_steps=cc.warehouse_outage_steps,
            engine_kills=cc.engine_kills,
            engine_kill_steps=cc.engine_kill_steps,
            settle_steps=cc.settle_steps)
    out = run_pipeline_soak(
        plan,
        seed=seed,
        rounds=args.rounds,
        predictor=not args.no_predictor,
        compare_unfaulted=not args.no_reference,
    )
    print(json.dumps(out, indent=2, default=str))
    return 0 if out["gates_ok"] else 1


def _replay_width(cfg) -> int:
    """The feature width a replay run actually serves: a
    warehouse-source backfill streams the RAW landed table
    (``table_columns()`` wide, docs/replay.md), not the derived
    x_fields view — the serving model must be sized to the rows it
    will see."""
    if cfg.replay.source == "warehouse":
        return len(cfg.features.table_columns())
    return cfg.features.n_features


def _replay_swap_params(args, cfg):
    """The --hot-swap checkpoint: the worker-model stack re-initialised
    from a shifted seed — same tree structure and leaf shapes (a hot
    swap must not change the compiled program), observably different
    weights (post-swap probes prove the new checkpoint serves)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from fmda_tpu.models import build_model

    model_cfg = dataclasses.replace(
        cfg.model, bidirectional=False, dropout=0.0,
        hidden_size=args.hidden, n_features=_replay_width(cfg),
        cell=cfg.model.cell if cfg.model.cell != "attn" else "gru")
    window = args.window if args.window is not None else cfg.runtime.window
    return build_model(model_cfg).init(
        {"params": jax.random.PRNGKey(args.seed + 1)},
        jnp.zeros((1, window, model_cfg.n_features)))["params"]


def _run_replay(target, cfg, args, *, warehouse=None, swap_params=None,
                is_router=False, extra_on_round=None):
    """The --replay load: a max-speed virtual-clock backfill through
    the target's unmodified submit/pump surface (fmda_tpu.replay;
    docs/replay.md) instead of the cadence-shaped synthetic load.  With
    ``swap_params`` the checkpoint lands halfway through the backfill —
    straight into a solo gateway, or broadcast to every live worker
    through the router — without dropping a session."""
    from fmda_tpu.replay import (
        ReplayDriver, SyntheticHistory, WarehouseHistory,
    )

    rc = cfg.replay
    n_features = _replay_width(cfg)
    if rc.source == "warehouse":
        if warehouse is None:
            from fmda_tpu.stream.warehouse import Warehouse

            warehouse = Warehouse(cfg.features, cfg.warehouse)
        source = WarehouseHistory(
            warehouse, rc.n_tickers, n_features=n_features,
            start_ts=rc.start_ts, end_ts=rc.end_ts, chunk=rc.chunk)
    else:
        source = SyntheticHistory(
            rc.n_tickers, rc.n_rounds, n_features,
            seed=rc.seed, duty=rc.duty, step_s=rc.step_s)
    quality = None
    if cfg.quality.enabled and rc.source == "warehouse":
        # warehoused backfills have joinable labels: ride the replay
        # through the label-join evaluator so the run reports live
        # per-version quality alongside throughput
        from fmda_tpu.obs.quality import QualityEvaluator

        quality = QualityEvaluator(
            cfg.quality, warehouse=warehouse,
            max_lead=cfg.features.max_lead)
    # halfway for the synthetic source; best effort for a warehouse
    # backfill (its round count is only known once the rows stream)
    swap_at = max(1, rc.n_rounds // 2)
    tenant_classes, tenant_weights = _tenant_mix(args)
    swapped: dict = {}

    def on_round(r):
        if swap_params is not None and not swapped and r + 1 >= swap_at:
            if is_router:
                told = target.broadcast_hot_swap(swap_params)
                swapped.update({"round": r + 1, "workers_told": told})
            else:
                version = target.hot_swap(swap_params)
                swapped.update({"round": r + 1,
                                "weights_version": version})
        if extra_on_round is not None:
            extra_on_round(r)

    driver = ReplayDriver(
        target, source,
        tenant_classes=tenant_classes, tenant_weights=tenant_weights,
        seed=rc.seed,
        # a router encodes per link itself; the dialect round-trip is
        # the solo gateway's stand-in for those bytes
        wire_dialect=(None if is_router else rc.wire_dialect),
        on_round=on_round, quality=quality)
    out = driver.run()
    out["replay"] = {"source": rc.source, "n_tickers": rc.n_tickers}
    if swapped:
        out["hot_swap"] = swapped
    if quality is not None:
        quality.join()  # final join: drain whatever already has labels
        q = quality.summary()
        out["quality"] = {
            "conservation": q["conservation"],
            "overall": q["overall"],
            "versions": q["versions"],
        }
    return out


def _cmd_fleet_local(args) -> int:
    """serve-fleet --role local: the single-command topology — spawn
    router (inline) + N worker processes, drive the synthetic fleet
    load through the router, print aggregate + per-worker stats."""
    from fmda_tpu.fleet.launcher import launch_local_fleet, spawn_supported
    from fmda_tpu.runtime.loadgen import FleetLoadConfig, run_fleet_load

    cfg = _fleet_wire_override(args, _config(args))
    if not spawn_supported():
        print(json.dumps(
            {"skipped": "subprocess spawn unavailable on this host"}))
        return 0
    if args.chaos_plan:
        return _cmd_fleet_chaos(args, cfg)
    if args.trace or args.trace_out or args.trace_dir:
        from fmda_tpu.obs.trace import configure_tracing

        configure_tracing(enabled=True, sample_rate=args.trace_sample)
    n = args.workers if args.workers is not None else cfg.fleet.n_workers
    bucket_sizes = (tuple(int(b) for b in args.bucket_sizes.split(","))
                    if args.bucket_sizes else None)
    topo = launch_local_fleet(
        n_workers=n,
        config=cfg,
        hidden=args.hidden,
        seed=args.seed,
        capacity_per_worker=args.sessions,
        bucket_sizes=bucket_sizes,
        max_linger_ms=args.max_linger_ms,
        window=args.window,
        trace_dir=args.trace_dir,
    )
    telemetry = _fleet_telemetry(args, cfg)
    plane = None
    if telemetry is not None:
        from fmda_tpu.control import LocalFleetActuator

        plane = _control_plane(
            args, cfg, telemetry, router=topo.router,
            actuator=LocalFleetActuator(topo),
            initial_linger_ms=args.max_linger_ms,
            bucket_sizes=bucket_sizes)
    tele_server = None
    if telemetry is not None and args.metrics_port is not None:
        tele_server = telemetry.start_server(port=args.metrics_port)
        print(f"fleet telemetry: {tele_server.url}/metrics "
              f"(query, alerts, healthz)", file=sys.stderr)

    def on_round(r):
        if telemetry is not None:
            telemetry.maybe_collect(topo.router)
        if plane is not None:
            plane.maybe_tick()

    tenant_classes, tenant_weights = _tenant_mix(args)
    try:
        if args.replay:
            out = _run_replay(
                topo.router, cfg, args,
                swap_params=(_replay_swap_params(args, cfg)
                             if args.hot_swap else None),
                is_router=True,
                extra_on_round=(on_round if telemetry is not None
                                or plane is not None else None))
            if args.hot_swap:
                # the router's view of who acked which version — the
                # zero-downtime proof is spread == 0 with sessions intact
                fleet = topo.router.summary()
                out.setdefault("hot_swap", {})
                out["hot_swap"]["weights_versions"] = fleet.get(
                    "weights_versions")
                out["hot_swap"]["weights_version_spread"] = fleet.get(
                    "weights_version_spread")
        else:
            out = run_fleet_load(topo.router, FleetLoadConfig(
                n_sessions=args.sessions, n_ticks=args.ticks,
                duty=args.duty, seed=args.seed,
                storm_every=args.storm_every,
                storm_fraction=args.storm_fraction,
                burst_every=args.burst_every,
                burst_rounds=args.burst_rounds,
                slow_fraction=args.slow_fraction,
                slow_duty=args.slow_duty,
                tenant_classes=tenant_classes,
                tenant_weights=tenant_weights),
                on_round=(on_round if telemetry is not None
                          or plane is not None else None))
        if telemetry is not None:
            telemetry.collect(topo.router)  # final fold before teardown
    finally:
        worker_stats = topo.shutdown()
        if telemetry is not None:
            telemetry.close()
        if tele_server is not None and args.metrics_hold_s <= 0:
            # with --metrics-hold-s the endpoint outlives the load (the
            # curl/promtool demo workflow) and stops after the hold below
            tele_server.stop()
    out["workers"] = n
    out["worker_stats"] = worker_stats
    out["table_version"] = topo.router.table.version
    if telemetry is not None:
        out["alerts"] = telemetry.alerts()["firing"]
        out["fleet"] = {
            g["name"]: g["value"] for g in telemetry.fleet_gauges()}
    if plane is not None:
        out["control"] = plane.status()
    if args.trace_dir:
        from fmda_tpu.obs.trace import default_tracer

        router_trace = os.path.join(args.trace_dir, "router.json")
        with open(router_trace, "w") as fh:
            json.dump(default_tracer().chrome(), fh)
        out["trace_dir"] = args.trace_dir
        print(f"per-process traces in {args.trace_dir}; merge with "
              f"`python -m fmda_tpu trace --merge {args.trace_dir}`",
              file=sys.stderr)
    _maybe_write_trace(args, out)
    print(json.dumps(out, indent=2, default=str))
    if tele_server is not None and args.metrics_hold_s > 0:
        # the endpoint outlives the load so an operator can curl
        # /alerts + /query against the run's final state (same contract
        # as the solo role's --metrics-hold-s)
        import time

        print(f"holding fleet telemetry endpoint for "
              f"{args.metrics_hold_s:.0f}s", file=sys.stderr)
        time.sleep(args.metrics_hold_s)
        tele_server.stop()
    return 0


def cmd_serve_fleet(args) -> int:
    """Multi-tenant serving proof: N concurrent ticker sessions through
    the dynamic micro-batching runtime (fmda_tpu.runtime; docs/runtime.md)
    against a synthetic multi-ticker load — one fused jit step per flush
    serves every active session.  Prints the runtime metrics (per-stage
    latency histograms, shed/queue counters, compiled-bucket count) as
    one JSON object.

    ``--role router|worker|local`` runs the multi-host topology instead
    (fmda_tpu.fleet; docs/multihost.md): a router fronting N worker
    processes over the cross-process bus, with session routing,
    membership, and live migration."""
    if args.replay and args.role not in ("solo", "local"):
        print("--replay drives a solo gateway or the local topology; "
              "use --role solo or --role local", file=sys.stderr)
        return 2
    if args.replay and args.role == "local" and _config(
            args).replay.source == "warehouse":
        # spawned workers size their models from the live feature
        # schema; a warehouse backfill streams raw landed rows
        # (narrower) — only the solo gateway sizes itself to them
        print("[replay] source=warehouse backfills run solo "
              "(landed-row width); drop --role local", file=sys.stderr)
        return 2
    if args.hot_swap and not args.replay:
        print("--hot-swap lands mid-backfill; it needs --replay",
              file=sys.stderr)
        return 2
    if args.replay and args.predictor:
        print("--replay serves carried-state sessions; it composes "
              "with --cell, not --predictor", file=sys.stderr)
        return 2
    if args.continuous_train and args.role != "solo":
        print("--continuous-train runs beside the solo gateway; "
              "use --role solo (fleet-wide: run `train --continuous` "
              "against the shared warehouse and let the router "
              "broadcast)", file=sys.stderr)
        return 2
    if args.continuous_train and (args.replay or args.predictor):
        print("--continuous-train is its own load shape; drop "
              "--replay/--predictor", file=sys.stderr)
        return 2
    if args.swap_guard and not args.continuous_train:
        print("--swap-guard gates --continuous-train swaps; add "
              "--continuous-train", file=sys.stderr)
        return 2
    if args.role == "worker":
        return _cmd_fleet_worker(args)
    if args.role == "broker":
        return _cmd_fleet_broker(args)
    if args.role == "router":
        return _cmd_fleet_router(args)
    if args.role == "local":
        return _cmd_fleet_local(args)
    _ensure_backend(args)
    import dataclasses

    import jax

    from fmda_tpu.app import Application
    from fmda_tpu.runtime import FleetLoadConfig, run_fleet_load

    cfg = _fleet_wire_override(args, _config(args))
    bucket_sizes = (tuple(int(b) for b in args.bucket_sizes.split(","))
                    if args.bucket_sizes else None)
    if args.predictor:
        # the window-re-scan Predictor path: the batching knobs land on
        # the predictor_* half of RuntimeConfig
        overrides = {
            k: v for k, v in dict(
                predictor_max_linger_ms=args.max_linger_ms,
                predictor_queue_bound=args.queue_bound,
                predictor_window=args.window,
                predictor_bucket_sizes=bucket_sizes,
                predictor_ring=(True if args.ring else None),
                pipeline_depth=(0 if args.serial else None),
                slo_p99_ms=args.slo_p99_ms,
            ).items() if v is not None
        }
    else:
        overrides = {
            k: v for k, v in dict(
                capacity=max(args.sessions, cfg.runtime.capacity,
                             cfg.replay.n_tickers if args.replay else 0),
                max_linger_ms=args.max_linger_ms,
                queue_bound=args.queue_bound,
                window=args.window,
                bucket_sizes=bucket_sizes,
                pipeline_depth=(0 if args.serial else None),
                shard_pool=args.shard_pool,
                slo_p99_ms=args.slo_p99_ms,
            ).items() if v is not None
        }
    cfg = dataclasses.replace(
        cfg, runtime=dataclasses.replace(cfg.runtime, **overrides))
    if args.trace or args.trace_out:
        # enable BEFORE the Application builds, so every captured
        # default-tracer handle (bus, gateway) sees the switch
        from fmda_tpu.obs.trace import configure_tracing

        configure_tracing(enabled=True, sample_rate=args.trace_sample)
    # [profiling] applies before any pool compiles (ledger settings,
    # memory cadence, optional continuous host profiler)
    from fmda_tpu.obs.device import configure_device_obs

    configure_device_obs(cfg.profiling)

    from fmda_tpu.models import build_model
    import jax.numpy as jnp

    if args.predictor:
        # batched-Predictor proof run: synthetic corpus warehouse, a
        # randomly-initialised flagship bidirectional model (the serving
        # math is checkpoint-independent), every servable timestamp
        # signalled in bursts through the PredictorGateway
        from fmda_tpu.data.normalize import NormParams
        from fmda_tpu.data.synthetic import (
            SyntheticMarketConfig, build_corpus,
        )
        from fmda_tpu.runtime import PredictorLoadConfig, run_predictor_load
        import numpy as np

        wh, _ = build_corpus(
            cfg.features,
            SyntheticMarketConfig(seed=args.seed,
                                  n_days=args.predictor_days))
        app = Application(cfg, warehouse=wh)
        model_cfg = dataclasses.replace(
            cfg.model, dropout=0.0, hidden_size=args.hidden,
            n_features=len(wh.x_fields))
        window = (cfg.runtime.predictor_window
                  if cfg.runtime.predictor_window is not None
                  else cfg.runtime.window)
        params = build_model(model_cfg).init(
            {"params": jax.random.PRNGKey(args.seed)},
            jnp.zeros((1, window, model_cfg.n_features)))["params"]
        norm = NormParams(
            np.zeros(model_cfg.n_features, np.float32),
            np.ones(model_cfg.n_features, np.float32))
        gateway = app.attach_predictor_fleet(
            model_cfg, params, norm, max_staleness_s=None)
        timestamps = wh.timestamps()[window - 1:]
        load_cfg = PredictorLoadConfig(
            n_signals=args.signals, burst=args.burst)

        def run_load():
            return run_predictor_load(gateway, timestamps, load_cfg)
    else:
        if args.continuous_train:
            # the continuous-train proof run tails a real warehouse:
            # build the synthetic corpus through the production
            # streaming stack and size the serving model to its joined
            # feature width (the trainer must train the SAME param tree
            # the pool serves, or the hot swap would rebind wrong)
            from fmda_tpu.data.synthetic import (
                SyntheticMarketConfig, build_corpus,
            )

            wh, _ = build_corpus(
                cfg.features,
                SyntheticMarketConfig(seed=args.seed,
                                      n_days=args.continuous_days))
            app = Application(cfg, warehouse=wh)
        else:
            app = Application(cfg)

        # synthetic proof run: a randomly-initialised unidirectional
        # carrier (the serving math is checkpoint-independent; --hidden
        # sizes it)
        model_cfg = dataclasses.replace(
            cfg.model, bidirectional=False, dropout=0.0,
            hidden_size=args.hidden,
            n_features=(len(app.warehouse.x_fields)
                        if args.continuous_train
                        else _replay_width(cfg) if args.replay
                        else cfg.features.n_features),
            cell=cfg.model.cell if cfg.model.cell != "attn" else "gru")
        model = build_model(model_cfg)

        params = model.init(
            {"params": jax.random.PRNGKey(args.seed)},
            jnp.zeros((1, cfg.runtime.window,
                       model_cfg.n_features)))["params"]

        gateway = app.attach_fleet(model_cfg, params)
        if args.replay:
            swap_params = (_replay_swap_params(args, cfg)
                           if args.hot_swap else None)

            def run_load():
                return _run_replay(gateway, cfg, args,
                                   warehouse=app.warehouse,
                                   swap_params=swap_params)
        else:
            load_cfg = FleetLoadConfig(
                n_sessions=args.sessions,
                n_ticks=args.ticks, duty=args.duty, seed=args.seed,
                storm_every=args.storm_every,
                storm_fraction=args.storm_fraction,
                burst_every=args.burst_every,
                burst_rounds=args.burst_rounds,
                slow_fraction=args.slow_fraction,
                slow_duty=args.slow_duty)

            def run_load():
                return run_fleet_load(gateway, load_cfg)
    continuous = None
    continuous_thread = None
    if args.continuous_train:
        # the trainer tails the corpus warehouse beside the serving
        # load; every accepted round hot-swaps the live pool (host-side
        # rebind — serving never recompiles; docs/training.md)
        import threading

        from fmda_tpu.train.continuous import (
            ContinuousTrainer, gateway_publisher)

        require_eval = None
        if args.swap_guard:
            from fmda_tpu.eval.shadow import ShadowEvaluator

            require_eval = ShadowEvaluator(
                params, model_config=model_cfg, warehouse=app.warehouse,
                quality_config=cfg.quality, max_lead=cfg.features.max_lead,
                window=cfg.runtime.window,
                # the model is sized to the joined x_fields view; the
                # shadow replay streams raw landed chunks and must map
                # them through the derived views
                row_transform=app.warehouse.joined_row_transform)
        continuous = ContinuousTrainer(
            app.warehouse, model_cfg, cfg.train,
            checkpoint_dir=(args.train_checkpoint_dir
                            or cfg.train.checkpoint_dir),
            publish=gateway_publisher(gateway, require_eval=require_eval),
            bid_levels=cfg.features.bid_levels,
            ask_levels=cfg.features.ask_levels,
            drift_bins=cfg.quality.drift_bins,
            target_lead=cfg.features.max_lead)
        continuous_thread = threading.Thread(
            target=lambda: continuous.run(max_rounds=args.train_rounds),
            daemon=True, name="fmda-continuous-train")
        continuous_thread.start()
    if args.metrics_port is not None:
        server = app.observability.start_server(port=args.metrics_port)
        print(f"metrics endpoint: {server.url}/metrics "
              f"(healthz, snapshot, events, trace)", file=sys.stderr)
    if args.jax_profile:
        # device-side work joins the host spans: a TensorBoard/XProf
        # capture of the whole load; carried-state pool flushes are
        # annotated as numbered StepTraceAnnotation steps
        from fmda_tpu.utils.tracing import device_trace

        if not args.predictor:
            gateway.annotate_device_steps = True
        with device_trace(args.jax_profile):
            out = run_load()
        print(f"jax profile captured to {args.jax_profile} "
              f"(tensorboard --logdir)", file=sys.stderr)
    else:
        out = run_load()
    if args.predictor:
        out["ring"] = gateway.pool.use_ring
    else:
        out["cell"] = model_cfg.cell
    if continuous is not None:
        # let the tail quiesce on its own (bounded follow: at most
        # continuous_follow_polls empty polls) so the backlog's drain
        # round lands; stop() is the backstop, not the happy path
        continuous_thread.join(timeout=120.0)
        if continuous_thread.is_alive():
            continuous.stop()
            continuous_thread.join(timeout=120.0)
        summary = continuous.summary()
        summary["weights_version"] = gateway.weights_version
        summary["pool_compile_count"] = gateway.pool.compile_count
        out["continuous_train"] = summary
    out["backend"] = jax.default_backend()
    if args.trace or args.trace_out:
        from fmda_tpu.obs.trace import default_tracer

        tracer = default_tracer()
        out["tracing"] = {
            "traces_finished": tracer.traces_finished,
            "spans_buffered": len(tracer.spans()),
            "e2e": tracer.e2e.summary(),
        }
        if args.trace_out:
            with open(args.trace_out, "w") as fh:
                json.dump(tracer.chrome(), fh)
            out["tracing"]["file"] = args.trace_out
            print(f"perfetto trace written to {args.trace_out} "
                  f"(load at https://ui.perfetto.dev, or "
                  f"`python -m fmda_tpu trace --input {args.trace_out}`)",
                  file=sys.stderr)
    slo_ok = True
    # args.slo_p99_ms already merged into cfg.runtime via `overrides`
    slo_ms = cfg.runtime.slo_p99_ms
    if slo_ms is not None:
        p99 = out.get("latency", {}).get("total", {}).get("p99_ms")
        slo_ok = p99 is not None and p99 <= slo_ms
        out["slo"] = {
            "p99_ms_bound": slo_ms,
            "p99_ms": p99,
            "ok": slo_ok,
            "soft": bool(args.slo_soft),
        }
    print(json.dumps(out, indent=2))
    if args.metrics_port is not None and args.metrics_hold_s > 0:
        # keep the endpoint scrapeable after the load (curl/promtool
        # demos; the load itself is finite) — BEFORE the SLO verdict
        # exits, so a violating run's histograms stay inspectable
        import time

        print(f"holding metrics endpoint for {args.metrics_hold_s:.0f}s",
              file=sys.stderr)
        time.sleep(args.metrics_hold_s)
    if slo_ms is not None and not slo_ok and not args.slo_soft:
        p99 = out["slo"]["p99_ms"]
        print("SLO gate failed: "
              + (f"total p99 {p99}ms > {slo_ms}ms bound"
                 if p99 is not None else
                 "no latency data collected (zero ticks served)")
              + " (--slo-soft reports without failing)", file=sys.stderr)
        return 1
    return 0


def _print_status(snapshot: dict, health: dict,
                  alerts: dict = None, control: dict = None) -> None:
    """Human-readable registry snapshot + health verdict (+ the SLO
    alert table when the endpoint serves ``/alerts``, + the control
    plane's loop state when it serves ``/control``)."""

    def key(s):
        labels = ",".join(f"{k}={v}" for k, v in
                          sorted(s.get("labels", {}).items()))
        return f"{s['name']}{{{labels}}}" if labels else s["name"]

    print(f"status: {health['status']}")
    for name, check in sorted(health.get("checks", {}).items()):
        mark = "ok  " if check["ok"] else "FAIL"
        print(f"  {mark} {name:<14} {check['detail']}")
    if alerts and alerts.get("alerts"):
        print(f"slo alerts (burn threshold "
              f"{alerts.get('burn_threshold')}x):")
        for name, a in sorted(alerts["alerts"].items()):
            mark = "FIRE" if a.get("state") == "firing" else "ok  "
            print(f"  {mark} {name:<16} "
                  f"fast {a.get('burn_fast', 0):>8.2f}x  "
                  f"slow {a.get('burn_slow', 0):>8.2f}x  "
                  f"{a.get('detail', '')}")
    if control and control.get("enabled"):
        _print_control(control)
    perf = _perf_summary(snapshot)
    if perf:
        _print_perf_summary(perf)
    replay = _replay_summary(snapshot)
    if replay:
        _print_replay_summary(replay)
    quality = _quality_summary(snapshot)
    if quality:
        _print_quality_summary(quality)
    for kind in ("counters", "gauges"):
        samples = sorted(snapshot.get(kind, []), key=key)
        if samples:
            print(f"{kind}:")
            for s in samples:
                v = s["value"]
                v = int(v) if float(v) == int(v) else round(float(v), 6)
                print(f"  {key(s):<52} {v}")
    hists = sorted(snapshot.get("histograms", []), key=key)
    if hists:
        print("latency:")
        print(f"  {'series':<52} {'count':>8} {'p50_ms':>9} "
              f"{'p99_ms':>9} {'mean_ms':>9}")
        for s in hists:
            n = s["count"]
            mean_ms = (s["sum_s"] / n * 1e3) if n else 0.0
            print(f"  {key(s):<52} {n:>8} {s['p50_s'] * 1e3:>9.3f} "
                  f"{s['p99_s'] * 1e3:>9.3f} {mean_ms:>9.3f}")


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0:
            return (f"{int(n)}B" if unit == "B" else f"{n:.1f}{unit}")
        n /= 1024.0
    return f"{n:.1f}TiB"


def _perf_summary(snapshot: dict) -> dict:
    """The device/compiler facts inside ``status`` (ISSUE 17): MFU,
    post-warmup recompiles, memory watermark + leak verdict.  Reads
    both vocabularies — a process registry's device collector
    (``device_mfu``, ``compile_unexpected_total``, ...) and a fleet
    telemetry's landed worker series (``worker_device_mfu``, ...) —
    and returns {} when neither is present (older endpoints)."""
    by_name: dict = {}
    for kind in ("counters", "gauges"):
        for s in snapshot.get(kind, []):
            by_name.setdefault(s["name"], []).append(float(s["value"]))

    def agg(fn, *names):
        vals = [v for n in names for v in by_name.get(n, [])]
        return fn(vals) if vals else None

    out = {}
    mfu = agg(max, "device_mfu", "worker_device_mfu")
    if mfu is not None:
        out["mfu"] = mfu
    intensity = agg(max, "device_arithmetic_intensity")
    if intensity is not None:
        out["arithmetic_intensity"] = intensity
    recompiles = agg(sum, "compile_unexpected_total",
                     "worker_recompiles_total")
    if recompiles is not None:
        out["recompiles_after_warmup"] = int(recompiles)
    compile_s = agg(sum, "compile_seconds_total",
                    "worker_compile_seconds_total")
    if compile_s is not None:
        out["compile_seconds"] = compile_s
    watermark = agg(max, "device_memory_watermark_bytes",
                    "worker_memory_watermark_bytes")
    if watermark is not None:
        out["memory_watermark_bytes"] = watermark
    leak = agg(max, "device_memory_leak_suspected",
               "worker_memory_leak_suspected")
    if leak is not None:
        out["memory_leak_suspected"] = bool(leak)
    return out


def _print_perf_summary(perf: dict) -> None:
    parts = []
    if "mfu" in perf:
        parts.append(f"mfu {perf['mfu'] * 100:.2f}%")
    if "compile_seconds" in perf:
        parts.append(f"compile {perf['compile_seconds']:.3f}s")
    if "recompiles_after_warmup" in perf:
        n = perf["recompiles_after_warmup"]
        parts.append(f"post-warmup recompiles {n}"
                     + (" !!" if n else ""))
    if "memory_watermark_bytes" in perf:
        parts.append(
            f"mem watermark {_fmt_bytes(perf['memory_watermark_bytes'])}")
    if perf.get("memory_leak_suspected"):
        parts.append("LEAK SUSPECTED")
    print("perf: " + " | ".join(parts))


def _replay_summary(snapshot: dict) -> dict:
    """The replay section of ``status`` — present only while a backfill
    is active (the driver's ``replay_active`` gauge).  Reads any prefix
    vocabulary (``runtime_``/``router_``/``worker_``), like
    :func:`_perf_summary`."""
    out: dict = {}
    for s in snapshot.get("gauges", []):
        name = s["name"]
        for base in ("replay_active", "replay_rows_per_s",
                     "replay_virtual_watermark",
                     "replay_max_ticker_lag_s"):
            if name == base or name.endswith("_" + base):
                out[base] = max(float(s["value"]), out.get(base, 0.0))
    if out.get("replay_active", 0.0) <= 0.0:
        return {}
    return out


def _print_replay_summary(replay: dict) -> None:
    from datetime import datetime, timezone

    parts = ["backfill active"]
    if "replay_rows_per_s" in replay:
        parts.append(f"{replay['replay_rows_per_s']:,.0f} rows/s")
    wm = replay.get("replay_virtual_watermark")
    if wm:
        stamp = datetime.fromtimestamp(
            wm, tz=timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
        parts.append(f"virtual watermark {stamp}")
    if "replay_max_ticker_lag_s" in replay:
        parts.append(
            f"max ticker lag {replay['replay_max_ticker_lag_s']:.0f}s")
    print("replay: " + " | ".join(parts))


def _quality_summary(snapshot: dict) -> dict:
    """The model-quality section of ``status`` — present once the
    label-join evaluator has published at least one joined window
    (docs/observability.md "Model quality")."""
    out: dict = {"versions": {}}
    for s in snapshot.get("gauges", []):
        name, labels = s["name"], s.get("labels", {})
        if name == "quality_subset_accuracy":
            v = labels.get("version", "?")
            out["versions"].setdefault(v, {})["accuracy"] = float(s["value"])
        elif name == "quality_hamming_loss":
            v = labels.get("version", "?")
            out["versions"].setdefault(v, {})["hamming"] = float(s["value"])
        elif name == "quality_pending":
            out["pending"] = float(s["value"])
        elif name == "quality_drift_score":
            out["drift"] = float(s["value"])
    for s in snapshot.get("counters", []):
        if s["name"] in ("quality_joined_total", "quality_join_expired_total",
                         "quality_captures_shed_total"):
            out[s["name"]] = out.get(s["name"], 0.0) + float(s["value"])
    if not out["versions"] and "quality_joined_total" not in out:
        return {}
    return out


def _print_quality_summary(quality: dict) -> None:
    parts = []
    joined = quality.get("quality_joined_total")
    if joined is not None:
        parts.append(f"joined {int(joined)}")
    for v, m in sorted(quality.get("versions", {}).items()):
        acc = m.get("accuracy")
        ham = m.get("hamming")
        seg = f"v{v} acc {acc:.3f}" if acc is not None else f"v{v}"
        if ham is not None:
            seg += f" hamming {ham:.3f}"
        parts.append(seg)
    if "drift" in quality:
        parts.append(f"drift psi {quality['drift']:.3f}")
    if quality.get("pending"):
        parts.append(f"pending {int(quality['pending'])}")
    expired = quality.get("quality_join_expired_total", 0.0)
    shed = quality.get("quality_captures_shed_total", 0.0)
    if expired or shed:
        parts.append(f"lost {int(expired)} expired / {int(shed)} shed")
    print("quality: " + " | ".join(parts))


def _print_control(control: dict) -> None:
    """The controller section of ``status``: loop modes + knobs, the
    per-tenant admit/shed aggregates, and the last few decisions."""
    batching = control.get("batching") or {}
    autoscale = control.get("autoscale") or {}
    line = f"control: target p99 {control.get('target_p99_ms')}ms"
    if batching:
        cap = batching.get("bucket_cap")
        line += (f" | batching {batching.get('mode')} "
                 f"linger {batching.get('linger_ms'):.2f}ms "
                 f"cap {'-' if cap is None else cap}")
    if autoscale:
        line += (f" | autoscale {autoscale.get('mode')} "
                 f"workers {autoscale.get('workers')} "
                 f"[{autoscale.get('min_workers')}.."
                 f"{autoscale.get('max_workers')}]")
    print(line)
    tenants = control.get("tenants") or {}
    if tenants:
        print("  tenants:")
        for name, v in sorted(tenants.items()):
            print(f"    {name:<36} {v}")
    decisions = control.get("decisions") or []
    if decisions:
        print(f"  decisions (last {min(len(decisions), 5)}):")
        for d in decisions[-5:]:
            extra = (f"worker {d.get('worker')}"
                     if d.get("loop") == "autoscale"
                     else f"linger {d.get('linger_ms')}ms "
                          f"cap {d.get('bucket_cap')}")
            print(f"    t+{d.get('t', 0):.1f}s {d.get('loop'):<9} "
                  f"{d.get('action'):<12} {extra}")


def _scrape_endpoint(endpoint: str):
    """GET /snapshot + /healthz (+ /alerts and /control, absent on
    older endpoints) off one endpoint; raises on transport failure
    (callers decide whether one dead worker fails the probe)."""
    import urllib.error
    import urllib.request

    base = (endpoint if "://" in endpoint
            else f"http://{endpoint}").rstrip("/")
    with urllib.request.urlopen(base + "/snapshot", timeout=10) as r:
        snapshot = json.loads(r.read())
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            health = json.loads(r.read())
    except urllib.error.HTTPError as e:
        # 503 = degraded; the body still carries the check detail
        health = json.loads(e.read())

    def _optional(path: str):
        # absent on worker endpoints (no telemetry) and on older
        # routers — the snapshot and health verdict still stand alone
        try:
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError, json.JSONDecodeError):
            return None

    return snapshot, health, _optional("/alerts"), _optional("/control")


def _status_multi(endpoints) -> int:
    """Fleet-wide status: scrape every endpoint (one per worker/router
    process), print per-process health, then the aggregate verdict.
    Exit 0 iff every endpoint answered ok; an unreachable process is a
    degraded fleet, not a CLI crash."""
    import urllib.error

    per = {}
    for ep in endpoints:
        try:
            per[ep] = _scrape_endpoint(ep)
        except (urllib.error.URLError, OSError,
                json.JSONDecodeError) as e:
            per[ep] = (None, {
                "status": "unreachable",
                "checks": {},
                "error": str(e),
            }, None, None)
    n_ok = 0
    for ep, (snapshot, health, alerts, control) in per.items():
        status = health.get("status")
        print(f"===== {ep}: {status} =====")
        if status == "unreachable":
            print(f"  {health.get('error')}")
            continue
        if status == "ok":
            n_ok += 1
        _print_status(snapshot, health, alerts, control)
    aggregate = "ok" if n_ok == len(endpoints) else "degraded"
    print(f"aggregate: {aggregate} ({n_ok}/{len(endpoints)} endpoints ok)")
    return 0 if aggregate == "ok" else 1


def cmd_status(args) -> int:
    """Observability snapshot: local (build the app, sample its registry)
    or remote (GET /snapshot + /healthz + /alerts off running
    endpoints).  Several ``--endpoint`` values — one per fleet worker —
    report per-worker health plus the aggregate verdict.  ``--watch N``
    re-scrapes every N seconds, redrawing in place, until Ctrl-C (clean
    exit 0) — watching a soak without a shell loop."""
    if args.watch:
        return _status_watch(args)
    return _status_once(args)


def _status_watch(args) -> int:
    import time

    try:
        while True:
            if sys.stdout.isatty():
                # clear + home: redraw in place like `watch(1)`
                print("\x1b[2J\x1b[H", end="")
            _status_once(args)
            print(f"-- every {args.watch:g}s (Ctrl-C to exit) --",
                  flush=True)
            time.sleep(args.watch)
    except KeyboardInterrupt:
        # the operator closed the watch — a clean exit, not an error
        # (the per-refresh verdicts were already printed)
        return 0


def _status_once(args) -> int:
    alerts = None
    control = None
    if args.endpoint:
        import urllib.error

        if len(args.endpoint) > 1:
            return _status_multi(args.endpoint)
        try:
            snapshot, health, alerts, control = \
                _scrape_endpoint(args.endpoint[0])
        except (urllib.error.URLError, OSError,
                json.JSONDecodeError) as e:
            # a down daemon is the most common reason to run this probe
            # — report it cleanly, don't traceback
            print(f"cannot scrape {args.endpoint[0]}: {e}",
                  file=sys.stderr)
            return 2
    else:
        import dataclasses

        from fmda_tpu.app import Application

        cfg = _config(args)
        if args.warehouse:
            cfg = dataclasses.replace(
                cfg,
                warehouse=dataclasses.replace(
                    cfg.warehouse, path=args.warehouse),
            )
        # never bind the scrape port here: a config with
        # endpoint_enabled=true belongs to the daemon this command is
        # most likely being run to inspect (use --endpoint for that)
        cfg = dataclasses.replace(
            cfg,
            observability=dataclasses.replace(
                cfg.observability, endpoint_enabled=False),
        )
        app = Application(cfg)
        snapshot = app.observability.snapshot()
        health = app.observability.health()
    _print_status(snapshot, health, alerts, control)
    firing = bool(alerts and alerts.get("firing"))
    return 0 if health.get("status") == "ok" and not firing else 1


def cmd_trace(args) -> int:
    """Per-stage latency attribution for recorded tick traces — the
    "where did tick T spend its 38 ms" tool (docs/OPERATIONS.md §4d).
    Input is Chrome/Perfetto trace_event JSON: a ``serve-fleet
    --trace-out`` file, a running endpoint's ``/trace``, or several
    per-process files stitched by trace id (``--merge``)."""
    from fmda_tpu.obs.trace import (
        format_trace, group_chrome_traces, merge_chrome_traces,
    )

    if args.merge:
        import glob as _glob

        # each --merge arg may be a file, a directory of per-process
        # --trace-out files (a topology's --trace-dir merges in one
        # command), or a glob pattern
        paths = []
        for arg in args.merge:
            if os.path.isdir(arg):
                expanded = sorted(_glob.glob(os.path.join(arg, "*.json")))
                if not expanded:
                    print(f"no *.json trace files in directory {arg}",
                          file=sys.stderr)
                    return 2
            elif _glob.has_magic(arg):
                expanded = sorted(_glob.glob(arg))
                if not expanded:
                    print(f"glob {arg!r} matched nothing", file=sys.stderr)
                    return 2
            else:
                expanded = [arg]
            paths.extend(expanded)
        docs = []
        for path in paths:
            try:
                with open(path) as fh:
                    docs.append(json.load(fh))
            except (OSError, json.JSONDecodeError) as e:
                print(f"cannot read {path}: {e}", file=sys.stderr)
                return 2
        doc = merge_chrome_traces(docs)
        if args.out:
            try:
                with open(args.out, "w") as fh:
                    json.dump(doc, fh)
            except OSError as e:
                print(f"cannot write {args.out}: {e}", file=sys.stderr)
                return 2
            n_traces = len(group_chrome_traces(doc))
            print(f"merged {len(paths)} trace files "
                  f"({n_traces} traces) -> {args.out} "
                  "(load at https://ui.perfetto.dev)", file=sys.stderr)
            return 0
        # no --out: fall through to the attribution display over the
        # merged document (cross-process journeys group by trace id)
    elif args.endpoint:
        import urllib.error
        import urllib.request

        base = (args.endpoint if "://" in args.endpoint
                else f"http://{args.endpoint}").rstrip("/")
        try:
            with urllib.request.urlopen(base + "/trace", timeout=10) as r:
                doc = json.loads(r.read())
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            print(f"cannot scrape {base}/trace: {e}", file=sys.stderr)
            return 2
    elif args.input:
        try:
            with open(args.input) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read {args.input}: {e}", file=sys.stderr)
            return 2
    else:
        print("pass --input FILE (a serve-fleet --trace-out file), "
              "--endpoint HOST:PORT (a running /trace endpoint), or "
              "--merge FILE FILE... (stitch per-process trace files)",
              file=sys.stderr)
        return 2
    traces = group_chrome_traces(doc)
    if args.min_ms is not None:
        traces = [t for t in traces if t["e2e_ms"] >= args.min_ms]
    if args.slowest is not None:
        traces = sorted(
            traces, key=lambda t: t["e2e_ms"], reverse=True)[:args.slowest]
    else:
        traces = traces[-args.last:]
    if not traces:
        print("no traces matched (is tracing enabled and sampled?)",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(traces, indent=2))
    else:
        print("\n".join(format_trace(t) for t in traces))
    return 0


def cmd_perf(args) -> int:
    """The device/compiler performance report (docs/observability.md
    §device): compile ledger, top programs by compile time, MFU +
    roofline position, memory watermarks, kernel fallbacks, and the
    host profiler's hottest stacks.  Input is a running endpoint's
    ``/device`` (+ ``/profile``) or a saved device report — a
    flight-recorder bundle's ``device.json`` or the bench phase's
    ledger artifact."""
    profile_text = None
    if args.endpoint:
        import urllib.error
        import urllib.request

        base = (args.endpoint if "://" in args.endpoint
                else f"http://{args.endpoint}").rstrip("/")
        try:
            with urllib.request.urlopen(base + "/device", timeout=10) as r:
                doc = json.loads(r.read())
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            print(f"cannot scrape {base}/device: {e}", file=sys.stderr)
            return 2
        try:
            with urllib.request.urlopen(base + "/profile", timeout=10) as r:
                profile_text = r.read().decode("utf-8", "replace")
        except (urllib.error.URLError, OSError):
            # older endpoints / profiler not attached: the device
            # report still stands alone
            profile_text = None
    elif args.input:
        try:
            with open(args.input) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read {args.input}: {e}", file=sys.stderr)
            return 2
    else:
        print("pass --endpoint HOST:PORT (a running /device endpoint) "
              "or --input FILE (a flight-recorder bundle's device.json "
              "or a bench ledger artifact)", file=sys.stderr)
        return 2
    if args.profile:
        try:
            with open(args.profile) as fh:
                profile_text = fh.read()
        except OSError as e:
            print(f"cannot read {args.profile}: {e}", file=sys.stderr)
            return 2
    # a bare ledger dump (the bench artifact) renders like a report
    # with only the ledger section
    if "ledger" not in doc and "programs" in doc:
        doc = {"ledger": doc}
    if args.json:
        if profile_text is not None:
            doc = {**doc, "profile_folded": profile_text}
        print(json.dumps(doc, indent=2))
        return 0
    _print_perf_report(doc, profile_text, top=args.top)
    return 0


def cmd_quality(args) -> int:
    """The model-quality report (docs/observability.md "Model
    quality"): per-weights-version live accuracy/F-beta off the
    label-join evaluator, drift scores vs the training-time reference
    profile, and the capture/join conservation ledger.  Input is a
    running endpoint's ``/quality``, a flight-recorder bundle
    directory (its ``quality.json``), or the bench
    ``quality_overhead`` artifact."""
    if args.endpoint:
        import urllib.error
        import urllib.request

        base = (args.endpoint if "://" in args.endpoint
                else f"http://{args.endpoint}").rstrip("/")
        try:
            with urllib.request.urlopen(base + "/quality", timeout=10) as r:
                doc = json.loads(r.read())
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            print(f"cannot scrape {base}/quality: {e}", file=sys.stderr)
            return 2
    elif args.bundle:
        path = os.path.join(args.bundle, "quality.json")
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
    elif args.artifact:
        try:
            with open(args.artifact) as fh:
                doc = json.load(fh)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read {args.artifact}: {e}", file=sys.stderr)
            return 2
    else:
        print("pass --endpoint HOST:PORT (a running /quality endpoint), "
              "--bundle DIR (a flight-recorder postmortem bundle), or "
              "--artifact FILE (the bench quality_overhead artifact)",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc, indent=2))
        return 0
    _print_quality_report(doc)
    return 0


def _print_quality_report(doc: dict) -> None:
    if "overhead_pct" in doc:
        # the bench quality_overhead artifact, not an evaluator document
        print(f"quality_overhead bench: overhead {doc['overhead_pct']:.2f}% "
              f"(budget {doc.get('budget_pct')}%, "
              f"quiet_host={doc.get('quiet_host')}, ok={doc.get('ok')})")
        print(f"  joined {doc.get('joined')} over {doc.get('rounds')} rounds "
              f"x {doc.get('sessions')} sessions")
        return
    if not doc.get("enabled", True):
        print("quality evaluation disabled ([quality] enabled=false "
              "or no evaluator attached)")
        return
    labels = doc.get("labels") or []
    overall = doc.get("overall") or {}
    beta = doc.get("beta", 0.5)
    print(f"model quality (threshold {doc.get('threshold')}, "
          f"F-beta beta={beta:g}, label lag {doc.get('max_lead')} rows):")
    cons = doc.get("conservation") or {}
    print(f"  captured {cons.get('captured', 0)} = "
          f"joined {cons.get('joined', 0)} + expired {cons.get('expired', 0)}"
          f" + shed {cons.get('shed', 0)} + pending {cons.get('pending', 0)}"
          f" (join errors: {doc.get('join_errors', 0)})")
    rows = [("overall", overall)]
    rows += [(f"v{v}", s) for v, s in sorted(
        (doc.get("versions") or {}).items())]
    print(f"  {'version':<10} {'n':>7} {'accuracy':>9} {'hamming':>9} "
          + " ".join(f"F:{label}" for label in labels))
    for name, s in rows:
        if not s or not s.get("n"):
            print(f"  {name:<10} {'0':>7} {'-':>9} {'-':>9}")
            continue
        fbeta = " ".join(
            f"{f:>8.3f}" for f in (s.get("fbeta") or []))
        print(f"  {name:<10} {s['n']:>7} {s['subset_accuracy']:>9.4f} "
              f"{s['hamming_loss']:>9.4f} {fbeta}")
    drift = doc.get("drift")
    if drift:
        print(f"  drift: max PSI {drift.get('max_psi', 0.0):.4f} over "
              f"{drift.get('rows', 0)} sampled rows "
              f"(prediction PSI {drift.get('prediction_psi')})")


def _print_perf_report(doc: dict, profile_text, *, top: int) -> None:
    ledger = doc.get("ledger") or {}
    programs = list(ledger.get("programs") or [])
    print("compile ledger"
          + (f" (backend {ledger['backend']})"
             if ledger.get("backend") else "") + ":")
    print(f"  compiles {ledger.get('compiles_total', 0)}"
          f" | compile time {ledger.get('compile_seconds_total', 0.0):.3f}s"
          f" | post-warmup recompiles"
          f" {ledger.get('unexpected_recompiles_total', 0)}"
          f" | cost-probe failures {ledger.get('cost_probe_failures', 0)}")
    if "mfu" in doc:
        print(f"  mfu {float(doc['mfu']) * 100:.2f}%")
    if programs:
        programs.sort(key=lambda p: -float(p.get("compile_seconds", 0.0)))
        print(f"  top {min(top, len(programs))} programs "
              f"by compile time:")
        print(f"    {'program':<32} {'signature':<18} {'compiles':>8} "
              f"{'calls':>8} {'compile_s':>10} {'gflops':>9}")
        for p in programs[:top]:
            print(f"    {str(p.get('program', '')):<32} "
                  f"{str(p.get('signature', '')):<18} "
                  f"{p.get('compiles', 0):>8} {p.get('calls', 0):>8} "
                  f"{float(p.get('compile_seconds', 0.0)):>10.3f} "
                  f"{float(p.get('flops', 0.0)) / 1e9:>9.3f}")
    memory = doc.get("memory") or {}
    if memory.get("samples"):
        leak = " | LEAK SUSPECTED" if memory.get("leak_suspected") else ""
        print("device memory:")
        print(f"  live {_fmt_bytes(memory.get('live_bytes', 0))}"
              f" | watermark {_fmt_bytes(memory.get('watermark_bytes', 0))}"
              f" | samples {memory.get('samples', 0)}{leak}")
        for owner, nbytes in sorted((memory.get("by_owner") or {}).items()):
            print(f"    {owner:<44} {_fmt_bytes(nbytes)}")
    fallbacks = doc.get("kernel_fallbacks") or {}
    if fallbacks:
        print("kernel fallbacks:")
        for key, n in sorted(fallbacks.items()):
            print(f"    {key:<44} {n}")
    if profile_text:
        from fmda_tpu.obs.pyprof import HostProfiler

        stacks = sorted(HostProfiler.parse_folded(profile_text).items(),
                        key=lambda kv: -kv[1])
        if stacks:
            total = sum(n for _, n in stacks)
            print(f"hottest host stacks ({total} samples):")
            for stack, n in stacks[:top]:
                frames = stack.split(";")
                leaf = frames[-1] if frames else stack
                root = frames[0] if frames else ""
                print(f"  {n:>7}  {root} ... {leaf}"
                      if len(frames) > 2 else f"  {n:>7}  {stack}")


def cmd_lint(args) -> int:
    """The static-analysis gate (docs/analysis.md).  Exit-code contract
    mirrors the serve-fleet gates: 0 = clean against the baseline,
    1 = new findings, 2 = usage error — CI scripts can gate on it
    directly and parse ``--json`` for the details."""
    import pathlib

    from fmda_tpu.analysis import default_rules, run_lint

    if not args.no_drift:
        import importlib.util

        if importlib.util.find_spec("jax") is None:
            print(
                "jax is not installed on this host — the jax-api-drift "
                "rule has nothing to resolve against; re-run with "
                "--no-drift",
                file=sys.stderr)
            return 2
    rules = default_rules(drift=not args.no_drift)
    if args.rule:
        by_id = {r.id: r for r in rules}
        unknown = [rid for rid in args.rule if rid not in by_id]
        if unknown:
            print(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(available: {', '.join(sorted(by_id))})",
                file=sys.stderr)
            return 2
        rules = [by_id[rid] for rid in args.rule]
    baseline = pathlib.Path(args.baseline) if args.baseline else None
    if baseline is not None and not baseline.is_file():
        # only the *default* baseline may be absent (fresh tree); an
        # explicit path that resolves to nothing is a typo, and gating
        # against an empty register silently would defeat the gate
        print(f"baseline file not found: {baseline}", file=sys.stderr)
        return 2
    try:
        result = run_lint(rules, baseline_path=baseline)
    except ValueError as exc:  # malformed baseline (no justification, …)
        print(str(exc), file=sys.stderr)
        return 2
    if args.drift_report:
        if "jax_api_drift" not in result.reports:
            # --no-drift or a --rule filter excluded the drift rule:
            # silently leaving a stale inventory on disk would be worse
            # than refusing
            print(
                "--drift-report needs the jax-api-drift rule in the "
                "run (drop --no-drift / include --rule jax-api-drift)",
                file=sys.stderr)
            return 2
        out = pathlib.Path(args.drift_report)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(
            json.dumps(result.reports["jax_api_drift"], indent=2) + "\n")
    if args.sarif:
        from fmda_tpu.analysis import to_sarif

        out = pathlib.Path(args.sarif)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(
            json.dumps(to_sarif(result, rules), indent=2) + "\n")
    if args.json:
        print(json.dumps(result.as_dict(), indent=2))
        return 0 if result.ok else 1
    for f in result.new:
        print(f.format())
    for e in result.stale_baseline:
        print(
            f"stale baseline entry (debt paid — prune it): "
            f"[{e['rule']}] {e['path']}: {e['message']}",
            file=sys.stderr)
    for e in result.forbidden_baseline:
        print(
            f"forbidden baseline entry ([{e['rule']}] is a zero-baseline "
            f"hard gate — fix the code, never grandfather it): "
            f"{e['path']}: {e['message']}",
            file=sys.stderr)
    print(f"{result.n_modules} modules: {len(result.new)} new finding(s), "
          f"{len(result.baselined)} baselined, "
          f"{result.suppressed} suppressed, "
          f"{len(result.stale_baseline)} stale baseline entr"
          f"{'y' if len(result.stale_baseline) == 1 else 'ies'}, "
          f"{len(result.forbidden_baseline)} forbidden")
    return 0 if result.ok else 1


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="fmda_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument(
        "--config", default=None, metavar="JSON",
        help="FrameworkConfig overrides as JSON "
             "(fmda_tpu.config.save_config writes the full schema; "
             "partial files override sections). The CLI honors features/"
             "warehouse/bus/model/train; session and mesh apply to the "
             "library Application/Trainer APIs")
    common.add_argument(
        "--platform", choices=("auto", "cpu", "ambient"), default="auto",
        help="accelerator selection: 'auto' probes the ambient backend "
             "with a timeout and falls back to CPU if it is unreachable "
             "(never hangs); 'cpu' forces the host platform; 'ambient' "
             "trusts the environment without probing")
    common.add_argument(
        "--probe-timeout-s", type=float, default=120.0,
        help="backend probe timeout for --platform auto")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("demo", parents=[common], help="synthetic end-to-end proof run")
    p.add_argument("--days", type=int, default=8)
    p.add_argument("--epochs", type=int, default=None,
                   help="default: config's train.epochs, or 2 standalone")
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.set_defaults(fn=cmd_demo)

    p = sub.add_parser("ingest", parents=[common], help="fill a warehouse file")
    p.add_argument("--warehouse", required=True, help="sqlite file path")
    p.add_argument("--synthetic-days", type=int, default=0)
    p.add_argument("--replay", default=None, metavar="FIXTURES",
                   help="re-run a recorded session (RecordingTransport "
                        "file) through the real acquisition layer")
    p.add_argument("--replay-start", default="2020-02-07 09:30:00",
                   help="simulated clock start for --replay")
    p.add_argument("--ticks", type=int, default=0,
                   help="cap on --replay session ticks (0 = until close)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--engine-checkpoint", default=None)
    p.add_argument("--checkpoint-every", type=int, default=None)
    p.set_defaults(fn=cmd_ingest)

    p = sub.add_parser("train", parents=[common], help="train over a warehouse file")
    p.add_argument("--warehouse", required=True)
    p.add_argument("--checkpoint-dir", default=None,
                   help="override config train.checkpoint_dir")
    p.add_argument("--epochs", type=int, default=None,
                   help="override config train.epochs (default 25)")
    p.add_argument("--batch-size", type=int, default=None,
                   help="override config train.batch_size (default 2)")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--continuous", action="store_true",
                   help="tail the warehouse and fine-tune continuously "
                        "([train] continuous_* knobs; versioned "
                        "checkpoints + drift profiles per round)")
    p.add_argument("--max-rounds", type=int, default=None,
                   help="bound --continuous fine-tune rounds "
                        "(default: until the warehouse quiesces)")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser("backtest", parents=[common], help="score a checkpoint over history")
    p.add_argument("--warehouse", required=True)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--window", type=int, default=None,
                   help="override config train.window (default 30)")
    p.add_argument("--threshold", type=float, default=None)
    p.set_defaults(fn=cmd_backtest)

    p = sub.add_parser("serve", parents=[common], help="prediction daemon over a warehouse")
    p.add_argument("--warehouse", required=True)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--window", type=int, default=None,
                   help="override config train.window (default 30)")
    p.add_argument("--threshold", type=float, default=None,
                   help="label decision threshold (match your backtest)")
    p.add_argument("--poll-interval-s", type=float, default=0.5)
    p.add_argument("--duration-s", type=float, default=0.0)
    p.add_argument("--once", action="store_true",
                   help="one poll pass, then exit")
    p.add_argument("--from-start", action="store_true",
                   help="serve existing history too, not just new rows")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser(
        "serve-fleet", parents=[common],
        help="multi-tenant micro-batching runtime vs a synthetic fleet")
    p.add_argument("--role",
                   choices=("solo", "broker", "router", "worker", "local"),
                   default="solo",
                   help="'solo' (default) runs the single-process fleet "
                        "runtime; the multi-host topology "
                        "(fmda_tpu.fleet, docs/multihost.md) splits into "
                        "'broker' (bus + bus server only — the local "
                        "Kafka stand-in), 'router' (session routing + "
                        "membership + migration, jax-free), 'worker' "
                        "(one slot-range owner), and 'local' (one "
                        "command: broker + N workers spawned, router "
                        "inline, synthetic load)")
    p.add_argument("--workers", type=int, default=None,
                   help="worker-process count for --role local/router "
                        "(default: config fleet.n_workers)")
    p.add_argument("--listen", type=int, default=None,
                   help="bus-server port for --role router (0 = "
                        "ephemeral; default: config fleet.port)")
    p.add_argument("--connect", default=None, metavar="HOST:PORT",
                   help="router bus-server address for --role worker")
    p.add_argument("--worker-id", default=None,
                   help="this worker's id (--role worker); the router "
                        "routes its slot-range to fleet_ticks_<id>")
    p.add_argument("--shared-bus", action="store_true",
                   help="--role worker: do the data plane on the shared "
                        "--connect bus too (an external broker topology, "
                        "e.g. Kafka-shaped) instead of hosting this "
                        "worker's own inbox/results bus")
    p.add_argument("--wire-format", default=None,
                   choices=["auto", "binary", "json"],
                   help="frame encoding on every SocketBus link "
                        "(overrides [fleet] wire_format; json = the "
                        "rollback format, docs/multihost.md)")
    p.add_argument("--duration-s", type=float, default=0.0,
                   help="safety-valve runtime bound for --role "
                        "worker/router (0 = until stopped)")
    p.add_argument("--storm-every", type=int, default=0,
                   help="adversarial reconnect storm: every N load "
                        "rounds, close + instantly reopen a burst of "
                        "sessions (0 = off)")
    p.add_argument("--storm-fraction", type=float, default=0.25,
                   help="fraction of sessions hit per reconnect storm")
    p.add_argument("--burst-every", type=int, default=0,
                   help="synchronized burst (market-open spike): every "
                        "N rounds EVERY session ticks for "
                        "--burst-rounds consecutive rounds (0 = off)")
    p.add_argument("--burst-rounds", type=int, default=1,
                   help="consecutive all-tick rounds per burst")
    p.add_argument("--slow-fraction", type=float, default=0.0,
                   help="fraction of sessions that are slow-drip "
                        "stragglers ticking at --slow-duty instead of "
                        "--duty (long-lived barely-ticking clients)")
    p.add_argument("--slow-duty", type=float, default=0.05,
                   help="tick probability per round for the slow-drip "
                        "straggler set")
    p.add_argument("--no-controller", action="store_true",
                   help="--role router/local: disable the adaptive "
                        "control plane (fmda_tpu.control; on by default "
                        "whenever fleet telemetry is) — fixed linger, "
                        "no autoscaling, global oldest-drop shedding")
    p.add_argument("--tenant-mix", default=None,
                   metavar="CLASS:WEIGHT,...",
                   help="--role local: tenant-labeled traffic mix, e.g. "
                        "'gold:1,standard:4' — sessions are assigned a "
                        "priority class weight-proportionally and opened "
                        "labeled (per-tenant QoS applies when [control] "
                        "tenant_classes configures the policy); "
                        "composable with --burst-every/--storm-every/"
                        "--slow-fraction")
    p.add_argument("--replay", action="store_true",
                   help="--role solo/local: historical backfill — serve "
                        "the [replay] config section's history source "
                        "(seeded synthetic or warehouse bulk reads) "
                        "through the unmodified serving path at max "
                        "speed on a virtual clock (the rows' own "
                        "timestamps; no wall-clock pacing), instead of "
                        "the cadence-shaped synthetic load "
                        "(docs/replay.md)")
    p.add_argument("--hot-swap", action="store_true",
                   help="with --replay: land a fresh-seed checkpoint "
                        "into the live fleet halfway through the "
                        "backfill — zero dropped sessions, zero "
                        "recompiles; results carry weights_version "
                        "from the swap barrier on")
    p.add_argument("--continuous-train", action="store_true",
                   help="--role solo: run the continuous fine-tuning "
                        "loop beside the serving gateway — a synthetic "
                        "corpus warehouse is tailed, fine-tuned on a "
                        "sliding window, and every round's checkpoint "
                        "hot-swaps into the live pool (zero serving "
                        "recompiles; [train] continuous_* knobs, "
                        "docs/training.md)")
    p.add_argument("--swap-guard", action="store_true",
                   help="with --continuous-train: shadow-score every "
                        "candidate against the incumbent before the "
                        "swap (fmda_tpu.eval.shadow; refusals keep the "
                        "incumbent serving and are counted)")
    p.add_argument("--continuous-days", type=int, default=2,
                   help="synthetic corpus size (trading days) for the "
                        "--continuous-train warehouse")
    p.add_argument("--train-rounds", type=int, default=None,
                   help="bound --continuous-train fine-tune rounds "
                        "(default: until the backlog quiesces)")
    p.add_argument("--train-checkpoint-dir", default=None,
                   help="--continuous-train checkpoint directory "
                        "(default: config train.checkpoint_dir)")
    p.add_argument("--chaos-plan", default=None, metavar="FILE",
                   help="--role local: run the chaos soak under this "
                        "fault-plan JSON (fmda_tpu.chaos.FaultPlan; "
                        "docs/chaos.md) instead of the plain load; "
                        "'generate' derives a plan from the config's "
                        "[chaos] knobs + seed.  Exits 1 iff a "
                        "never-abort gate fails")
    p.add_argument("--chaos-no-reference", action="store_true",
                   help="skip the unfaulted reference run (and with it "
                        "the bit-identity gate) — faster soak, "
                        "accounting + failover gates only")
    p.add_argument("--trace-dir", default=None, metavar="DIR",
                   help="--role local: enable tracing in every process "
                        "and write one trace file per process into DIR "
                        "(merge: `python -m fmda_tpu trace --merge DIR`)")
    p.add_argument("--sessions", type=int, default=64,
                   help="concurrent ticker sessions (pool capacity grows "
                        "to fit when the config's is smaller)")
    p.add_argument("--ticks", type=int, default=100,
                   help="submission rounds over the fleet")
    p.add_argument("--duty", type=float, default=1.0,
                   help="fraction of sessions ticking per round")
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--cell", default=None, choices=["gru", "lstm", "ssm"],
                   help="carried-state cell family for the serving "
                        "pool (overrides [model] cell; default env "
                        "FMDA_FLEET_CELL, else the config).  'ssm' is "
                        "the O(1)-cache family — GRU-vs-SSM ticks/s at "
                        "equal --hidden is two runs of this command "
                        "(docs/runtime.md 'The SSM cell family')")
    p.add_argument("--window", type=int, default=None,
                   help="override config runtime.window (default 30)")
    p.add_argument("--bucket-sizes", default=None, metavar="N,N,...",
                   help="override config runtime.bucket_sizes "
                        "(ascending; each is one compiled program)")
    p.add_argument("--max-linger-ms", type=float, default=None,
                   help="override config runtime.max_linger_ms")
    p.add_argument("--queue-bound", type=int, default=None,
                   help="override config runtime.queue_bound")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--predictor", action="store_true",
                   help="serve the window-re-scan Predictor path "
                        "instead of carried-state sessions: "
                        "predict-timestamp signals over a synthetic "
                        "corpus, batched into bucketed (B, window, F) "
                        "forwards (runtime.predictor_* knobs; "
                        "docs/runtime.md 'Batched Predictor path')")
    p.add_argument("--predictor-days", type=int, default=3,
                   help="synthetic corpus size for --predictor (days)")
    p.add_argument("--signals", type=int, default=0,
                   help="signal count for --predictor (0 = every "
                        "servable warehouse timestamp)")
    p.add_argument("--burst", type=int, default=32,
                   help="signals published per poll for --predictor "
                        "(the engine's signal-after-commit burst shape)")
    p.add_argument("--ring", action="store_true", default=None,
                   help="enable the device-resident window ring for "
                        "--predictor (runtime.predictor_ring: "
                        "consecutive signals re-send only new rows)")
    p.add_argument("--serial", action="store_true", default=None,
                   help="disable the one-deep flush overlap pipeline "
                        "(runtime.pipeline_depth=0; bit-identical A/B "
                        "reference for the default overlapped path)")
    p.add_argument("--shard-pool", action="store_true", default=None,
                   help="shard the session pool's slot axis across the "
                        "configured device mesh (runtime.shard_pool; "
                        "1-device meshes degrade to the unsharded pool)")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   help="latency-SLO gate: exit 1 unless p99 of "
                        "submit->publish stays under this bound "
                        "(overrides config runtime.slo_p99_ms)")
    p.add_argument("--slo-soft", action="store_true",
                   help="report the SLO verdict in the JSON but never "
                        "fail the run (loaded-host escape hatch)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve /metrics + /healthz + /snapshot on this "
                        "port during the run (0 = ephemeral); for "
                        "--role router/local this is the fleet "
                        "telemetry endpoint (+ /query + /alerts)")
    p.add_argument("--postmortem-dir", default=None, metavar="DIR",
                   help="--role router/local: flight-recorder bundle "
                        "directory (overrides [slo] postmortem_dir) — "
                        "an SLO alert firing or an injected chaos fault "
                        "dumps a rotated postmortem bundle there")
    p.add_argument("--metrics-hold-s", type=float, default=0.0,
                   help="keep the metrics endpoint up this long after "
                        "the load finishes (curl/promtool demos)")
    p.add_argument("--trace", action="store_true",
                   help="enable end-to-end tick tracing for the run "
                        "(fmda_tpu.obs.trace; spans also served on "
                        "/trace when --metrics-port is up)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="trace sampling rate in [0,1] (default 1.0 — "
                        "every tick; production fleets run ~0.01)")
    p.add_argument("--trace-out", default=None, metavar="FILE",
                   help="write the span ring as Chrome/Perfetto "
                        "trace_event JSON after the load (implies "
                        "--trace; inspect with `python -m fmda_tpu "
                        "trace --input FILE` or ui.perfetto.dev)")
    p.add_argument("--jax-profile", default=None, metavar="DIR",
                   help="capture a jax device profile of the load "
                        "(TensorBoard/XProf), pool flushes annotated "
                        "as numbered steps")
    p.set_defaults(fn=cmd_serve_fleet)

    p = sub.add_parser(
        "status", parents=[common],
        help="pretty-print an observability snapshot + health verdict")
    p.add_argument("--endpoint", default=None, metavar="HOST:PORT",
                   nargs="+",
                   help="scrape running endpoints' /snapshot + /healthz "
                        "instead of building a local app; several "
                        "endpoints (one per fleet worker) report "
                        "per-worker + aggregate health")
    p.add_argument("--warehouse", default=None,
                   help="warehouse file for the local snapshot (default: "
                        "config's path)")
    p.add_argument("--watch", type=float, default=None, metavar="N",
                   help="live-refresh mode: re-scrape and redraw every "
                        "N seconds until Ctrl-C (clean exit 0) — watch "
                        "a soak without a shell loop")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser(
        "trace", parents=[common],
        help="per-stage latency attribution for recorded tick traces")
    p.add_argument("--input", default=None, metavar="FILE",
                   help="Chrome/Perfetto trace_event JSON file "
                        "(serve-fleet --trace-out)")
    p.add_argument("--endpoint", default=None, metavar="HOST:PORT",
                   help="scrape a running endpoint's /trace instead")
    p.add_argument("--merge", nargs="+", default=None, metavar="PATH",
                   help="stitch per-process --trace-out files into one "
                        "trace by trace id (timelines aligned on shared "
                        "journeys); each PATH may be a file, a glob, or "
                        "a directory of *.json trace files (a topology's "
                        "--trace-dir merges in one command); with --out "
                        "writes the merged Perfetto JSON, without it "
                        "shows the attribution over the merged document")
    p.add_argument("--out", default=None, metavar="FILE",
                   help="write the --merge result to this file")
    p.add_argument("--last", type=int, default=10,
                   help="show the newest N traces (default 10)")
    p.add_argument("--slowest", type=int, default=None, metavar="N",
                   help="show the N slowest traces by e2e duration "
                        "instead of the newest")
    p.add_argument("--min-ms", type=float, default=None,
                   help="only traces with e2e duration >= this (ms)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (grouped trace dicts)")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "perf", parents=[common],
        help="device/compiler performance report: compile ledger, "
             "MFU, memory watermarks, hottest host stacks")
    p.add_argument("--endpoint", default=None, metavar="HOST:PORT",
                   help="scrape a running endpoint's /device (+ "
                        "/profile) — a serve-fleet worker or the "
                        "fleet telemetry endpoint")
    p.add_argument("--input", default=None, metavar="FILE",
                   help="saved device report JSON instead: a "
                        "flight-recorder bundle's device.json or the "
                        "bench device_obs_overhead ledger artifact")
    p.add_argument("--profile", default=None, metavar="FILE",
                   help="folded-stack profile text to report hottest "
                        "stacks from (a bundle's profile.folded); "
                        "--endpoint fetches /profile automatically")
    p.add_argument("--top", type=int, default=10,
                   help="rows per table: top programs, hottest "
                        "stacks (default 10)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (the device report "
                        "document, plus profile_folded when present)")
    p.set_defaults(fn=cmd_perf)

    p = sub.add_parser(
        "quality", parents=[common],
        help="model-quality report: per-weights-version live "
             "accuracy/F-beta, drift vs the training profile, "
             "capture/join conservation")
    p.add_argument("--endpoint", default=None, metavar="HOST:PORT",
                   help="scrape a running endpoint's /quality (the "
                        "fleet telemetry endpoint)")
    p.add_argument("--bundle", default=None, metavar="DIR",
                   help="read a flight-recorder postmortem bundle's "
                        "quality.json instead")
    p.add_argument("--artifact", default=None, metavar="FILE",
                   help="read a bench quality_overhead artifact "
                        "(artifacts/quality_eval.json) instead")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (the /quality "
                        "document verbatim)")
    p.set_defaults(fn=cmd_quality)

    p = sub.add_parser(
        "chaos-pipeline", parents=[common],
        help="data-plane chaos soak: feeds -> engine -> journaled "
             "warehouse -> predictor under a seeded fault plan "
             "(docs/chaos.md); exit 1 iff a never-abort gate fails")
    p.add_argument("--seed", type=int, default=None,
                   help="plan + market seed (default: [chaos] seed; "
                        "FMDA_CHAOS_SEED drives the bench phase)")
    p.add_argument("--rounds", type=int, default=30,
                   help="virtual steps the plan schedules over")
    p.add_argument("--plan", default=None, metavar="FILE",
                   help="explicit fault-plan JSON instead of the "
                        "seeded data-plane schedule (the reproduction "
                        "path)")
    p.add_argument("--no-predictor", action="store_true",
                   help="skip the jitted Predictor stage (jax-free, "
                        "faster; drops the probes-served gate)")
    p.add_argument("--no-reference", action="store_true",
                   help="skip the unfaulted reference replay (faster; "
                        "drops the bit-identity gate)")
    p.set_defaults(fn=cmd_chaos_pipeline)

    p = sub.add_parser(
        "lint",
        help="framework-aware static analysis gate (docs/analysis.md)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable result document (schema "
                        "covered by tests/test_analysis.py)")
    p.add_argument("--rule", action="append", default=None, metavar="ID",
                   help="run only this rule (repeatable); baseline "
                        "entries for other rules are ignored, not stale")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline JSON of grandfathered findings "
                        "(default: fmda_tpu/analysis/baseline.json)")
    p.add_argument("--no-drift", action="store_true",
                   help="skip the JAX API-drift resolver — the one rule "
                        "that imports jax (fast editor loops, jax-free "
                        "hosts)")
    p.add_argument("--drift-report", default=None, metavar="FILE",
                   help="write the machine-readable jax drift inventory "
                        "(the porting work-list artifact: "
                        "artifacts/jax_api_drift.json in this repo)")
    p.add_argument("--sarif", default=None, metavar="FILE",
                   help="write the run as a SARIF 2.1.0 document (new "
                        "findings as results, baselined ones suppressed) "
                        "— what CI uploads to render findings as diff "
                        "annotations")
    p.set_defaults(fn=cmd_lint)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # stdout consumer (head, a closed pager) went away mid-print —
        # normal unix behavior, not an error
        try:
            sys.stdout.close()
        except BrokenPipeError:
            pass
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
