from fmda_tpu.train.losses import class_weights, weighted_bce_with_logits
from fmda_tpu.train.trainer import EpochMetrics, Trainer, TrainState
from fmda_tpu.train.checkpoint import restore_checkpoint, save_checkpoint

__all__ = [
    "class_weights",
    "weighted_bce_with_logits",
    "Trainer",
    "TrainState",
    "EpochMetrics",
    "save_checkpoint",
    "restore_checkpoint",
]
