from fmda_tpu.train.losses import (
    class_weights,
    weighted_bce_sums,
    weighted_bce_with_logits,
)
from fmda_tpu.train.trainer import (
    EpochMetrics,
    Trainer,
    TrainState,
    imbalance_weights_from_source,
)
from fmda_tpu.train.continuous import (
    ContinuousTrainer,
    TailSource,
    gateway_publisher,
    router_publisher,
)
from fmda_tpu.train.multiticker import MultiTickerDataset
from fmda_tpu.train.checkpoint import restore_checkpoint, save_checkpoint

__all__ = [
    "class_weights",
    "weighted_bce_sums",
    "weighted_bce_with_logits",
    "Trainer",
    "TrainState",
    "EpochMetrics",
    "imbalance_weights_from_source",
    "ContinuousTrainer",
    "TailSource",
    "gateway_publisher",
    "router_publisher",
    "MultiTickerDataset",
    "save_checkpoint",
    "restore_checkpoint",
]
