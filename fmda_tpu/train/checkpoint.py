"""Checkpointing: params + optimizer + step + normalization stats.

The reference persists only ``model_params.pt`` (notebook cell 39) and a
separate ``norm_params`` pickle (sql_pytorch_dataloader.py:147-153), with no
optimizer state and no mid-training resume.  Here the whole training state
is one Orbax checkpoint tree, so resume is exact and serving loads the norm
stats from the same artifact.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from fmda_tpu.data.normalize import NormParams


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(
    directory: str,
    state: Any,
    norm_params: Optional[NormParams] = None,
    *,
    step: Optional[int] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Save a full training checkpoint; returns the checkpoint path."""
    directory = os.path.abspath(directory)
    step = int(state.step) if step is None else step
    path = os.path.join(directory, f"step_{step:08d}")
    tree = {
        "params": jax.device_get(state.params),
        "opt_state": jax.device_get(state.opt_state),
        "step": np.asarray(step, np.int64),
    }
    if norm_params is not None:
        tree["norm"] = {
            "x_min": np.asarray(norm_params.x_min),
            "x_max": np.asarray(norm_params.x_max),
        }
    if extra:
        tree["extra"] = extra
    _checkpointer().save(path, tree, force=True)
    return path


def restore_checkpoint(path: str) -> Tuple[Dict[str, Any], Optional[NormParams]]:
    """Restore a checkpoint tree; returns (tree, norm_params-or-None)."""
    tree = _checkpointer().restore(os.path.abspath(path))
    norm = None
    if "norm" in tree and tree["norm"] is not None:
        norm = NormParams(
            np.asarray(tree["norm"]["x_min"], np.float32),
            np.asarray(tree["norm"]["x_max"], np.float32),
        )
    return tree, norm


def latest_checkpoint(directory: str) -> Optional[str]:
    """Most recent step_* checkpoint path under a directory."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    steps = sorted(
        d for d in os.listdir(directory) if d.startswith("step_")
    )
    return os.path.join(directory, steps[-1]) if steps else None
