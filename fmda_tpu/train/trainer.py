"""Training harness: jitted steps, chunked epochs, checkpointing.

Replaces the reference's notebook training loop
(biGRU_model_training.ipynb cells 11-39 + biGRU_model.py:162-286) with a
proper API.  Same semantics — chunk-level contiguous split, per-chunk
normalization, weighted BCE, Adam with global-norm clip 50, per-batch
metrics averaged per epoch — but everything device-side:

- one compiled ``train_step``/``eval_step`` reused for every batch (fixed
  shapes via padded+masked batches — no per-batch Python/sklearn work);
- gradients, clipping, Adam, and all four metrics fused into the step;
- optional data parallelism: pass a :class:`jax.sharding.Mesh` and the step
  shards the batch across the ``dp`` axis (XLA inserts the ICI all-reduce
  for gradients automatically).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from fmda_tpu.config import ModelConfig, TrainConfig
from fmda_tpu.data.pipeline import (
    Batch,
    ChunkDataset,
    WindowBatches,
    prefetch_batches,
)
from fmda_tpu.data.source import FeatureSource
from fmda_tpu.models import build_model
from fmda_tpu.obs.device import tracked_jit
from fmda_tpu.ops.metrics import multilabel_metrics
from fmda_tpu.train.losses import (
    class_weights,
    weighted_bce_sums,
    weighted_bce_with_logits,
)

log = logging.getLogger("fmda_tpu.train")


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


class EpochMetrics(NamedTuple):
    loss: float
    accuracy: float
    hamming: float
    fbeta: np.ndarray  # (n_classes,)


class Trainer:
    """Builds the model + optimizer and runs chunked epochs over a source."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        *,
        weight: Optional[np.ndarray] = None,
        pos_weight: Optional[np.ndarray] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        dp_axis: str = "dp",
    ) -> None:
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg
        self.model = build_model(model_cfg)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(train_cfg.clip),
            optax.adam(train_cfg.learning_rate),
        )
        self.weight = None if weight is None else jnp.asarray(weight)
        self.pos_weight = None if pos_weight is None else jnp.asarray(pos_weight)
        self.mesh = mesh
        self.dp_axis = dp_axis
        self._train_step = self._build_train_step()
        self._eval_step = self._build_eval_step()
        # placed-batch cache: (id(dataset), chunk tuple) -> (dataset,
        # [Batch]) — see _run_chunks; the dataset ref pins id() validity
        self._placed_cache: Dict[Any, Tuple[Any, List[Batch]]] = {}

    # -- state ---------------------------------------------------------------

    def _init_state_local(self, rng: jax.Array) -> TrainState:
        """Fresh state on the default device (no mesh placement)."""
        cfg = self.model_cfg
        dummy = jnp.zeros(
            (1, self.train_cfg.window, cfg.n_features), jnp.float32
        )
        variables = self.model.init({"params": rng}, dummy)
        opt_state = self.optimizer.init(variables["params"])
        return TrainState(
            params=variables["params"],
            opt_state=opt_state,
            step=jnp.zeros((), jnp.int32),
        )

    def _place_state(self, state: TrainState) -> TrainState:
        if self.mesh is not None:
            # multi-process safe: plain device_put onto a sharding that
            # spans processes runs a host-side cross-process assert some
            # CPU builds cannot execute (parallel/distributed.py)
            from fmda_tpu.parallel.distributed import place_replicated

            state = place_replicated(self.mesh, state)
        return state

    def init_state(self, rng: jax.Array) -> TrainState:
        return self._place_state(self._init_state_local(rng))

    def restore_state(self, checkpoint_path: str) -> TrainState:
        """Exact-resume a checkpoint into this trainer's state structure.

        The raw checkpoint tree stores the optimizer state as plain
        containers; its leaves are grafted back onto the typed optax
        structure a fresh ``init_state`` provides, so ``fit(...,
        initial_state=restore_state(p))`` continues training bit-exactly
        (step counter included — the dropout stream folds on it).
        """
        from fmda_tpu.train.checkpoint import restore_checkpoint

        tree, norm = restore_checkpoint(checkpoint_path)
        # remembered so a subsequent fit() can detect that the data source
        # (and hence the recomputed normalization) changed since the save
        self._restored_norm = norm
        # structure/dtype template only — no mesh placement of throwaway
        # arrays; the restored state is placed once below
        template = self._init_state_local(jax.random.PRNGKey(0))
        params = jax.tree.map(
            lambda t, r: jnp.asarray(r, t.dtype), template.params,
            tree["params"],
        )
        opt_state = jax.tree.unflatten(
            jax.tree.structure(template.opt_state),
            [jnp.asarray(leaf) for leaf in jax.tree.leaves(tree["opt_state"])],
        )
        return self._place_state(TrainState(
            params=params, opt_state=opt_state,
            step=jnp.asarray(int(tree["step"]), jnp.int32),
        ))

    # -- compiled steps ------------------------------------------------------

    def _batch_sharding(self):
        if self.mesh is None:
            return None
        from fmda_tpu.parallel.mesh import batch_sharding

        return batch_sharding(self.mesh, self.dp_axis)

    def _step_shardings(self):
        """(replicated, batch-dp) NamedShardings under a mesh, else None.

        With a mesh the compiled steps carry explicit in/out shardings:
        params/optimizer state replicated over every device, the batch
        split along the dp axis (XLA inserts the gradient all-reduce).
        A 1-device mesh lowers to the identical program as the meshless
        jit — bit-identity is test-pinned (tests/test_train_parallel.py).
        """
        if self.mesh is None:
            return None
        from fmda_tpu.parallel.mesh import batch_sharding, replicated_sharding

        return (
            replicated_sharding(self.mesh),
            batch_sharding(self.mesh, self.dp_axis),
        )

    def _build_train_step(self):
        model, tc = self.model, self.train_cfg
        weight, pos_weight = self.weight, self.pos_weight
        accum = tc.accum_steps

        def grads_full(params, batch: Batch, dropout_rng):
            def loss_fn(params):
                logits = model.apply(
                    {"params": params},
                    batch.x,
                    deterministic=False,
                    rngs={"dropout": dropout_rng},
                )
                loss = weighted_bce_with_logits(
                    logits,
                    batch.y,
                    weight=weight,
                    pos_weight=pos_weight,
                    example_mask=batch.mask,
                )
                return loss, logits

            (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            return loss, logits, grads

        def grads_accum(params, batch: Batch, dropout_rng):
            # (B, ...) -> (K, B/K, ...): equal fixed-shape microbatches
            # scanned into summed gradients.  The masked loss is a global
            # mean (sum / valid-element count), so the scan accumulates
            # the *unnormalized* loss sum, gradient-of-sum, and element
            # count, and normalizes once at the end — the full-batch
            # gradient exactly, up to float re-association
            # (docs/training.md "Accumulation math").  Peak activation
            # memory is one microbatch instead of the full batch.
            micro = jax.tree.map(
                lambda a: a.reshape((accum, a.shape[0] // accum)
                                    + a.shape[1:]),
                batch,
            )

            def sum_loss_fn(params, mb: Batch, mb_rng):
                logits = model.apply(
                    {"params": params},
                    mb.x,
                    deterministic=False,
                    rngs={"dropout": mb_rng},
                )
                s, count = weighted_bce_sums(
                    logits,
                    mb.y,
                    weight=weight,
                    pos_weight=pos_weight,
                    example_mask=mb.mask,
                )
                return s, (count, logits)

            def body(carry, xs):
                grad_sum, loss_sum, count_sum = carry
                mb, k = xs
                # each microbatch gets its own dropout stream (folded on
                # the microbatch index) — full/accumulated equivalence is
                # stated at dropout 0.0
                (s, (count, logits)), g = jax.value_and_grad(
                    sum_loss_fn, has_aux=True
                )(params, mb, jax.random.fold_in(dropout_rng, k))
                carry = (
                    jax.tree.map(jnp.add, grad_sum, g),
                    loss_sum + s,
                    count_sum + count,
                )
                return carry, logits

            zeros = jax.tree.map(jnp.zeros_like, params)
            init = (zeros, jnp.zeros((), jnp.float32),
                    jnp.zeros((), jnp.float32))
            (grad_sum, loss_sum, count_sum), logits_k = jax.lax.scan(
                body, init, (micro, jnp.arange(accum))
            )
            denom = jnp.maximum(count_sum, 1.0)
            grads = jax.tree.map(lambda g: g / denom, grad_sum)
            # metrics run on the full-batch logits, same as the K=1 path
            logits = logits_k.reshape((-1,) + logits_k.shape[2:])
            return loss_sum / denom, logits, grads

        def step_fn(state: TrainState, batch: Batch, rng: jax.Array):
            dropout_rng = jax.random.fold_in(rng, state.step)
            if accum == 1:
                loss, logits, grads = grads_full(
                    state.params, batch, dropout_rng)
            else:
                loss, logits, grads = grads_accum(
                    state.params, batch, dropout_rng)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            metrics = multilabel_metrics(
                logits,
                batch.y,
                threshold=tc.prob_threshold,
                beta=tc.fbeta_beta,
                example_mask=batch.mask,
            )
            new_state = TrainState(
                params=params, opt_state=opt_state, step=state.step + 1
            )
            return new_state, loss, metrics

        jit_kwargs: Dict[str, Any] = {"donate_argnums": (0,)}
        shardings = self._step_shardings()
        if shardings is not None:
            replicated, batched = shardings
            jit_kwargs["in_shardings"] = (
                replicated, Batch(batched, batched, batched), replicated)
            jit_kwargs["out_shardings"] = (replicated, replicated, replicated)
        return tracked_jit(step_fn, name="train_step", **jit_kwargs)

    def _build_eval_step(self):
        model, tc = self.model, self.train_cfg

        def eval_fn(params, batch: Batch):
            logits = model.apply({"params": params}, batch.x)
            loss = weighted_bce_with_logits(
                logits,
                batch.y,
                weight=self.weight,
                pos_weight=self.pos_weight,
                example_mask=batch.mask,
            )
            metrics = multilabel_metrics(
                logits,
                batch.y,
                threshold=tc.prob_threshold,
                beta=tc.fbeta_beta,
                example_mask=batch.mask,
            )
            return loss, metrics

        jit_kwargs: Dict[str, Any] = {}
        shardings = self._step_shardings()
        if shardings is not None:
            replicated, batched = shardings
            jit_kwargs["in_shardings"] = (
                replicated, Batch(batched, batched, batched))
            jit_kwargs["out_shardings"] = (replicated, replicated)
        return tracked_jit(eval_fn, name="eval_step", **jit_kwargs)

    # -- compile accounting ---------------------------------------------------

    def mark_warm(self) -> None:
        """Declare step warm-up over: any compile after this is counted
        as *unexpected* on the compile ledger (the contract the
        ``train_throughput`` bench phase and the continuous loop pin)."""
        self._train_step.mark_warm()
        self._eval_step.mark_warm()

    @property
    def unexpected_recompiles(self) -> int:
        return (self._train_step.unexpected_recompiles
                + self._eval_step.unexpected_recompiles)

    @property
    def compile_counts(self) -> Dict[str, Optional[int]]:
        """Distinct compiled programs per step — the pin the
        ``train_throughput`` bench asserts (batches are always padded to
        ``batch_size``, so each step compiles exactly once).  None when
        jax's (private) cache probe is unavailable."""
        return {"train_step": self._train_step.cache_size(),
                "eval_step": self._eval_step.cache_size()}

    # -- batch plumbing ------------------------------------------------------

    def _place_batches(self, batches: Iterable[Batch]) -> Iterable[Batch]:
        """The overlapped input pipeline: host composition runs in a
        background thread, composed batches are transferred immediately
        (dp batch sharding under a mesh; when the job spans processes
        each process's batches are its *local* shard of the global batch
        and are assembled in place), and up to ``train.prefetch_depth``
        placed batches ride ahead of the step loop.  Host-side waits
        surface as ``train_input_stall_seconds``."""
        from fmda_tpu.obs.registry import default_registry

        stall = default_registry().histogram("train_input_stall_seconds")
        sharding = self._batch_sharding()
        if sharding is None:
            place = jax.device_put
        elif jax.process_count() > 1:
            from fmda_tpu.parallel.distributed import place_local_batch

            def place(b: Batch) -> Batch:
                return place_local_batch(self.mesh, b, self.dp_axis)
        else:
            def place(b: Batch) -> Batch:
                return Batch(
                    jax.device_put(b.x, sharding),
                    jax.device_put(b.y, sharding),
                    jax.device_put(b.mask, sharding),
                )
        return prefetch_batches(
            batches,
            place,
            depth=self.train_cfg.prefetch_depth,
            stall_observer=stall.observe,
        )

    def _chunk_batches(
        self, dataset: ChunkDataset, chunk_idx: int
    ) -> Iterable[Batch]:
        return self._place_batches(
            WindowBatches(dataset, chunk_idx, self.train_cfg.batch_size)
        )

    # -- epochs --------------------------------------------------------------

    def _run_chunks(
        self,
        state: TrainState,
        dataset: ChunkDataset,
        chunk_indices: Sequence[int],
        rng: Optional[jax.Array],
        train: bool,
    ) -> Tuple[TrainState, EpochMetrics, np.ndarray]:
        # one flat host generator over every chunk, behind one pipeline:
        # the window gather/normalization of chunk k+1 (cached after the
        # first epoch — ChunkDataset.windows) happens in the composer
        # thread while the device computes on chunk k's batches.
        #
        # With ``cache_chunks`` set, the PLACED batches of the first
        # pass are kept and later epochs replay the device-side buffers
        # directly — no re-gather, no re-pad, no re-transfer (batches
        # are never donated, so reuse is safe; same arrays -> bit-
        # identical epochs).  RAM bound: cache_chunks chunks of windows
        # on the host (ChunkDataset) plus their placed batches.
        cache_on = (self.train_cfg.cache_chunks > 0
                    and len(chunk_indices) <= self.train_cfg.cache_chunks)
        key = (id(dataset), tuple(chunk_indices))
        if cache_on:
            entry = self._placed_cache.get(key)
            # the entry pins its dataset, so a live hit can never be an
            # id()-reuse collision from a collected dataset
            if entry is not None and entry[0] is dataset:
                return self._run_batches(state, (entry[1],), rng, train)

        def host_batches() -> Iterable[Batch]:
            for idx in chunk_indices:
                yield from WindowBatches(
                    dataset, idx, self.train_cfg.batch_size)

        placed = self._place_batches(host_batches())
        if not cache_on:
            return self._run_batches(state, (placed,), rng, train)
        sink: List[Batch] = []

        def capturing() -> Iterable[Batch]:
            for b in placed:
                sink.append(b)
                yield b

        out = self._run_batches(state, (capturing(),), rng, train)
        self._placed_cache[key] = (dataset, sink)
        while len(self._placed_cache) > 4:  # train + val + headroom
            self._placed_cache.pop(next(iter(self._placed_cache)))
        return out

    def _run_batches(
        self,
        state: TrainState,
        batch_iterables,
        rng: Optional[jax.Array],
        train: bool,
    ) -> Tuple[TrainState, EpochMetrics, np.ndarray]:
        import time as _time

        from fmda_tpu.obs.registry import default_registry
        from fmda_tpu.utils.tracing import step_annotation

        phase = "train" if train else "eval"
        # observability: host-side step dispatch wall clock (steps are
        # async — this measures trace+dispatch, not device compute; the
        # first step's compile dominates its bin, by design visible)
        reg = default_registry()
        step_hist = reg.histogram("train_step_seconds", phase=phase)
        step_counter = reg.counter("train_steps_total", phase=phase)
        # Per-batch results are folded into running on-device accumulators
        # (async adds) — the host never blocks mid-pass and memory stays
        # O(1) instead of holding every batch's arrays live across an
        # epoch.  One device_get at the end drains the totals.
        acc = None
        step_no = 0
        for batches in batch_iterables:
            for batch in batches:
                # marks each step in a device profile when one is being
                # captured (utils.tracing.device_trace); free otherwise
                t0 = _time.perf_counter()
                with step_annotation(phase, step_no):
                    if train:
                        state, loss, metrics = self._train_step(
                            state, batch, rng)
                    else:
                        loss, metrics = self._eval_step(state.params, batch)
                step_hist.observe(_time.perf_counter() - t0)
                step_counter.inc()
                step_no += 1
                vals = (loss, metrics.accuracy, metrics.hamming,
                        metrics.fbeta, metrics.confusion)
                acc = vals if acc is None else jax.tree.map(
                    jnp.add, acc, vals)
        n_classes = self.model_cfg.output_size
        if acc is None:
            log.warning(
                "pass produced no batches (source too short for "
                "window=%d/chunk_size=%d, or empty chunk split) — metrics "
                "are NaN", self.train_cfg.window, self.train_cfg.chunk_size,
            )
            nan = float("nan")
            return (
                state,
                EpochMetrics(nan, nan, nan, np.zeros(n_classes)),
                np.zeros((n_classes, 2, 2), np.int64),
            )
        loss_sum, acc_sum, ham_sum, fbeta_sum, confusion_total = (
            jax.device_get(acc)
        )
        epoch = EpochMetrics(
            loss=float(loss_sum) / step_no,
            accuracy=float(acc_sum) / step_no,
            hamming=float(ham_sum) / step_no,
            fbeta=np.asarray(fbeta_sum) / step_no,
        )
        return state, epoch, np.asarray(confusion_total, np.int64)

    def _warn_if_norm_drifted(self, dataset: ChunkDataset) -> None:
        """Resume runs recompute normalization from the *current* source;
        if rows landed since the checkpoint was written, the serving stats
        (last-chunk min/max) shift under the restored params — loud, not
        silent."""
        saved = getattr(self, "_restored_norm", None)
        if saved is None:
            return
        now = dataset.final_norm_params
        if not (
            np.allclose(saved.x_min, now.x_min)
            and np.allclose(saved.x_max, now.x_max)
        ):
            log.warning(
                "resuming on a source whose normalization stats differ from "
                "the checkpoint's (data changed since the save): inputs are "
                "rescaled relative to what the restored params saw"
            )

    def fit(
        self,
        source: FeatureSource,
        *,
        rng: Optional[jax.Array] = None,
        epochs: Optional[int] = None,
        bid_levels: int = 0,
        ask_levels: int = 0,
        initial_state: Optional[TrainState] = None,
        dataset: Optional[ChunkDataset] = None,
    ) -> Tuple[TrainState, Dict[str, List[EpochMetrics]], ChunkDataset]:
        """Train over a feature source; returns (state, history, dataset).

        ``initial_state`` (e.g. from :meth:`restore_state`) resumes
        mid-training instead of initialising fresh; ``epochs`` then means
        *additional* epochs to run.  ``dataset`` reuses a previously
        returned :class:`ChunkDataset` (it must wrap ``source``) instead
        of re-materializing it — a resumed fit then keeps every warm
        cache tier: host window gathers AND the placed device batches,
        which are keyed on dataset identity.
        """
        tc = self.train_cfg
        rng = jax.random.PRNGKey(tc.seed) if rng is None else rng
        init_rng, step_rng = jax.random.split(rng)
        if dataset is None:
            dataset = ChunkDataset(
                source,
                tc.chunk_size,
                tc.window,
                bid_levels=bid_levels,
                ask_levels=ask_levels,
                cache_chunks=tc.cache_chunks,
            )
        train_chunks, val_chunks, _ = dataset.split(tc.val_size, tc.test_size)
        state = (
            initial_state if initial_state is not None
            else self.init_state(init_rng)
        )
        if initial_state is not None:
            self._warn_if_norm_drifted(dataset)
        history: Dict[str, List[EpochMetrics]] = {"train": [], "val": []}
        from fmda_tpu.obs.registry import default_registry

        reg = default_registry()
        epoch_hist = reg.histogram("train_epoch_seconds")
        epoch_counter = reg.counter("train_epochs_total")
        import time as _time

        for epoch in range(epochs if epochs is not None else tc.epochs):
            t_epoch = _time.perf_counter()
            state, train_metrics, _ = self._run_chunks(
                state, dataset, train_chunks, step_rng, train=True
            )
            history["train"].append(train_metrics)
            if val_chunks:
                _, val_metrics, _ = self._run_chunks(
                    state, dataset, val_chunks, None, train=False
                )
            else:
                # continuous fine-tune rounds run val_size=0 (quality is
                # judged by the shadow gate, not a holdout) — NaN metrics
                # without the empty-pass warning
                nan = float("nan")
                val_metrics = EpochMetrics(
                    nan, nan, nan, np.zeros(self.model_cfg.output_size))
            history["val"].append(val_metrics)
            epoch_hist.observe(_time.perf_counter() - t_epoch)
            epoch_counter.inc()
            log.info(
                "epoch %d: train loss=%.4f acc=%.4f hamming=%.4f | "
                "val acc=%.4f hamming=%.4f",
                epoch + 1,
                train_metrics.loss,
                train_metrics.accuracy,
                train_metrics.hamming,
                val_metrics.accuracy,
                val_metrics.hamming,
            )
        return state, history, dataset

    def fit_multi(
        self,
        sources: Dict[str, FeatureSource],
        *,
        rng: Optional[jax.Array] = None,
        epochs: Optional[int] = None,
        bid_levels: int = 0,
        ask_levels: int = 0,
        mixed_batch_per_ticker: Optional[int] = None,
    ):
        """Multi-ticker shared-encoder training (north-star config 2):
        one model, batches interleaved across instruments, per-ticker
        chunk normalization.  Returns (state, history, MultiTickerDataset).

        ``mixed_batch_per_ticker=k`` switches from chunk-interleaved
        single-ticker batches to the north-star *mixed* composition: every
        step's batch concatenates ``k`` windows from EVERY ticker
        (``len(sources) * k`` rows/step — e.g. 50 x 16 = 800), so each
        gradient mixes all instruments and the device sees one big batch.
        """
        from fmda_tpu.train.multiticker import MultiTickerDataset

        tc = self.train_cfg
        rng = jax.random.PRNGKey(tc.seed) if rng is None else rng
        init_rng, step_rng = jax.random.split(rng)
        mtd = MultiTickerDataset(
            sources, tc.chunk_size, tc.window,
            bid_levels=bid_levels, ask_levels=ask_levels,
        )
        train_chunks, val_chunks, _ = mtd.splits(tc.val_size, tc.test_size)
        if mixed_batch_per_ticker:
            k = mixed_batch_per_ticker

            def iters(chunks):
                # mixed composition is the expensive host stage (~12 ms
                # per 800-row batch): _place_batches runs it in the
                # composer thread and double-buffers the transfer
                return (
                    self._place_batches(mtd.mixed_batches(rc, k))
                    for rc in mtd.rounds(chunks)
                )
        else:
            def iters(chunks):
                return (
                    self._place_batches(mtd.batches(t, c, tc.batch_size))
                    for t, c in chunks
                )
        state = self.init_state(init_rng)
        history: Dict[str, List[EpochMetrics]] = {"train": [], "val": []}
        for epoch in range(epochs if epochs is not None else tc.epochs):
            state, train_metrics, _ = self._run_batches(
                state, iters(train_chunks), step_rng, train=True,
            )
            history["train"].append(train_metrics)
            _, val_metrics, _ = self._run_batches(
                state, iters(val_chunks), None, train=False,
            )
            history["val"].append(val_metrics)
            log.info(
                "multi epoch %d: train loss=%.4f acc=%.4f | val acc=%.4f",
                epoch + 1, train_metrics.loss, train_metrics.accuracy,
                val_metrics.accuracy,
            )
        return state, history, mtd

    def evaluate(
        self,
        state: TrainState,
        dataset: ChunkDataset,
        chunk_indices: Sequence[int],
    ) -> Tuple[EpochMetrics, np.ndarray]:
        """Eval pass (reference evaluate_model + confusion accumulation,
        biGRU_model.py:227-286)."""
        _, metrics, confusion = self._run_chunks(
            state, dataset, chunk_indices, None, train=False
        )
        return metrics, confusion


def imbalance_weights_from_source(source: FeatureSource) -> Tuple[np.ndarray, np.ndarray]:
    """Compute (weight, pos_weight) from the full target table — the
    notebook's ``SELECT SUM(target)/COUNT`` pass (cells 13-16)."""
    ids = range(1, len(source) + 1)
    y = source.fetch_targets(ids)
    counts = np.maximum(y.sum(axis=0), 1.0)
    return class_weights(counts, len(y))
