"""Continuous fine-tuning: tail the warehouse, fine-tune, hot-swap.

The loop the PR 17–19 plumbing was built for, closed.  A
:class:`ContinuousTrainer` tails fresh rows through the warehouse
reader's bounded follow mode (``Warehouse.iter_row_chunks(follow=...)``
— the change-data-capture feed, keyset-resumed across polls), and every
time ``train.continuous_min_rows`` fresh rows have landed it

1. fine-tunes on a sliding window of the newest
   ``train.continuous_window_rows`` rows (warm-started from the previous
   round's state — one compiled step for the whole loop's lifetime:
   every round's batches are the same padded shapes, so after the first
   round's warm-up ``recompiles == 0`` is a pinned contract);
2. writes a versioned checkpoint (``step_NNNNNNNN``) plus the
   ``quality_profile.json`` drift baseline beside it;
3. publishes the new params through an injected ``publish`` callable —
   :func:`router_publisher` (``FleetRouter.broadcast_hot_swap`` with the
   shadow-eval guardrail via ``require_eval``) or
   :func:`gateway_publisher` (solo ``FleetGateway.hot_swap``).  Refused
   candidates are counted, never retried blindly — the incumbent keeps
   serving, the next round gets another shot.

Serving never stops, never recompiles: a hot swap is a host-side weight
rebind on the pool (docs/replay.md "Hot swap"), and the trainer runs
beside it — same process (``serve-fleet --continuous-train``) or a
separate one pointed at the same warehouse (``python -m fmda_tpu train
--continuous``).

Everything time-shaped is injected (``wait_fn``), so tests drive the
loop to quiescence with zero wall sleeps; the CLI passes nothing and
gets the ``train.continuous_poll_s`` wall-clock poll.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fmda_tpu.config import ModelConfig, TrainConfig
from fmda_tpu.train.trainer import (
    Trainer,
    TrainState,
    imbalance_weights_from_source,
)

log = logging.getLogger("fmda_tpu.train.continuous")


class _Stopped(Exception):
    """Raised out of the injected waiter to abort the tail promptly."""


class TailSource:
    """A :class:`FeatureSource` view of the newest rows of another
    source: positions ``1..n`` map to base positions
    ``offset+1..offset+n`` (the 1-based dense position space every
    source speaks).  The sliding fine-tune window, without copying."""

    def __init__(self, base, offset: int, n: int) -> None:
        self._base = base
        self._offset = int(offset)
        self._n = int(n)

    @property
    def x_fields(self) -> Tuple[str, ...]:
        return tuple(self._base.x_fields)

    def __len__(self) -> int:
        return self._n

    def fetch(self, ids: Sequence[int]) -> np.ndarray:
        return self._base.fetch([self._offset + int(i) for i in ids])

    def fetch_targets(self, ids: Sequence[int]) -> np.ndarray:
        return self._base.fetch_targets([self._offset + int(i) for i in ids])


def gateway_publisher(
    gateway, *, require_eval: Optional[Callable[[Any], Tuple[bool, dict]]] = None
) -> Callable[[Any], Tuple[bool, Dict[str, Any]]]:
    """Publish rounds into a solo :class:`FleetGateway`.

    ``require_eval`` is the same guardrail contract
    ``FleetRouter.broadcast_hot_swap`` takes (e.g.
    :class:`fmda_tpu.eval.shadow.ShadowEvaluator`): candidate params in,
    ``(ok, detail)`` out — a refusal keeps the incumbent serving."""

    def publish(params) -> Tuple[bool, Dict[str, Any]]:
        if require_eval is not None:
            ok, detail = require_eval(params)
            if not ok:
                return False, dict(detail)
        version = gateway.hot_swap(params)
        return True, {"version": int(version)}

    return publish


def router_publisher(
    router, *, require_eval: Optional[Callable[[Any], Tuple[bool, dict]]] = None
) -> Callable[[Any], Tuple[bool, Dict[str, Any]]]:
    """Publish rounds fleet-wide via ``broadcast_hot_swap`` (the router
    runs the guardrail itself and counts/publishes refusals)."""

    def publish(params) -> Tuple[bool, Dict[str, Any]]:
        told = router.broadcast_hot_swap(params, require_eval=require_eval)
        return told > 0, {"workers_told": int(told)}

    return publish


class ContinuousTrainer:
    """Sliding-window fine-tuning over a live warehouse.

    Parameters
    ----------
    warehouse:
        Any warehouse speaking the :class:`FeatureSource` protocol plus
        ``iter_row_chunks(follow=...)`` (both backends do).
    model_cfg / train_cfg:
        The serving model family (the param tree MUST match what the
        serving pool was built with, or the hot swap would rebind to a
        mismatched tree) and the ``[train]`` knobs — the
        ``continuous_*`` fields drive this loop.
    publish:
        ``params -> (accepted, detail)``; see :func:`gateway_publisher`
        / :func:`router_publisher`.  None = checkpoints only.
    wait_fn:
        Called between empty tail polls (default: wall sleep of
        ``train.continuous_poll_s``).  Tests inject the row generator
        here and never sleep.
    """

    def __init__(
        self,
        warehouse,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig,
        *,
        checkpoint_dir: str,
        publish: Optional[Callable[[Any], Tuple[bool, Dict[str, Any]]]] = None,
        bid_levels: int = 0,
        ask_levels: int = 0,
        drift_bins: int = 16,
        target_lead: int = 0,
        mesh=None,
        dp_axis: str = "dp",
        wait_fn: Optional[Callable[[], None]] = None,
        chunk: int = 1024,
    ) -> None:
        self.warehouse = warehouse
        self.train_cfg = train_cfg
        self.checkpoint_dir = checkpoint_dir
        self.publish = publish
        self.bid_levels = bid_levels
        self.ask_levels = ask_levels
        self.drift_bins = drift_bins
        self.target_lead = target_lead
        self.chunk = int(chunk)
        self._wait_fn = wait_fn
        self._stop = threading.Event()
        # class-imbalance weights are computed ONCE, from the history
        # available at loop start: they are closed-over constants of the
        # compiled step, and re-deriving them per round would mean a new
        # program (a recompile) every round — the loop pins zero
        weight, pos_weight = (None, None)
        if len(warehouse) > 0:
            try:
                weight, pos_weight = imbalance_weights_from_source(warehouse)
            except (ValueError, ZeroDivisionError):
                log.warning("imbalance weights unavailable — unweighted BCE")
        self.trainer = Trainer(
            model_cfg, train_cfg,
            weight=weight, pos_weight=pos_weight,
            mesh=mesh, dp_axis=dp_axis,
        )
        self._state: Optional[TrainState] = None
        self.checkpoints: List[str] = []
        self.rounds = 0
        self.rows_seen = 0
        self.swaps_accepted = 0
        self.swaps_refused = 0
        self.last_metrics: Optional[Dict[str, float]] = None

    # -- control ------------------------------------------------------------

    def stop(self) -> None:
        """Ask a running :meth:`run` to come home: the tail aborts at
        the next poll, a round in flight completes (a half-applied
        optimizer step is worse than a late stop), then run() returns."""
        self._stop.set()

    def _wait(self) -> None:
        if self._stop.is_set():
            raise _Stopped()
        if self._wait_fn is not None:
            self._wait_fn()
        else:
            import time as _time

            _time.sleep(self.train_cfg.continuous_poll_s)
        if self._stop.is_set():
            raise _Stopped()

    # -- the loop ------------------------------------------------------------

    def run(
        self,
        *,
        max_rounds: Optional[int] = None,
        initial_state: Optional[TrainState] = None,
    ) -> Dict[str, Any]:
        """Tail → fine-tune → checkpoint → publish, until the warehouse
        quiesces (``continuous_follow_polls`` consecutive empty polls),
        ``max_rounds`` rounds have run, or :meth:`stop` is called.
        Returns the loop summary (also the shape ``serve-fleet
        --continuous-train`` reports)."""
        tc = self.train_cfg
        self._state = initial_state
        budget = max_rounds if max_rounds is not None else 0
        fresh = 0
        tail = self.warehouse.iter_row_chunks(
            chunk=self.chunk,
            follow=tc.continuous_follow_polls,
            poll_wait=self._wait,
        )
        try:
            for _ts, rows in tail:
                fresh += len(rows)
                self.rows_seen += len(rows)
                if fresh < tc.continuous_min_rows:
                    continue
                if self._round():
                    fresh = 0
                if self._stop.is_set():
                    break
                if budget and self.rounds >= budget:
                    break
        except _Stopped:
            pass
        finally:
            tail.close()
        # the tail quiesced (or the budget hit) with fresh rows still
        # untrained: drain them into one final round so a bounded run
        # always covers every row it saw
        if fresh >= 1 and not self._stop.is_set() \
                and not (budget and self.rounds >= budget):
            self._round()
        return self.summary()

    def _round(self) -> bool:
        """One fine-tune round over the sliding tail window.  False =
        skipped (window still too short to window/chunk)."""
        tc = self.train_cfg
        n = len(self.warehouse)
        lo = max(0, n - tc.continuous_window_rows)
        source = TailSource(self.warehouse, lo, n - lo)
        # a round needs at least one full chunk of windows
        if len(source) < tc.chunk_size + tc.window:
            log.info(
                "round skipped: window has %d rows, need >= %d",
                len(source), tc.chunk_size + tc.window)
            return False
        from fmda_tpu.obs.registry import default_registry

        import time as _time

        reg = default_registry()
        t0 = _time.perf_counter()
        state, history, dataset = self.trainer.fit(
            source,
            epochs=tc.continuous_epochs,
            bid_levels=self.bid_levels,
            ask_levels=self.ask_levels,
            initial_state=self._state,
        )
        self._state = state
        if self.rounds == 0:
            # round 1 carried the compiles; from here every compile is a
            # contract violation the ledger counts
            self.trainer.mark_warm()
        self.rounds += 1
        reg.counter("continuous_rounds_total").inc()
        reg.histogram("continuous_round_seconds").observe(
            _time.perf_counter() - t0)
        last = history["train"][-1]
        self.last_metrics = {
            "loss": float(last.loss), "accuracy": float(last.accuracy)}
        from fmda_tpu.train.checkpoint import save_checkpoint

        ckpt = save_checkpoint(
            self.checkpoint_dir, state, dataset.final_norm_params)
        self.checkpoints.append(ckpt)
        self._write_profile(ckpt)
        if self.publish is not None:
            import jax

            accepted, detail = self.publish(jax.device_get(state.params))
            outcome = "accepted" if accepted else "refused"
            reg.counter("continuous_swaps_total", outcome=outcome).inc()
            if accepted:
                self.swaps_accepted += 1
            else:
                self.swaps_refused += 1
            log.info("round %d: swap %s %s", self.rounds, outcome, detail)
        return True

    def _write_profile(self, ckpt: str) -> None:
        """The drift-monitor baseline beside the checkpoint — same
        best-effort contract as the one-shot ``train`` command (a
        degenerate window must not kill the loop)."""
        from fmda_tpu.eval.drift import (
            build_profile, profile_path_for, save_profile)

        try:
            wh = self.warehouse
            n = len(wh)
            ids = list(range(max(1, n - 4096 + 1), n + 1))
            rows = wh.fetch(ids)
            targets = (
                wh.fetch_targets(ids) if n > self.target_lead else None)
            profile = build_profile(
                rows, targets, bins=self.drift_bins,
                columns=list(wh.x_fields))
            save_profile(profile_path_for(ckpt), profile)
        except (ValueError, IndexError, OSError) as e:
            log.warning("quality profile not written beside %s: %s", ckpt, e)

    def summary(self) -> Dict[str, Any]:
        return {
            "rounds": self.rounds,
            "rows_seen": self.rows_seen,
            "checkpoints": list(self.checkpoints),
            "swaps_accepted": self.swaps_accepted,
            "swaps_refused": self.swaps_refused,
            "trainer_unexpected_recompiles":
                self.trainer.unexpected_recompiles,
            "last_metrics": self.last_metrics,
        }
