"""Multi-ticker shared-encoder training (north-star config 2).

The reference trains on exactly one ticker (SPY hard-coded,
producer.py:262).  The scale-out config batches windows from many tickers
through one shared encoder: every ticker contributes its own chunked,
per-ticker-normalized windows (windows never span tickers), and batches
interleave tickers so each step's gradient mixes instruments — on TPU this
just makes the batch dimension bigger, which is exactly what the MXU wants.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

import numpy as np

from fmda_tpu.data.normalize import NormParams
from fmda_tpu.data.pipeline import Batch, ChunkDataset, WindowBatches
from fmda_tpu.data.source import FeatureSource


class MultiTickerDataset:
    """Per-ticker chunk datasets over a shared feature schema."""

    def __init__(
        self,
        sources: Dict[str, FeatureSource],
        chunk_size: int,
        window: int,
        *,
        bid_levels: int = 0,
        ask_levels: int = 0,
    ) -> None:
        if not sources:
            raise ValueError("no sources")
        fields = {tuple(s.x_fields) for s in sources.values()}
        if len(fields) != 1:
            raise ValueError(
                "tickers must share one feature schema (shared encoder); "
                f"got {len(fields)} distinct schemas"
            )
        self.tickers = tuple(sources)
        self.datasets: Dict[str, ChunkDataset] = {
            t: ChunkDataset(
                src, chunk_size, window,
                bid_levels=bid_levels, ask_levels=ask_levels,
            )
            for t, src in sources.items()
        }

    def splits(
        self, val_size: float, test_size: float
    ) -> Tuple[List[Tuple[str, int]], List[Tuple[str, int]], List[Tuple[str, int]]]:
        """Per-ticker chunk splits, interleaved across tickers so every
        epoch pass mixes instruments."""
        train: List[Tuple[str, int]] = []
        val: List[Tuple[str, int]] = []
        test: List[Tuple[str, int]] = []
        per_ticker = {
            t: ds.split(val_size, test_size) for t, ds in self.datasets.items()
        }
        def interleave(select) -> List[Tuple[str, int]]:
            out: List[Tuple[str, int]] = []
            queues = {t: list(select(s)) for t, s in per_ticker.items()}
            while any(queues.values()):
                for t in self.tickers:
                    if queues[t]:
                        out.append((t, queues[t].pop(0)))
            return out

        return (
            interleave(lambda s: s[0]),
            interleave(lambda s: s[1]),
            interleave(lambda s: s[2]),
        )

    def batches(
        self, ticker: str, chunk_idx: int, batch_size: int
    ) -> WindowBatches:
        return WindowBatches(self.datasets[ticker], chunk_idx, batch_size)

    def rounds(
        self, chunks: List[Tuple[str, int]]
    ) -> List[Dict[str, int]]:
        """Regroup an interleaved ``(ticker, chunk)`` list (as produced by
        :meth:`splits`) into *rounds*: round ``r`` holds the r-th listed
        chunk of every ticker that still has one.  Rounds are the unit of
        mixed-composition training — see :meth:`mixed_batches`."""
        seen: Dict[str, int] = {t: 0 for t in self.tickers}
        rounds: List[Dict[str, int]] = []
        for ticker, chunk_idx in chunks:
            r = seen[ticker]
            seen[ticker] = r + 1
            while len(rounds) <= r:
                rounds.append({})
            rounds[r][ticker] = chunk_idx
        return rounds

    def mixed_batches(
        self, round_chunks: Dict[str, int], per_ticker: int
    ) -> Iterator[Batch]:
        """Fixed-shape batches mixing every ticker in one step — the
        north-star composition (50 tickers x 16 windows/step): each batch
        concatenates ``per_ticker`` windows from every ticker's chunk of
        this round, each ticker normalized with its own chunk stats.
        Every batch has shape ``(len(tickers) * per_ticker, ...)``
        regardless of which tickers are present or exhausted (absent
        slots are zero-filled with mask 0), so one jitted step serves the
        whole run.  On TPU the mixed batch is simply a bigger batch
        dimension — exactly what the MXU wants."""
        iters: Dict[str, Iterator[Batch]] = {
            t: iter(WindowBatches(self.datasets[t], c, per_ticker))
            for t, c in round_chunks.items()
        }
        # shape donors from any participating dataset
        any_ds = self.datasets[next(iter(round_chunks))]
        window = any_ds.window
        n_feat = len(any_ds.source.x_fields)
        n_cls = any_ds.source.fetch_targets([any_ds.window]).shape[-1]
        zero = Batch(
            x=np.zeros((per_ticker, window, n_feat), np.float32),
            y=np.zeros((per_ticker, n_cls), np.float32),
            mask=np.zeros(per_ticker, np.float32),
        )
        while iters:
            parts: List[Batch] = []
            alive = False
            for t in self.tickers:
                it = iters.get(t)
                part = zero
                if it is not None:
                    try:
                        part = next(it)
                        alive = True
                    except StopIteration:
                        iters.pop(t)
                parts.append(part)
            if not alive:
                return
            yield Batch(
                x=np.concatenate([p.x for p in parts]),
                y=np.concatenate([p.y for p in parts]),
                mask=np.concatenate([p.mask for p in parts]),
            )

    def final_norm_params(self) -> Dict[str, NormParams]:
        """Per-ticker serving norm stats (each instrument has its own
        scale; sharing one min/max across tickers would wash out FX vs
        equity magnitudes)."""
        return {t: ds.final_norm_params for t, ds in self.datasets.items()}
