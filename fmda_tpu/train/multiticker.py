"""Multi-ticker shared-encoder training (north-star config 2).

The reference trains on exactly one ticker (SPY hard-coded,
producer.py:262).  The scale-out config batches windows from many tickers
through one shared encoder: every ticker contributes its own chunked,
per-ticker-normalized windows (windows never span tickers), and batches
interleave tickers so each step's gradient mixes instruments — on TPU this
just makes the batch dimension bigger, which is exactly what the MXU wants.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from fmda_tpu.data.normalize import NormParams
from fmda_tpu.data.pipeline import ChunkDataset, WindowBatches
from fmda_tpu.data.source import FeatureSource


class MultiTickerDataset:
    """Per-ticker chunk datasets over a shared feature schema."""

    def __init__(
        self,
        sources: Dict[str, FeatureSource],
        chunk_size: int,
        window: int,
        *,
        bid_levels: int = 0,
        ask_levels: int = 0,
    ) -> None:
        if not sources:
            raise ValueError("no sources")
        fields = {tuple(s.x_fields) for s in sources.values()}
        if len(fields) != 1:
            raise ValueError(
                "tickers must share one feature schema (shared encoder); "
                f"got {len(fields)} distinct schemas"
            )
        self.tickers = tuple(sources)
        self.datasets: Dict[str, ChunkDataset] = {
            t: ChunkDataset(
                src, chunk_size, window,
                bid_levels=bid_levels, ask_levels=ask_levels,
            )
            for t, src in sources.items()
        }

    def splits(
        self, val_size: float, test_size: float
    ) -> Tuple[List[Tuple[str, int]], List[Tuple[str, int]], List[Tuple[str, int]]]:
        """Per-ticker chunk splits, interleaved across tickers so every
        epoch pass mixes instruments."""
        train: List[Tuple[str, int]] = []
        val: List[Tuple[str, int]] = []
        test: List[Tuple[str, int]] = []
        per_ticker = {
            t: ds.split(val_size, test_size) for t, ds in self.datasets.items()
        }
        def interleave(select) -> List[Tuple[str, int]]:
            out: List[Tuple[str, int]] = []
            queues = {t: list(select(s)) for t, s in per_ticker.items()}
            while any(queues.values()):
                for t in self.tickers:
                    if queues[t]:
                        out.append((t, queues[t].pop(0)))
            return out

        return (
            interleave(lambda s: s[0]),
            interleave(lambda s: s[1]),
            interleave(lambda s: s[2]),
        )

    def batches(
        self, ticker: str, chunk_idx: int, batch_size: int
    ) -> WindowBatches:
        return WindowBatches(self.datasets[ticker], chunk_idx, batch_size)

    def final_norm_params(self) -> Dict[str, NormParams]:
        """Per-ticker serving norm stats (each instrument has its own
        scale; sharing one min/max across tickers would wash out FX vs
        equity magnitudes)."""
        return {t: ds.final_norm_params for t, ds in self.datasets.items()}
