"""Training reports: learning curves + per-label confusion heatmaps.

The reference renders these inline in the training notebook (learning
curves cell 30, validation confusion heatmaps cells 31/37) and they are its
only published quality evidence; here they are a library call over the
history/confusion structures the :class:`~fmda_tpu.train.trainer.Trainer`
already returns, writing PNG/SVG files an experiment can commit.

matplotlib is imported lazily and is NOT a package dependency — these are
host-side report artifacts, nothing device-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from fmda_tpu.config import TARGET_COLUMNS
from fmda_tpu.eval.metrics import StreamingCounts, batch_counts


def history_table(history: Dict[str, List]) -> str:
    """Markdown table of per-epoch train/val metrics."""
    lines = [
        "| epoch | train loss | train acc | train Hamming | val acc | val Hamming |",
        "|---|---|---|---|---|---|",
    ]
    for i, (tr, va) in enumerate(zip(history["train"], history["val"])):
        lines.append(
            f"| {i + 1} | {tr.loss:.4f} | {tr.accuracy:.4f} | "
            f"{tr.hamming:.4f} | {va.accuracy:.4f} | {va.hamming:.4f} |"
        )
    return "\n".join(lines)


def offline_quality(
    probabilities: np.ndarray,
    targets: np.ndarray,
    *,
    threshold: float = 0.5,
) -> StreamingCounts:
    """Fold a whole offline evaluation split into the SAME sufficient
    statistics the live label-join evaluator accumulates
    (:class:`fmda_tpu.eval.metrics.StreamingCounts`), so an offline
    report and the ``/quality`` endpoint can never disagree on metric
    definitions — one numpy vocabulary, two call sites."""
    return batch_counts(probabilities, targets, threshold=threshold)


def quality_table(
    counts: StreamingCounts,
    labels: Sequence[str] = TARGET_COLUMNS,
    *,
    beta: float = 0.5,
    title: Optional[str] = None,
) -> str:
    """Markdown quality report over shared streaming counts.

    Renders whatever a :class:`StreamingCounts` holds — an offline split
    folded by :func:`offline_quality` or a snapshot pulled from the live
    evaluator's per-version accumulators — so the offline and online
    reports are the same table over the same arithmetic.
    """
    summary = counts.summary(beta)
    confusion = counts.confusion()
    lines = []
    if title:
        lines.append(f"**{title}** — n={summary['n']}, "
                     f"subset accuracy {summary['subset_accuracy']:.4f}, "
                     f"Hamming loss {summary['hamming_loss']:.4f}")
        lines.append("")
    lines += [
        f"| label | F{beta:g} | tp | fp | fn | tn |",
        "|---|---|---|---|---|---|",
    ]
    for i, label in enumerate(labels):
        (tn, fp), (fn, tp) = confusion[i]
        lines.append(
            f"| {label} | {summary['fbeta'][i]:.4f} | {int(tp)} | "
            f"{int(fp)} | {int(fn)} | {int(tn)} |"
        )
    return "\n".join(lines)


def plot_history(history: Dict[str, List], path: str) -> str:
    """Learning curves (loss, subset accuracy, Hamming loss) to ``path``."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    epochs = np.arange(1, len(history["train"]) + 1)
    fig, axes = plt.subplots(1, 3, figsize=(13, 3.6))
    axes[0].plot(epochs, [m.loss for m in history["train"]], label="train")
    axes[0].plot(epochs, [m.loss for m in history["val"]], label="val")
    axes[0].set_title("weighted BCE loss")
    axes[1].plot(epochs, [m.accuracy for m in history["train"]], label="train")
    axes[1].plot(epochs, [m.accuracy for m in history["val"]], label="val")
    axes[1].set_title("subset accuracy")
    axes[2].plot(epochs, [m.hamming for m in history["train"]], label="train")
    axes[2].plot(epochs, [m.hamming for m in history["val"]], label="val")
    axes[2].set_title("Hamming loss")
    for ax in axes:
        ax.set_xlabel("epoch")
        ax.grid(True, alpha=0.3)
        ax.legend()
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path


def plot_confusion(
    confusion: np.ndarray,
    path: str,
    labels: Sequence[str] = TARGET_COLUMNS,
) -> str:
    """Per-label 2x2 confusion heatmaps (reference notebook cells 31/37).

    ``confusion``: (n_labels, 2, 2) as returned by ``Trainer.evaluate``.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    n = len(labels)
    fig, axes = plt.subplots(1, n, figsize=(3.2 * n, 3.2))
    if n == 1:
        axes = [axes]
    for ax, label, cm in zip(axes, labels, confusion):
        ax.imshow(cm, cmap="Blues")
        for i in range(2):
            for j in range(2):
                ax.text(j, i, f"{int(cm[i, j])}", ha="center", va="center",
                        color="black")
        ax.set_title(label)
        ax.set_xticks([0, 1], ["pred 0", "pred 1"])
        ax.set_yticks([0, 1], ["true 0", "true 1"])
    fig.tight_layout()
    fig.savefig(path, dpi=110)
    plt.close(fig)
    return path
