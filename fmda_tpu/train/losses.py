"""Losses and class-imbalance weighting.

The reference trains with ``BCEWithLogitsLoss(weight=[N/n_c],
pos_weight=[(N-n_c)/n_c])`` (training notebook cells 13-16, 29).  The same
math here, as a pure jnp function with optional padded-example masking
(fixed-shape batches on TPU pad the tail; padded rows must not contribute).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def class_weights(label_counts: np.ndarray, n_examples: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-class (weight, pos_weight) from positive-label counts.

    weight_c = N / n_c;  pos_weight_c = (N - n_c) / n_c  (notebook cells 13-16).
    """
    counts = np.asarray(label_counts, np.float64)
    weight = n_examples / counts
    pos_weight = (n_examples - counts) / counts
    return weight.astype(np.float32), pos_weight.astype(np.float32)


def weighted_bce_with_logits(
    logits: jax.Array,
    targets: jax.Array,
    *,
    weight: Optional[jax.Array] = None,
    pos_weight: Optional[jax.Array] = None,
    example_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean weighted binary cross-entropy on logits (torch semantics).

    ``l = -w * [ pw * y * log(sigmoid(x)) + (1-y) * log(1 - sigmoid(x)) ]``
    reduced by mean over all (valid) elements; numerically stable via
    log-sigmoid.
    """
    targets = targets.astype(logits.dtype)
    log_p = jax.nn.log_sigmoid(logits)
    log_not_p = jax.nn.log_sigmoid(-logits)
    pw = pos_weight if pos_weight is not None else 1.0
    per_elem = -(pw * targets * log_p + (1.0 - targets) * log_not_p)
    if weight is not None:
        per_elem = per_elem * weight
    if example_mask is None:
        return jnp.mean(per_elem)
    m = example_mask.astype(per_elem.dtype)[:, None]
    denom = jnp.maximum(jnp.sum(m) * per_elem.shape[-1], 1.0)
    return jnp.sum(per_elem * m) / denom


def weighted_bce_sums(
    logits: jax.Array,
    targets: jax.Array,
    *,
    weight: Optional[jax.Array] = None,
    pos_weight: Optional[jax.Array] = None,
    example_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Unnormalized (loss_sum, element_count) for gradient accumulation.

    The masked mean above is ``sum / max(valid_rows * n_classes, 1)`` — a
    *global* normalizer, so a K-way microbatch split cannot just average
    per-microbatch means (partial tail masks would skew it).  Accumulating
    these sums and counts across microbatches and dividing once recovers
    the full-batch loss (and, by linearity of the gradient, the
    full-batch gradient) exactly up to float re-association
    (docs/training.md "Accumulation math").
    """
    targets = targets.astype(logits.dtype)
    log_p = jax.nn.log_sigmoid(logits)
    log_not_p = jax.nn.log_sigmoid(-logits)
    pw = pos_weight if pos_weight is not None else 1.0
    per_elem = -(pw * targets * log_p + (1.0 - targets) * log_not_p)
    if weight is not None:
        per_elem = per_elem * weight
    if example_mask is None:
        n = float(per_elem.shape[0] * per_elem.shape[-1])
        return jnp.sum(per_elem), jnp.asarray(n, per_elem.dtype)
    m = example_mask.astype(per_elem.dtype)[:, None]
    return jnp.sum(per_elem * m), jnp.sum(m) * per_elem.shape[-1]
