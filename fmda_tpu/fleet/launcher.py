"""Single-command local fleet topology: workers spawned, router inline.

``launch_local_fleet`` builds the whole multi-host topology on one
machine for benches, tests, and demos, with each tier in its **own
process** (own GIL — a shared interpreter would serialize bus frame
handling behind the load driver and flatten the scaling the topology
exists to buy):

- the calling process runs the **router** and hosts the **control bus**
  behind a :class:`~fmda_tpu.fleet.wire.BusServer` (membership +
  migrated state — low-rate traffic);
- N **worker** processes (``serve-fleet --role worker``) build
  identical models from the shared seed (same machine, same jax —
  deterministic init), connect a SocketBus for control, and each host
  their OWN data-plane bus (inbox + results), announced in their
  heartbeats — the router links to every worker directly and the
  worker's serving hot loop never crosses a socket;
- the launcher blocks until membership is complete, so bootstrap joins
  never migrate anything.

The launcher is router-role code: no jax (the workers own the
accelerator math in their own processes).
"""

from __future__ import annotations

import logging
import os
import re
import subprocess
import sys
import tempfile
import time
from dataclasses import replace as dc_replace
from typing import Dict, List, Optional, Sequence

from fmda_tpu.config import (
    FleetTopologyConfig,
    FrameworkConfig,
    fleet_topics,
)
from fmda_tpu.fleet.router import FleetRouter
from fmda_tpu.fleet.wire import BusServer

log = logging.getLogger("fmda_tpu.fleet")


def spawn_supported(python: str = sys.executable) -> bool:
    """Can this host spawn worker subprocesses at all?  (Sandboxed CI
    hosts sometimes cannot — the multihost bench reports ``skipped``
    instead of erroring there.)"""
    try:
        proc = subprocess.run(
            [python, "-c", "pass"], timeout=60,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return proc.returncode == 0
    except Exception:  # noqa: BLE001 — loss-free: a capability probe; any failure means "no"
        return False


def _build_local_bus(config: FrameworkConfig, topics: Sequence[str]):
    """NativeBus when buildable (the C++ log is the production-shaped
    local broker), InProcessBus otherwise — same fallback contract as
    :func:`fmda_tpu.app.default_bus`, with the fleet topics added and
    the arena sized for deep tick backlogs."""
    try:
        from fmda_tpu.stream.native_bus import NativeBus, native_available

        if native_available():
            return NativeBus(
                topics,
                arena_bytes=config.fleet.bus_arena_bytes,
                max_records=config.bus.capacity)
    except Exception as e:  # noqa: BLE001 — loss-free: loud fallback to InProcessBus, never a failed startup
        log.warning("native bus unavailable (%s); using InProcessBus", e)
    from fmda_tpu.stream.bus import InProcessBus

    return InProcessBus(topics, capacity=config.bus.capacity)


class LocalFleet:
    """A running local topology: workers spawned, router inline."""

    def __init__(
        self,
        *,
        router: FleetRouter,
        server,
        bus,
        procs: List[subprocess.Popen],
        worker_ids: List[str],
        log_dir: str,
        worker_argv: Optional[Dict[str, List[str]]] = None,
        repo_root: Optional[str] = None,
    ) -> None:
        self.router = router
        self.server = server
        self.bus = bus
        self.procs = procs
        self.worker_ids = worker_ids
        self.log_dir = log_dir
        #: exact spawn command per worker id — the chaos soak revives a
        #: killed worker by replaying it (a fresh incarnation: same id,
        #: fresh state, hellos its own way back into membership)
        self.worker_argv = worker_argv or {}
        self.repo_root = repo_root

    def proc_for(self, worker_id: str) -> Optional[subprocess.Popen]:
        try:
            return self.procs[self.worker_ids.index(worker_id)]
        except ValueError:  # loss-free: unknown id means "no process"
            return None

    def kill_worker(self, worker_id: str) -> bool:
        """SIGKILL one worker process — no drain, no goodbye: the
        silent-death failure the heartbeat timeout exists to catch
        (the chaos soak's ``kill worker:<id>`` events land here)."""
        proc = self.proc_for(worker_id)
        if proc is None or proc.poll() is not None:
            return False
        proc.kill()
        proc.wait(timeout=10.0)
        log.warning("chaos: killed worker %s (pid %d)",
                    worker_id, proc.pid)
        return True

    def add_worker(self) -> Optional[str]:
        """Spawn ONE MORE worker process into the running topology (the
        autoscaler's scale-up actuation) — a fresh id, the same argv
        template as the bootstrap workers.  Non-blocking: the new
        worker hellos its own way into membership exactly like any
        join, so the caller's ordinary pump loop sees it arrive (and no
        results are consumed waiting here).  Returns the new worker id,
        or None when the topology can't grow (no argv template)."""
        if not self.worker_ids or self.repo_root is None:
            return None
        template = self.worker_argv.get(self.worker_ids[0])
        if template is None or "--worker-id" not in template:
            return None
        m = re.match(r"^(.*?)(\d+)$", self.worker_ids[0])
        prefix = m.group(1) if m else self.worker_ids[0]
        used = set()
        for wid in self.worker_ids:
            m = re.match(re.escape(prefix) + r"(\d+)$", wid)
            if m:
                used.add(int(m.group(1)))
        idx = 0
        while idx in used:
            # never reuse an id: revive_worker owns the same-id path,
            # and a retired id's goodbye may still be settling
            idx += 1
        wid = f"{prefix}{idx}"
        argv = list(template)
        argv[argv.index("--worker-id") + 1] = wid
        proc = _spawn(
            argv, os.path.join(self.log_dir, f"{wid}.log"),
            self.repo_root)
        self.worker_ids.append(wid)
        self.procs.append(proc)
        self.worker_argv[wid] = argv
        log.info("scale-up: spawned worker %s (pid %d)", wid, proc.pid)
        return wid

    def revive_worker(self, worker_id: str) -> bool:
        """Spawn a fresh incarnation of a killed worker (same id, same
        argv).  It hellos on its own; the router treats it as any other
        join — rebalance, fresh bus at offset 0 (the hello purges any
        saved resume position)."""
        argv = self.worker_argv.get(worker_id)
        proc = self.proc_for(worker_id)
        if argv is None or self.repo_root is None:
            return False
        if proc is not None and proc.poll() is None:
            return False  # still alive — nothing to revive
        new = _spawn(
            argv,
            os.path.join(self.log_dir, f"{worker_id}.revived.log"),
            self.repo_root)
        self.procs[self.worker_ids.index(worker_id)] = new
        log.warning("chaos: revived worker %s (pid %d)",
                    worker_id, new.pid)
        return True

    def __enter__(self) -> "LocalFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def shutdown(
        self, *, graceful: bool = True, timeout_s: float = 30.0
    ) -> Dict[str, dict]:
        """Stop the topology; returns the final per-worker stats (off
        their goodbye heartbeats).  Stragglers are terminated, then
        killed — shutdown always completes."""
        try:
            self.router.stop_workers(graceful=graceful)
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                self.router.pump()
                if all(p.poll() is not None for p in self.procs):
                    break
                time.sleep(0.05)
        # loss-free: shutdown path — the finally below still reaps
        # every process, and final stats come from the router's view
        except ConnectionError:
            log.warning("bus connection lost during shutdown")
        finally:
            for p in self.procs:
                if p.poll() is None:
                    p.terminate()
            for p in self.procs:
                if p.poll() is None:
                    try:
                        p.wait(timeout=5.0)
                    # loss-free: escalation, not a swallow — the kill
                    # below reaps the process that ignored terminate()
                    except subprocess.TimeoutExpired:
                        p.kill()
            self.router.close()
            self.server.stop()
        return self.router.worker_stats()

    def worker_logs(self) -> Dict[str, str]:
        """Captured stdout+stderr per spawned process (post-mortem)."""
        out = {}
        for name in self.worker_ids:
            path = os.path.join(self.log_dir, f"{name}.log")
            try:
                with open(path) as fh:
                    out[name] = fh.read()
            except OSError:  # loss-free: post-mortem probe; no log is ""
                out[name] = ""
        return out


def _spawn(argv: List[str], log_path: str, repo_root: str):
    log_fh = open(log_path, "w")
    proc = subprocess.Popen(
        argv, stdout=log_fh, stderr=subprocess.STDOUT, cwd=repo_root)
    log_fh.close()  # the child holds its own descriptor
    return proc


def launch_local_fleet(
    *,
    n_workers: int,
    config: Optional[FrameworkConfig] = None,
    hidden: int = 32,
    seed: int = 0,
    capacity_per_worker: Optional[int] = None,
    bucket_sizes: Optional[Sequence[int]] = None,
    max_linger_ms: Optional[float] = None,
    window: Optional[int] = None,
    trace_dir: Optional[str] = None,
    platform: str = "cpu",
    wait_timeout_s: float = 180.0,
    python: str = sys.executable,
    log_dir: Optional[str] = None,
    wrap_bus=None,
) -> LocalFleet:
    """Spawn the whole topology and block until every worker joined.

    Worker model/runtime knobs are passed on the command line so every
    process builds the identical serving stack; ``trace_dir`` enables
    tracing in every process with one ``--trace-out`` file per worker
    (merge with ``python -m fmda_tpu trace --merge <trace_dir>``).
    """
    config = config or FrameworkConfig()
    fleet_cfg: FleetTopologyConfig = dc_replace(
        config.fleet, n_workers=n_workers)
    worker_ids = [
        f"{fleet_cfg.worker_prefix}{i}" for i in range(n_workers)]
    log_dir = log_dir or tempfile.mkdtemp(prefix="fmda_fleet_")
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    # ship the WHOLE config to every worker process: topology knobs
    # (heartbeat cadence, grace windows — the chaos soak shortens them)
    # must match across the fleet, and CLI flags only cover the model/
    # batching subset
    from fmda_tpu.config import save_config

    config_path = os.path.join(log_dir, "fleet_config.json")
    save_config(config, config_path)

    # the router's own bus: the control plane, plus shared-mode inbox/
    # results topics so --shared-bus workers (and tests) still work
    from fmda_tpu.config import DEFAULT_TOPICS

    topics = tuple(DEFAULT_TOPICS) + fleet_topics(worker_ids)
    bus = _build_local_bus(config, topics)
    server = BusServer(bus, host=fleet_cfg.host, port=fleet_cfg.port,
                       wire_format=fleet_cfg.wire_format).start()
    address = server.address
    procs: List[subprocess.Popen] = []
    worker_argv: Dict[str, List[str]] = {}
    try:
        for wid in worker_ids:
            argv = [
                python, "-m", "fmda_tpu", "serve-fleet",
                "--role", "worker",
                "--platform", platform,
                "--worker-id", wid,
                "--connect", address,
                "--hidden", str(hidden),
                "--seed", str(seed),
                "--config", config_path,
            ]
            if capacity_per_worker is not None:
                argv += ["--sessions", str(capacity_per_worker)]
            if bucket_sizes is not None:
                argv += ["--bucket-sizes",
                         ",".join(str(b) for b in bucket_sizes)]
            if max_linger_ms is not None:
                argv += ["--max-linger-ms", str(max_linger_ms)]
            if window is not None:
                argv += ["--window", str(window)]
            if trace_dir:
                argv += ["--trace", "--trace-out",
                         os.path.join(trace_dir, f"{wid}.json")]
            worker_argv[wid] = argv
            procs.append(_spawn(
                argv, os.path.join(log_dir, f"{wid}.log"), repo_root))

        # `wrap_bus` interposes on the ROUTER's bus handle only (the
        # BusServer keeps serving the raw bus to workers) — the chaos
        # soak wraps a ChaosBus here so control-plane faults hit the
        # router without perturbing the workers' transport
        router = FleetRouter(
            wrap_bus(bus) if wrap_bus is not None else bus,
            fleet_cfg, n_features=config.features.n_features)

        def _sleep_and_check(dt: float) -> None:
            time.sleep(dt)
            for p, wid in zip(procs, worker_ids):
                if p.poll() is not None:
                    tail = ""
                    try:
                        with open(os.path.join(
                                log_dir, f"{wid}.log")) as fh:
                            tail = fh.read()[-2000:]
                    # loss-free: the log tail is best-effort garnish —
                    # the RuntimeError below still raises either way
                    except OSError:
                        pass
                    raise RuntimeError(
                        f"worker {wid} exited rc={p.returncode} before "
                        f"joining; log tail:\n{tail}")

        router.wait_for_workers(
            n_workers, timeout_s=wait_timeout_s,
            sleep_fn=_sleep_and_check)
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
        server.stop()
        raise
    return LocalFleet(
        router=router, server=server, bus=bus, procs=procs,
        worker_ids=worker_ids, log_dir=log_dir,
        worker_argv=worker_argv, repo_root=repo_root)
