"""The fleet router: session → owner routing, membership, live migration.

One :class:`FleetRouter` fronts N worker processes.  It owns the
session registry and the versioned :class:`~fmda_tpu.fleet.hashring
.OwnershipTable`; every session's ticks flow to its owner's inbox topic
in submission order, and results come back on the prediction topic.
The router is deliberately **model-free**: it never touches jax, numpy
math, or checkpoints — a bus-only host runs it (the tier-1 hygiene
check enforces no module-scope jax on this import path).

Data-plane topology
-------------------

The control plane (membership, migrated state) is one topic on the
router's bus.  The data plane (ticks in, results out) has two shapes:

- **shared bus** — every worker reads/writes the router's own bus (an
  in-process topology, or one external broker/Kafka).  Simple, but one
  broker serializes the whole fleet's hot path;
- **worker-hosted** — each worker serves its *own* bus (inbox + results)
  and announces its address in every heartbeat; the router connects a
  :class:`~fmda_tpu.fleet.wire.SocketBus` per worker and exchanges each
  pump's traffic in one batched round trip per worker.  The worker's
  serving loop then never crosses a socket, and data-plane capacity
  scales with the worker count — the partitions-move-with-their-owner
  shape (``serve-fleet --role worker`` does this by default).

Ordering and the migration protocol
-----------------------------------

Per-session tick order is preserved end to end by *in-band* sequencing,
never by timestamps:

1. the router is single-threaded per pump, so a session's ticks enter
   its owner's **FIFO inbox topic** in submission order;
2. the worker consumes its inbox in offset order and its embedded
   :class:`~fmda_tpu.runtime.gateway.FleetGateway` preserves per-session
   order through micro-batching (one row per session per flush);
3. migration markers ride the same inbox: a ``drain_session`` message
   enqueued *after* a session's last routed tick is necessarily
   processed after it.

Migrating session S from worker A to worker B (ownership-table change):

- the router stops routing S (new ticks **buffer** at the router,
  bounded + counted) and enqueues ``drain_session`` on A's inbox;
- A serves everything queued for S, exports S's carried state +
  sequence counter (bit-exact codec, :mod:`fmda_tpu.fleet.state`),
  publishes it on the control topic, and frees the slot;
- the router receives the state, enqueues ``open`` (with state) on B's
  inbox followed by the buffered ticks in order, and resumes routing.

No tick is dropped (buffered, not discarded), none is reordered (every
hop is FIFO), and none is duplicated (each tick is routed exactly once;
the state transfer carries the sequence counter so B continues A's
``seq`` stream).  A worker that dies *without* draining loses carried
state by definition — its sessions are reopened fresh on the new owner
(``sessions_lost_state`` counted) and ticks already in its inbox age
out as ``results_missing``: counted degradation, never silence.
"""

from __future__ import annotations

import itertools
import logging
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from fmda_tpu.chaos.inject import default_chaos
from fmda_tpu.config import (
    FleetTopologyConfig,
    TOPIC_FLEET_CONTROL,
    TOPIC_FLEET_PREDICTION,
    fleet_worker_topic,
)
from fmda_tpu.stream import codec
from fmda_tpu.fleet.hashring import OwnershipTable
from fmda_tpu.fleet.membership import GOODBYE, HEARTBEAT, HELLO, MembershipView
from fmda_tpu.fleet.state import (
    encode_norm,
    encode_param_tree,
    encode_row,
    to_legacy_msgs,
)
from fmda_tpu.obs.trace import default_tracer, now_ns
from fmda_tpu.runtime.metrics import RuntimeMetrics

log = logging.getLogger("fmda_tpu.fleet")

#: chaos injection (fmda_tpu.chaos): disabled = one branch per pump/link
_CHAOS = default_chaos()


class NoLiveWorkers(RuntimeError):
    """open_session on a fleet with an empty membership."""


@dataclass(frozen=True)
class FleetResult:
    """One served tick as observed at the router (mirrors the worker
    gateway's result, decoded off the prediction topic)."""

    session_id: str
    seq: int
    probabilities: np.ndarray
    labels: Tuple[str, ...]
    #: the serving weights that produced it (None before any hot swap
    #: — docs/replay.md "Hot swap"); the quality plane's join key.
    weights_version: Optional[int] = None


@dataclass
class _Session:
    """Router-side registry entry for one session."""

    session_id: str
    #: current owner worker id (None while orphaned — no live workers)
    owner: Optional[str]
    norm_wire: Optional[dict]
    #: next router-side sequence number (stays in lockstep with the
    #: owning gateway's ``seq`` because ticks are routed exactly once)
    next_seq: int = 0
    #: "active" = ticks route; "migrating" = ticks buffer until the
    #: pending open lands on the new owner
    status: str = "active"
    #: current migration id (stale session_state messages are ignored)
    mig: Optional[str] = None
    #: ticks buffered while migrating/orphaned, in submission order
    buffer: Deque[dict] = field(default_factory=deque)
    #: exported state that arrived while no worker could host it
    pending_state: Optional[dict] = None
    #: tenant / priority-class label (fmda_tpu.control QoS); rides every
    #: open so the owning gateway classifies the session's ticks
    tenant: Optional[str] = None


@dataclass
class _WorkerLink:
    """The router's data-plane connection to one worker's own bus."""

    address: str
    bus: object
    #: next fleet_prediction offset to read off this worker's bus
    results_offset: int = 0


class FleetRouter:
    """Routes a session space over live workers; drives migration."""

    def __init__(
        self,
        bus,
        config: Optional[FleetTopologyConfig] = None,
        *,
        n_features: int,
        metrics: Optional[RuntimeMetrics] = None,
        clock: Callable[[], float] = time.monotonic,
        control_topic: str = TOPIC_FLEET_CONTROL,
        prediction_topic: str = TOPIC_FLEET_PREDICTION,
        connect_fn: Optional[Callable[[str], object]] = None,
        from_end: bool = False,
    ) -> None:
        self.cfg = config or FleetTopologyConfig()
        self.bus = bus
        self.n_features = n_features
        self.metrics = metrics or RuntimeMetrics()
        self.clock = clock
        self.control_topic = control_topic
        self.prediction_topic = prediction_topic
        self.membership = MembershipView(
            self.cfg.heartbeat_timeout_s, clock=clock)
        self.table = OwnershipTable(0, (), self.cfg.hash_space)
        self._sessions: Dict[str, _Session] = {}
        #: lazy per-worker owned-session counts (None = recompute);
        #: invalidated at every registry/owner mutation
        self._owned_cache: Optional[Dict[str, int]] = None
        #: ids of every session whose carried state this router ever
        #: lost (owner died undrained → fresh reopen).  The chaos
        #: soak's bit-identity gate excludes exactly these — loss is
        #: judged by observation, not by which faults were planned (a
        #: falsely-reaped worker's sessions lose state just as really)
        self.lost_state_sessions: set = set()
        #: session ids whose status != "active" (migrating/orphaned) —
        #: maintained at every status transition so saturation checks
        #: and drain's are-we-done test never scan the whole registry
        self._migrating: set = set()
        #: leaving workers already sent their stop (idempotence; the
        #: leave mark itself stays until the goodbye arrives, so the
        #: stopping worker is never re-added to live())
        self._stops_sent: set = set()
        #: per-worker outgoing message batch, flushed each pump with one
        #: publish_many (one JSON pass + one transport call per worker)
        self._outgoing: Dict[str, List[dict]] = {}
        #: data-plane links to worker-hosted buses (absent for workers
        #: sharing this router's bus)
        self._links: Dict[str, _WorkerLink] = {}
        #: worker ids that ever announced a data-plane address: their
        #: outgoing traffic must never fall through to the shared bus
        #: while a link is down (their inbox lives on THEIR bus)
        self._linked_ever: set = set()
        #: worker ids whose outgoing batch sat out a link outage — their
        #: next delivery re-checks ticks against the in-flight table
        #: (aged ones are already counted lost and must not be served)
        self._held_outgoing: set = set()
        #: (worker_id, address) -> results_offset saved when a link
        #: drops on a TRANSIENT error: the worker's bus (and its
        #: retained results) are still there, so the re-link must
        #: resume where it left off — restarting at 0 would re-deliver
        #: every retained result as a duplicate.  A fresh incarnation
        #: announces itself with a hello, which purges these (its new
        #: bus restarts at offset 0).
        self._link_resume: Dict[Tuple[str, str], int] = {}
        #: (session, seq) -> (t_submit, trace_ref) for latency + loss
        #: accounting; insertion-ordered, aged out at result_timeout_s
        self._inflight: "OrderedDict[Tuple[str, int], tuple]" = OrderedDict()
        #: workers we asked for a session report (takeover) whose answer
        #: is still outstanding — one request in flight per worker
        self._report_pending: set = set()
        #: wire-dialect capability per worker, from the ``wire`` field
        #: its liveness messages carry (absent = pre-v2): decides per
        #: consumer whether outgoing payloads use columnar blocks/raw
        #: arrays or the pre-v2 shapes — on a shared broker the
        #: router's own link format says nothing about the consumer
        self._peer_wire: Dict[str, int] = {}
        #: last hot-swap version this router broadcast (bumped per
        #: broadcast unless the caller pins one)
        self._swap_version = 0
        #: worker -> weights_version it last acked (``weights_swapped``
        #: control messages) — the fleet's mixed-version window is the
        #: spread of these values, surfaced in :meth:`summary`
        self._worker_weights: Dict[str, int] = {}
        #: ``from_end=True`` is the RESTART posture (router failover,
        #: docs/chaos.md): skip the control topic's history — replaying
        #: hours-old hellos would resurrect dead workers at receipt-time
        #: liveness — and re-learn membership from the next beats; the
        #: session registry is rebuilt from worker session reports
        self._control = bus.consumer(control_topic, from_end=from_end)
        self._results = bus.consumer(prediction_topic, from_end=from_end)
        self._mig_ids = itertools.count(1)
        self._tracer = default_tracer()
        #: set while the whole topology is being stopped: membership
        #: churn then triggers NO migrations/reopens (every worker is
        #: exiting — moving sessions between them is wasted motion)
        self._stopping = False
        #: how to reach a worker-announced data-plane address
        if connect_fn is None:
            from fmda_tpu.fleet.wire import SocketBus

            wire_format = self.cfg.wire_format
            connect_fn = lambda addr: SocketBus.connect(  # noqa: E731
                addr, timeout_s=30.0, wire_format=wire_format)
        self._connect_fn = connect_fn

    # -- membership bootstrap ------------------------------------------------

    def wait_for_workers(
        self,
        n: int,
        *,
        timeout_s: float = 60.0,
        sleep_fn: Callable[[float], None] = time.sleep,
    ) -> List[str]:
        """Pump the control topic until ``n`` workers are live (the
        launcher calls this before admitting sessions, so bootstrap
        joins never trigger migrations)."""
        deadline = self.clock() + timeout_s
        while True:
            self._drain_control()
            if len(self.membership) >= n:
                return self.membership.live()
            if self.clock() >= deadline:
                raise RuntimeError(
                    f"only {self.membership.live()} of {n} workers "
                    f"joined within {timeout_s:.0f}s")
            sleep_fn(0.01)

    # -- session admission ---------------------------------------------------

    def open_session(
        self, session_id: str, norm=None, *,
        tenant: Optional[str] = None,
    ) -> None:
        """Admit a session: register it and route an ``open`` to its
        owner.  Raises :class:`NoLiveWorkers` when the fleet is empty —
        admission control stays loud, like the gateway's.

        ``tenant`` labels the session with its QoS priority class
        (fmda_tpu.control); the label follows the session through every
        migration and failover reopen."""
        if session_id in self._sessions:
            raise ValueError(f"session {session_id!r} already open")
        owner = self.table.owner_of(session_id)
        if owner is None:
            self.metrics.count("rejected_sessions")
            raise NoLiveWorkers(
                "no live workers to own sessions (did the fleet start? "
                "wait_for_workers bootstraps membership)")
        sess = _Session(session_id, owner, encode_norm(norm),
                        tenant=tenant)
        self._sessions[session_id] = sess
        self._enqueue(owner, self._open_msg(sess))
        self.metrics.count("sessions_opened")
        self._sessions_changed()

    def close_session(self, session_id: str) -> None:
        sess = self._sessions.pop(session_id, None)
        if sess is None:
            raise KeyError(f"no open session {session_id!r}")
        if sess.owner is not None and sess.status == "active":
            self._enqueue(
                sess.owner, {"kind": "close", "session": session_id})
        # stop tracking the dead incarnation's in-flight ticks NOW: a
        # reopen restarts seq at 0, and a stale (session, seq) key would
        # collide with the new stream's tracking
        stale = [k for k in self._inflight if k[0] == session_id]
        for k in stale:
            del self._inflight[k]
        if stale:
            self.metrics.count("inflight_dropped_on_close", len(stale))
        self._migrating.discard(session_id)
        self.metrics.count("sessions_closed")
        self._sessions_changed()

    def _open_msg(self, sess: _Session, state: Optional[dict] = None) -> dict:
        msg = {
            "kind": "open",
            "session": sess.session_id,
            "norm": sess.norm_wire,
            "seq": int(state["seq"]) if state is not None else sess.next_seq,
            # v2 requester: the worker may answer with columnar result
            # blocks (and raw-array state) — absent (a pre-v2 router),
            # it keeps the per-tick result dicts
            "wire": 2,
        }
        if state is not None:
            msg["state"] = state
        if sess.mig is not None:
            msg["mig"] = sess.mig
        if sess.tenant is not None:
            msg["tenant"] = sess.tenant
        return msg

    def session_tenant(self, session_id: str) -> Optional[str]:
        """An open session's tenant label (None when unlabeled)."""
        sess = self._sessions.get(session_id)
        if sess is None:
            raise KeyError(f"no open session {session_id!r}")
        return sess.tenant

    def _sessions_changed(self) -> None:
        self.metrics.gauge("active_sessions", len(self._sessions))
        self._owned_cache = None

    def _owned_counts(self) -> Dict[str, int]:
        """Per-worker owned-session counts, cached between registry
        mutations: takeover detection reads this on essentially every
        heartbeat, and a scan of the whole registry per beat would put
        O(sessions × workers / heartbeat_interval) on the pump loop."""
        counts = self._owned_cache
        if counts is None:
            counts = {}
            for s in self._sessions.values():
                if s.owner is not None:
                    counts[s.owner] = counts.get(s.owner, 0) + 1
            self._owned_cache = counts
        return counts

    # -- the request path ----------------------------------------------------

    def submit(self, session_id: str, row: np.ndarray) -> int:
        """Route one tick; returns its per-session sequence number.
        Migrating/orphaned sessions buffer (bounded + counted) instead
        of racing their state transfer."""
        sess = self._sessions.get(session_id)
        if sess is None:
            raise KeyError(f"no open session {session_id!r}")
        row = np.asarray(row, np.float32)
        if row.shape != (self.n_features,):
            raise ValueError(
                f"row shape {row.shape} != ({self.n_features},) for "
                f"session {session_id!r}")
        seq = sess.next_seq
        sess.next_seq = seq + 1
        msg = {
            "kind": "tick",
            "session": session_id,
            "row": encode_row(row),
            "seq": seq,
        }
        ref = self._tracer.maybe_trace()
        if ref is not None:
            msg["trace"] = ref.wire
        self._inflight[(session_id, seq)] = (self.clock(), ref)
        self.metrics.count("routed_ticks")
        if sess.status == "active" and sess.owner is not None:
            self._enqueue(sess.owner, msg)
        else:
            sess.buffer.append(msg)
            self.metrics.count("buffered_ticks")
            while len(sess.buffer) > self.cfg.migration_buffer_bound:
                shed = sess.buffer.popleft()
                self._inflight.pop(
                    (session_id, shed["seq"]), None)
                self.metrics.count("migration_buffer_shed")
        return seq

    @property
    def saturated(self) -> bool:
        """Router-side backpressure: too many unanswered ticks in
        flight (the fleet is behind — an unbounded inbox backlog would
        eventually outrun bus retention), or a migration buffer at its
        bound.  Well-behaved producers pump-and-wait instead of racing
        either limit.  O(migrating sessions), not O(all sessions) —
        this sits in front of every submit."""
        if len(self._inflight) >= self.cfg.max_inflight_ticks:
            return True
        if not self._migrating:
            return False
        bound = self.cfg.migration_buffer_bound
        return any(
            len(self._sessions[sid].buffer) >= bound
            for sid in self._migrating
            if sid in self._sessions
        )

    def _set_status(self, sess: _Session, status: str) -> None:
        # every owner handoff passes through here right after the
        # assignment (migration complete, reopen) — drop the cache with it
        self._owned_cache = None
        sess.status = status
        if status == "active":
            self._migrating.discard(sess.session_id)
        else:
            self._migrating.add(sess.session_id)
        self.metrics.gauge("migrating_sessions", len(self._migrating))

    def _enqueue(self, worker_id: str, msg: dict) -> None:
        self._outgoing.setdefault(worker_id, []).append(msg)

    # -- the serving loop ----------------------------------------------------

    def pump(self, *, force: bool = False) -> List[FleetResult]:
        """One router cycle: fold control messages (membership, migrated
        state), reap silent workers, exchange data with every worker
        (outgoing batch + results, one round trip per linked worker),
        and return the results that arrived.  ``force`` is accepted for
        gateway-API compatibility (the router has no deferred flushes —
        every pump flushes)."""
        del force
        if _CHAOS.enabled:
            # injection point "router.pump": delay/hang windows stall
            # the control loop (the slow-router shape)
            _CHAOS.check("router.pump")
        try:
            self._drain_control()
        except (ConnectionError, OSError) as e:
            # the control bus is down (broker blip): the router keeps
            # pumping its data links — membership just ages until the
            # bus returns.  Counted degradation, never abort.
            self.metrics.count("bus_errors")
            log.warning("control-plane poll failed: %s", e)
        dead = self.membership.reap()
        if dead:
            self.metrics.count("workers_dead", len(dead))
            for wid in dead:
                # resume=True: a falsely-reaped worker (long stall, not
                # death) re-joins via its next beat and must not re-read
                # its retained results from 0; a truly dead worker's
                # replacement hellos, which purges the saved position
                self._close_link(wid, resume=True)
                self._stops_sent.discard(wid)
                self._drop_outgoing(wid)
                self._report_pending.discard(wid)
            self._rebalance(f"worker death: {sorted(dead)}")
        # a migration completed this pump may have emptied a leaving
        # worker — release it now, not on the next membership change
        self._maybe_release_leaving()
        results = self._exchange_data()
        self._age_inflight()
        self.metrics.gauge("inflight_ticks", len(self._inflight))
        return results

    def drain(
        self,
        *,
        timeout_s: float = 60.0,
        sleep_fn: Callable[[float], None] = time.sleep,
    ) -> List[FleetResult]:
        """Pump until every routed tick has answered (or aged out) and
        no migration is mid-flight — the end-of-load / shutdown path.
        Bounded by ``timeout_s`` of *stall* (no progress), not of total
        wall clock: a busy fleet draining a deep backlog keeps going as
        long as results keep arriving."""
        results: List[FleetResult] = []
        last_progress = self.clock()
        outstanding = len(self._inflight)
        while True:
            got = self.pump()
            results.extend(got)
            if not self._inflight and not self._migrating:
                return results
            now = self.clock()
            if len(self._inflight) != outstanding or got:
                outstanding = len(self._inflight)
                last_progress = now
            elif now - last_progress > timeout_s:
                self.metrics.count("drain_stalled")
                log.warning(
                    "drain stalled: %d ticks unanswered after %.0fs "
                    "without progress", len(self._inflight), timeout_s)
                return results
            sleep_fn(0.002)

    # -- data-plane exchange -------------------------------------------------

    def _exchange_data(self) -> List[FleetResult]:
        """Flush every per-worker outgoing batch and collect results.

        Linked (worker-hosted-bus) workers get ONE round trip each:
        their tick batch and their results read share a batched frame —
        on high-syscall-latency hosts the round-trip count is the
        router's throughput ceiling (fmda_tpu.fleet.wire).  Workers on
        the shared bus are published/polled through it as a group.
        """
        outgoing, self._outgoing = self._outgoing, {}
        tracing = self._tracer.enabled
        rows: List[tuple] = []
        for wid, link in list(self._links.items()):
            msgs = outgoing.pop(wid, [])
            if wid in self._held_outgoing:
                # this batch sat out a link outage: ticks that aged into
                # results_missing while held must not be delivered now —
                # serving a written-off tick would count it twice
                self._held_outgoing.discard(wid)
                msgs = self._drop_aged_ticks(wid, msgs)
            t0_ns = now_ns() if tracing else 0
            t0 = self.clock()
            try:
                if _CHAOS.enabled:
                    # injection point "link:<wid>": a partition window
                    # raises here and exercises the REAL link-failure
                    # machinery below (drop, count, heartbeat re-link)
                    _CHAOS.check("link:" + wid)
                with self.metrics.timer.stage("route"):
                    batch = getattr(link.bus, "batch", None)
                    read_op = {
                        "op": "read",
                        "topic": self.prediction_topic,
                        "offset": link.results_offset,
                        "max_records": None,
                    }
                    # runs of consecutive ticks leave as columnar
                    # blocks: one contiguous (B, F) f32 array + one
                    # i64 seq column per run instead of B dicts —
                    # encoded once, at the link's negotiated format
                    # (fmda_tpu.stream.codec).  A link that negotiated
                    # down to JSON instead gets the full pre-v2
                    # payload shapes (bare-base64 rows, enveloped
                    # arrays), so a genuinely old peer still parses.
                    # Error/requeue paths keep the per-tick `msgs`.
                    wire_msgs = self._lower_for(
                        wid, link.bus, msgs, direct=True)
                    if batch is not None:
                        ops = []
                        if wire_msgs:
                            ops.append({
                                "op": "publish_many",
                                "topic": fleet_worker_topic(wid),
                                "values": wire_msgs,
                            })
                        ops.append(read_op)
                        resps = link.bus.batch(ops)
                        for op, resp in zip(ops[:-1], resps[:-1]):
                            if "err" in resp:
                                self.metrics.count(
                                    "routed_publish_errors", len(msgs))
                                log.error(
                                    "router: publish to %s failed: %s",
                                    wid, resp["err"])
                        link_rows = link.bus.unwrap_op(read_op, resps[-1])
                    else:
                        if wire_msgs:
                            link.bus.publish_many(
                                fleet_worker_topic(wid), wire_msgs)
                        link_rows = [
                            (r.offset, r.value) for r in link.bus.read(
                                self.prediction_topic,
                                link.results_offset)]
            except (ConnectionError, OSError) as e:
                # the worker's bus went away mid-exchange: drop the
                # link (a live worker's next heartbeat re-links it —
                # every beat carries the address; a dead worker's
                # silence confirms the death by timeout).  Ticks in the
                # failed frame are at-most-once — re-sending could
                # double-advance a recurrence — so they are counted
                # lost (any that actually landed still answer and are
                # matched; the rest age into results_missing).  Control
                # messages ARE idempotent (a duplicate open replaces
                # with identical state, a duplicate close/drain is
                # counted unknown), so they re-queue ahead of newer
                # traffic and ride the re-link: a transient blip can no
                # longer strand a migration on a lost drain marker.
                self.metrics.count("link_errors")
                keep = [m for m in msgs if m.get("kind") != "tick"]
                n_ticks = len(msgs) - len(keep)
                if n_ticks:
                    # lint: ignore[counted-loss] pre-count: these ticks stay in _inflight and age into results_missing, which the gate sums — summing both would double count
                    self.metrics.count("routed_ticks_lost", n_ticks)
                if keep:
                    self.metrics.count("control_requeued", len(keep))
                    self._outgoing[wid] = keep + self._outgoing.get(wid, [])
                log.warning("data link to %s failed: %s", wid, e)
                self._close_link(wid, resume=True)
                continue
            if msgs:
                self.metrics.observe("route", self.clock() - t0)
                if tracing:
                    t1_ns = now_ns()
                    for msg in msgs:
                        wire = msg.get("trace")
                        if wire is not None:
                            self._tracer.add_span_wire(
                                wire, "route", "bus", t0_ns, t1_ns)
            if link_rows:
                link.results_offset = int(link_rows[-1][0]) + 1
                rows.extend(link_rows)
        # whatever remains targets shared-bus workers (or stale ids
        # whose topic still exists on the shared bus)
        if outgoing:
            publish_many = getattr(self.bus, "publish_many", None)
            for wid, msgs in outgoing.items():
                if wid in self._linked_ever and wid not in self._links:
                    # a worker-hosted worker whose link is down: its
                    # inbox lives on ITS bus, not the shared one —
                    # hold the batch for the heartbeat-driven re-link
                    # (dropped + counted if the worker is declared
                    # dead instead).  Ticks that aged out of the
                    # in-flight table while held are dropped NOW: they
                    # are already counted results_missing, so late
                    # delivery would serve a tick the accounting wrote
                    # off (counted twice) — and keeping them would let
                    # a long partition grow the hold without bound,
                    # where dropping caps it at max_inflight_ticks.
                    held = self._drop_aged_ticks(wid, msgs)
                    if held:
                        self._held_outgoing.add(wid)
                        self._outgoing[wid] = \
                            held + self._outgoing.get(wid, [])
                    continue
                t0_ns = now_ns() if tracing else 0
                t0 = self.clock()
                try:
                    with self.metrics.timer.stage("route"):
                        topic = fleet_worker_topic(wid)
                        wire_msgs = self._lower_for(
                            wid, self.bus, msgs, direct=False)
                        if publish_many is not None:
                            publish_many(topic, wire_msgs)
                        else:
                            for msg in wire_msgs:
                                self.bus.publish(topic, msg)
                except KeyError:
                    self.metrics.count("routed_publish_errors", len(msgs))
                    log.error(
                        "router: no inbox topic for %s on the shared "
                        "bus", wid)
                    continue
                except (ConnectionError, OSError) as e:
                    # shared broker down: counted, the pump survives —
                    # the same contract as a link failure, including the
                    # requeue: ticks are at-most-once (counted lost, the
                    # unanswered ones age into results_missing), but
                    # idempotent control messages ride the broker's
                    # recovery — a blip must not strand a migration on a
                    # dropped drain marker or leave a reopen dark
                    self.metrics.count("bus_errors")
                    keep = [m for m in msgs if m.get("kind") != "tick"]
                    n_ticks = len(msgs) - len(keep)
                    if n_ticks:
                        # lint: ignore[counted-loss] pre-count: these ticks age into results_missing, the summed term (see the link-failure twin above)
                        self.metrics.count("routed_ticks_lost", n_ticks)
                    if keep:
                        self.metrics.count("control_requeued", len(keep))
                        self._outgoing[wid] = \
                            keep + self._outgoing.get(wid, [])
                    log.warning(
                        "router: shared-bus publish for %s failed: %s",
                        wid, e)
                    continue
                self.metrics.observe("route", self.clock() - t0)
                if tracing:
                    t1_ns = now_ns()
                    for msg in msgs:
                        wire = msg.get("trace")
                        if wire is not None:
                            self._tracer.add_span_wire(
                                wire, "route", "bus", t0_ns, t1_ns)
        # shared-bus results: skip the poll only when every live worker
        # is linked (then nothing ever lands on the shared topic)
        if (not self._links
                or any(wid not in self._links
                       for wid in self.membership.workers)):
            try:
                rows.extend(
                    (r.offset, r.value) for r in self._results.poll())
            except (ConnectionError, OSError) as e:
                self.metrics.count("bus_errors")
                log.warning("shared-bus results poll failed: %s", e)
        return self._fold_results(rows)

    def _lower_for(
        self, worker_id: str, bus, msgs: List[dict], *, direct: bool,
    ) -> List[dict]:
        """Outgoing batch in the consuming WORKER's wire dialect:
        columnar tick blocks + raw arrays for v2 peers, the full pre-v2
        payload shapes (bare-base64 rows, enveloped arrays) otherwise.
        A JSON-negotiated link always lowers (the ``wire_format=json``
        rollback must roll the dialect back too, and a pre-v2 direct
        peer can only ever be on a JSON link).  On a ``direct`` link the
        transport terminates at the worker, so a binary negotiation
        proves a v2 peer; on the shared bus the router's own broker
        link says nothing about the consumer, so the worker's declared
        capability decides (the ``wire`` field its liveness messages
        carry — absent means pre-v2)."""
        if not msgs:
            return msgs
        legacy = getattr(bus, "negotiated_format", None) == "json"
        if not direct:
            legacy = legacy or self._peer_wire.get(worker_id, 1) < 2
        return to_legacy_msgs(msgs) if legacy else codec.coalesce_ticks(msgs)

    def _ensure_link(self, worker_id: str, address: Optional[str]) -> None:
        """(Re)connect the data-plane link a worker announces."""
        if not address:
            return
        link = self._links.get(worker_id)
        if link is not None and link.address == address:
            return
        if link is not None:
            self._close_link(worker_id)
        try:
            bus = self._connect_fn(address)
        except (OSError, ConnectionError) as e:
            self.metrics.count("link_errors")
            log.error("cannot connect %s data bus at %s: %s",
                      worker_id, address, e)
            return
        resume = self._link_resume.pop((worker_id, address), None)
        if resume is None:
            # start at the bus's END, not 0: a fresh worker's bus is
            # empty (end == 0, identical), but a TAKEOVER (this router
            # restarted while the worker kept serving) must not re-read
            # every result the dead router already consumed — those
            # ticks were never routed by this incarnation and would
            # only flood results_unmatched
            resume = 0
            end = getattr(bus, "end_offset", None)
            if end is not None:
                try:
                    resume = int(end(self.prediction_topic))
                # loss-free: probe fallback — resuming from 0 re-reads results (harmless duplicates, counted unmatched), never drops any
                except (ConnectionError, OSError, RuntimeError, KeyError):
                    resume = 0
        self._links[worker_id] = _WorkerLink(
            address=address, bus=bus, results_offset=resume)
        self._linked_ever.add(worker_id)
        log.info("data link to %s at %s (results from %d)",
                 worker_id, address, resume)

    def _close_link(self, worker_id: str, *, resume: bool = False) -> None:
        """Drop a worker's data link.  ``resume`` (transient link error:
        the worker's bus survives) saves the results read position so the
        heartbeat-driven re-link picks up where this one stopped; the
        default (leave/death/goodbye/shutdown — the process is gone)
        forgets it, because a replacement's bus restarts at offset 0."""
        link = self._links.pop(worker_id, None)
        if resume and link is not None:
            self._link_resume[(worker_id, link.address)] = \
                link.results_offset
        elif not resume:
            for key in [k for k in self._link_resume if k[0] == worker_id]:
                del self._link_resume[key]
        if link is not None:
            close = getattr(link.bus, "close", None)
            if close is not None:
                try:
                    close()
                except OSError:  # loss-free: teardown of a dead link
                    pass

    def _drop_aged_ticks(self, worker_id: str, msgs: List[dict]) -> List[dict]:
        """Filter ticks that aged out of the in-flight table from a
        batch held across a link outage: they are already counted
        ``results_missing``, so delivering them late would serve a tick
        the accounting wrote off (counted twice) — and dropping them
        caps a long partition's hold at ``max_inflight_ticks`` instead
        of letting it grow without bound.  Control messages always
        survive the hold (a migration must not strand on a dropped
        drain marker)."""
        now = self.clock()
        timeout = self.cfg.result_timeout_s
        kept = []
        for m in msgs:
            if m.get("kind") == "tick":
                entry = self._inflight.get((m["session"], m["seq"]))
                # expired-but-unswept ticks are dropped too: the sweep
                # at the end of this pump will count them, and a re-link
                # landing in the same pump must not deliver them first
                if entry is None or now - entry[0] > timeout:
                    continue
            kept.append(m)
        aged = len(msgs) - len(kept)
        if aged:
            # lint: ignore[counted-loss] these ticks already aged (or are aging this pump) into results_missing — this series is the diagnostic view, not the identity term
            self.metrics.count("routed_ticks_lost", aged)
            log.warning(
                "dropped %d held ticks for %s that aged out awaiting a "
                "re-link", aged, worker_id)
        return kept

    def _drop_outgoing(self, worker_id: str) -> None:
        """Discard a departed worker's pending batch (held for a
        re-link that will never happen) — counted, never silent; its
        sessions are reopened elsewhere by the same rebalance."""
        self._held_outgoing.discard(worker_id)
        msgs = self._outgoing.pop(worker_id, None)
        if not msgs:
            return
        n_ticks = sum(1 for m in msgs if m.get("kind") == "tick")
        if n_ticks:
            # lint: ignore[counted-loss] pre-count: the dropped ticks stay in _inflight and age into results_missing, the summed term
            self.metrics.count("routed_ticks_lost", n_ticks)
        # lint: ignore[counted-loss] counts MESSAGES (opens/closes/markers too), not ticks — the tick portion is accounted via results_missing above
        self.metrics.count("outgoing_dropped", len(msgs))
        log.warning(
            "dropped %d pending messages for departed worker %s "
            "(%d ticks)", len(msgs), worker_id, n_ticks)

    def _fold_results(self, rows) -> List[FleetResult]:
        results: List[FleetResult] = []
        flat: List[dict] = []
        for _offset, v in rows:
            if v.get("kind") == "result_block":
                # a columnar run (fmda_tpu.stream.codec.pack_results):
                # one (B, C) probability array + dictionary-encoded ids
                # expands back to per-result messages, bit-identical to
                # the per-tick dialect
                try:
                    flat.extend(codec.iter_results(v))
                except (KeyError, ValueError, TypeError):
                    self.metrics.count("results_undecodable")
                continue
            flat.append(v)
        for v in flat:
            sid, seq = v.get("session"), v.get("seq")
            if sid is None or seq is None:
                # not a result at all (a corrupted/foreign record on
                # the results topic) — count it, never crash on it
                self.metrics.count("results_undecodable")
                continue
            entry = self._inflight.pop((sid, seq), None)
            if entry is not None:
                t_submit, ref = entry
                self.metrics.observe("total", self.clock() - t_submit)
                if ref is not None:
                    self._tracer.finish_root(ref, "tick", "ingest", now_ns())
            else:
                # a result this router never routed (restart, foreign
                # producer, tick that aged out) — visible, not fatal
                self.metrics.count("results_unmatched")
            version = v.get("weights_version")
            results.append(FleetResult(
                sid, seq,
                np.asarray(v.get("probabilities", ()), np.float32),
                tuple(v.get("pred_labels", ())),
                int(version) if version is not None else None,
            ))
        self.metrics.count("results_received", len(results))
        return results

    def _age_inflight(self) -> None:
        now = self.clock()
        timeout = self.cfg.result_timeout_s
        while self._inflight:
            key = next(iter(self._inflight))
            t_submit, _ref = self._inflight[key]
            if now - t_submit <= timeout:
                break
            del self._inflight[key]
            self.metrics.count("results_missing")
            log.warning(
                "tick (%s, %d) unanswered after %.0fs — counted lost",
                key[0], key[1], timeout)

    # -- control plane -------------------------------------------------------

    def _drain_control(self) -> None:
        for rec in self._control.poll():
            self._handle_control(rec.value)

    def _handle_control(self, msg: dict) -> None:
        kind = msg.get("kind")
        if kind in (HELLO, HEARTBEAT, GOODBYE):
            wid = msg.get("worker")
            if wid:
                self._peer_wire[wid] = int(msg.get("wire", 1))
            if kind == HELLO:
                # a session-LESS hello is a fresh process whose data bus
                # restarts at offset 0 — purge any saved resume position.
                # A hello WITH sessions is the SAME incarnation re-dialing
                # the control plane (its data bus kept serving the whole
                # time): save the results read position so the re-link
                # resumes where this one stopped instead of jumping to
                # end and skipping unread results
                self._close_link(wid, resume=bool(msg.get("sessions")))
                if not msg.get("address"):
                    # a shared-bus incarnation of a previously linked id
                    self._linked_ever.discard(wid)
                if wid in self.membership.workers \
                        and not msg.get("sessions"):
                    # a session-less hello of a LIVE id: the process was
                    # killed and revived inside the heartbeat timeout —
                    # membership never noticed, but the carried state
                    # died with the old incarnation.  Same consequence
                    # as a detected death: reopen its sessions fresh,
                    # counted.  (A hello WITH sessions is the other
                    # direction — a control-plane reconnect of the same
                    # incarnation — and adopts below instead.)
                    self.metrics.count("worker_restarts")
                    self._drop_outgoing(wid)
                    self._reopen_for_restart(wid)
            if kind != GOODBYE:
                # link before rebalance: a join's first drain markers
                # and opens must have somewhere to land
                if msg.get("address"):
                    self._ensure_link(wid, msg["address"])
                else:
                    # shared-bus worker: its inbox rides THIS bus, and
                    # the launch-time topic set only covers the initial
                    # fleet — admit the topic so a late joiner is
                    # routable (ROADMAP (c); idempotent on all backends)
                    add = getattr(self.bus, "add_topic", None)
                    if add is not None:
                        add(fleet_worker_topic(wid))
            adopted = 0
            if kind == HELLO and msg.get("sessions"):
                # router failover: the hello of a worker that was
                # already serving (this router restarted, or the worker
                # re-dialed a new router) carries its open-session map;
                # the registry is rebuilt from it — the workers own the
                # truth about what is being served (docs/chaos.md)
                adopted = self._adopt_sessions(wid, msg["sessions"])
            event = self.membership.observe(msg)
            if event == "join":
                self.metrics.count("workers_joined")
                self._stops_sent.discard(wid)
                self._rebalance(f"worker join: {wid}")
            elif adopted:
                # adopted sessions on a non-join hello still need their
                # hash-table placement checked (migrations if the table
                # maps them elsewhere)
                self._rebalance(f"adopted {adopted} sessions from {wid}")
            if event == "leave":
                self.metrics.count("workers_left")
                # drop the link before the next pump would error on it
                self._close_link(wid)
                self._stops_sent.discard(wid)
                self._report_pending.discard(wid)
                self._drop_outgoing(wid)
                self._rebalance(f"worker leave: {wid}")
            elif kind == GOODBYE:
                # a released leaving worker's goodbye: already out of
                # live(), nothing to rebalance — just drop its link
                self._close_link(wid)
                self._stops_sent.discard(wid)
                self._report_pending.discard(wid)
                self._drop_outgoing(wid)
            else:
                # takeover detection: a beating worker serving more
                # sessions than this router's registry credits it with
                # means the registry predates us (we restarted) — ask
                # for the authoritative session map via its inbox
                self._maybe_request_report(wid, msg.get("stats"))
        elif kind == "session_state":
            self._on_session_state(msg)
        elif kind == "session_report":
            wid = msg.get("worker")
            self._report_pending.discard(wid)
            adopted = self._adopt_sessions(wid, msg.get("sessions"))
            if adopted:
                self._rebalance(f"adopted {adopted} sessions from {wid}")
        elif kind == "weights_swapped":
            # hot-swap ack: the worker's gateway is now serving this
            # version — the spread across workers IS the fleet's
            # mixed-version window (summary surfaces min/max)
            wid = msg.get("worker")
            if wid:
                self._worker_weights[wid] = int(msg.get("version", 0))
            self.metrics.count("hot_swaps_acked")
        elif kind == "leaving":
            self.request_leave(msg.get("worker"))
        elif kind == "open_failed":
            self.metrics.count("open_failures")
            log.error(
                "worker %s could not open session %s: %s",
                msg.get("worker"), msg.get("session"), msg.get("error"))
        # "ownership" announcements are our own — ignored on re-read

    def _adopt_sessions(
        self, worker_id: Optional[str], sessions: Optional[dict]
    ) -> int:
        """Fold a worker's authoritative session report into the
        registry (router failover, docs/chaos.md): sessions this router
        never heard of are registered with the reporter as owner, the
        reported ``seq`` continuing the result stream with no gap or
        collision, and the reported norm stats kept so a LATER owner
        death can still reopen the session fresh.  Sessions the
        registry already tracks are left alone — this router's view is
        authoritative for everything it actually routed."""
        if not worker_id or not sessions:
            return 0
        adopted = 0
        for sid, info in sessions.items():
            sess = self._sessions.get(sid)
            if sess is not None:
                if sess.owner != worker_id and sess.status == "active":
                    # two live workers claim one session (a protocol
                    # breach upstream): the registry wins — visible,
                    # and the reporter is told to drop its copy
                    self.metrics.count("adoption_conflicts")
                    self._enqueue(worker_id,
                                  {"kind": "close", "session": sid})
                    log.warning(
                        "session %s reported by %s but owned by %s — "
                        "close sent to the reporter",
                        sid, worker_id, sess.owner)
                continue
            self._sessions[sid] = _Session(
                sid, worker_id, info.get("norm"),
                next_seq=int(info.get("seq", 0)),
                tenant=info.get("tenant"))
            adopted += 1
        if adopted:
            self.metrics.count("sessions_adopted", adopted)
            self._sessions_changed()
            log.info(
                "adopted %d sessions from %s (registry rebuilt from "
                "worker state)", adopted, worker_id)
        return adopted

    def _maybe_request_report(
        self, worker_id: Optional[str], stats: Optional[dict]
    ) -> None:
        """Ask a worker for its session map when its heartbeat shows it
        serving more sessions than the registry credits it with — the
        restarted-router takeover path.  One request in flight per
        worker; the reply (``session_report``) clears it."""
        if not worker_id or worker_id in self._report_pending:
            return
        if not isinstance(stats, dict):
            return
        active = int(stats.get("active_sessions") or 0)
        if not active:
            return
        owned = self._owned_counts().get(worker_id, 0)
        if active <= owned:
            return
        self._report_pending.add(worker_id)
        self._enqueue(worker_id, {"kind": "report_sessions", "wire": 2})
        self.metrics.count("session_reports_requested")

    def request_leave(self, worker_id: Optional[str]) -> bool:
        """Gracefully drain a worker out of the fleet: it keeps serving
        while its sessions migrate off one ``drain_session`` at a time,
        and is stopped once it owns nothing.  True when the drain was
        actually initiated (the autoscaler's scale-down branches on
        this — a worker already leaving, or unknown, is not a move)."""
        if worker_id and self.membership.mark_leaving(worker_id):
            self.metrics.count("workers_leaving")
            self._rebalance(f"graceful leave: {worker_id}")
            return True
        return False

    def broadcast_retune(
        self, *, max_linger_ms: Optional[float] = None,
        bucket_cap: Optional[int] = None,
    ) -> int:
        """Push new batching knobs to every live worker's gateway (the
        batching controller's fleet-wide actuation).  Returns how many
        workers were told; each applies via ``FleetGateway.retune`` —
        bucket caps only ever select already-compiled buckets."""
        live = self.membership.live()
        for wid in live:
            self._enqueue(wid, {
                "kind": "retune",
                "max_linger_ms": max_linger_ms,
                "bucket_cap": bucket_cap,
                "wire": 2,
            })
        if live:
            self.metrics.count("retunes_broadcast")
        return len(live)

    def broadcast_hot_swap(
        self, params, *, version: Optional[int] = None,
        require_eval=None,
    ) -> int:
        """Land a new checkpoint into every live worker's gateway —
        zero dropped sessions fleet-wide (docs/replay.md "Hot swap").

        ``params`` is the checkpoint tree (numpy/array leaves; this
        process never imports jax — the worker casts on arrival).  The
        version is pinned here so every worker lands the SAME stamp:
        FIFO inbox ordering then bounds each worker's mixed-version
        window to the one flush in flight when the swap message lands,
        and each acks with a ``weights_swapped`` control message the
        fleet summary aggregates.  Returns how many workers were told.

        ``require_eval`` is the quality guardrail: a callable
        ``params -> (ok, detail)`` — typically a
        :class:`fmda_tpu.eval.shadow.ShadowEvaluator`, injected so this
        jax-free role never builds a serving stack itself.  A candidate
        it rejects is **refused**: counted (``hot_swaps_refused``),
        announced on the control topic for operators, zero workers
        told, the fleet keeps serving the incumbent.
        """
        if require_eval is not None:
            ok, detail = require_eval(params)
            if not ok:
                self.metrics.count("hot_swaps_refused")
                try:
                    # lint: ignore[wire-protocol] deliberately consumer-less: the refusal announcement is observability for operators tailing the control topic, not protocol (workers never branch on it)
                    self.bus.publish(self.control_topic, {
                        "kind": "hot_swap_refused",
                        "detail": dict(detail or {}),
                    })
                except (ConnectionError, OSError) as e:
                    # the announcement is observability, not protocol —
                    # a down control bus must not turn a refusal (local
                    # state only) into a crash
                    self.metrics.count("bus_errors")
                    log.warning("hot-swap refusal announcement "
                                "failed: %s", e)
                log.warning("hot swap REFUSED by quality guardrail: %s",
                            detail)
                return 0
        tree = encode_param_tree(params)
        self._swap_version = (version if version is not None
                              else self._swap_version + 1)
        live = self.membership.live()
        for wid in live:
            self._enqueue(wid, {
                "kind": "hot_swap",
                "params": tree,
                "version": int(self._swap_version),
                "wire": 2,
            })
        if live:
            self.metrics.count("hot_swaps_broadcast")
            self.metrics.gauge("weights_version", float(self._swap_version))
        return len(live)

    def _maybe_release_leaving(self) -> None:
        """Stop a leaving worker once no session is assigned to it any
        more (its drains are all complete).  The leave mark is NOT
        cleared here — the worker stays out of live() until its goodbye
        actually arrives, so a join rebalance in the stop→goodbye
        window can never route sessions (or migrated state) into the
        stopping worker's inbox."""
        for wid in sorted(self.membership.leaving - self._stops_sent):
            if self._owned_counts().get(wid):
                continue
            self._enqueue(wid, {"kind": "stop"})
            self._stops_sent.add(wid)

    def _rebalance(self, reason: str) -> None:
        """Re-derive the ownership table from the live set and move (or
        reopen) every session whose range changed hands."""
        live = self.membership.live()
        self.table = OwnershipTable.derive(
            self.table.version + 1, live, self.cfg.hash_space)
        self.metrics.count("rebalances")
        self.metrics.gauge("n_workers", len(live))
        self.metrics.gauge("table_version", self.table.version)
        if self._stopping:
            # the whole topology is exiting: goodbyes must not cascade
            # into pointless migrations between dying workers
            return
        try:
            # lint: ignore[wire-protocol] deliberately consumer-less: the announcement is observability for operators tailing the control topic, not protocol (workers never branch on it)
            self.bus.publish(self.control_topic, {
                "kind": "ownership", "table": self.table.to_wire(),
                "reason": reason,
            })
        except (ConnectionError, OSError) as e:
            # the announcement is observability, not protocol (workers
            # never consume it) — a down control bus must not abort a
            # rebalance that only touches local state + worker inboxes
            self.metrics.count("bus_errors")
            log.warning("ownership announcement failed: %s", e)
        log.info(
            "ownership v%d over %s (%s)", self.table.version, live, reason)
        # "present" = still alive and serving its inbox, even if leaving
        # (a leaving worker is out of live() — it gets no NEW sessions —
        # but it gracefully drains the ones it has)
        present = set(self.membership.workers)
        for sess in self._sessions.values():
            new_owner = self.table.owner_of(sess.session_id)
            if sess.status != "active":
                # migration already in flight: if the exporter died
                # before its state got out (or never existed), the state
                # is gone — reopen fresh; otherwise the state message is
                # still coming and will be routed against the new table
                if sess.owner not in present and sess.pending_state is None:
                    if sess.mig is not None:
                        self.metrics.count("migrations_aborted")
                    self._reopen_lost(sess, new_owner)
                elif sess.pending_state is not None and new_owner is not None:
                    self._complete_migration(sess, new_owner,
                                             sess.pending_state)
                continue
            if new_owner == sess.owner:
                continue
            if sess.owner not in present:
                # owner died with the carried state on board
                self._reopen_lost(sess, new_owner)
            else:
                self._start_migration(sess)
        self._maybe_release_leaving()

    def _start_migration(self, sess: _Session) -> None:
        self._set_status(sess, "migrating")
        sess.mig = f"m{next(self._mig_ids)}"
        self._enqueue(sess.owner, {
            "kind": "drain_session",
            "session": sess.session_id,
            "mig": sess.mig,
            # v2 requester: the worker may export raw-array state;
            # absent (a pre-v2 router), it lowers to base64 envelopes
            "wire": 2,
        })
        self.metrics.count("migrations_started")

    def _on_session_state(self, msg: dict) -> None:
        sess = self._sessions.get(msg.get("session"))
        if sess is None or sess.mig != msg.get("mig"):
            self.metrics.count("stale_session_state")
            return
        # state stays in wire form end to end — the router never decodes
        # the arrays, it only forwards them to the new owner
        new_owner = self.table.owner_of(sess.session_id)
        if new_owner is None:
            # every worker left between export and now: hold the state
            # until one joins (the next rebalance re-enters here)
            sess.pending_state = msg["state"]
            sess.owner = None
            self._owned_cache = None
            return
        self._complete_migration(sess, new_owner, msg["state"])

    def _complete_migration(
        self, sess: _Session, new_owner: str, state: dict
    ) -> None:
        self._enqueue(new_owner, self._open_msg(sess, state=state))
        replayed = len(sess.buffer)
        while sess.buffer:
            self._enqueue(new_owner, sess.buffer.popleft())
        sess.owner = new_owner
        self._set_status(sess, "active")
        sess.mig = None
        sess.pending_state = None
        self.metrics.count("migrations_completed")
        self.metrics.count("migration_replayed_ticks", replayed)
        log.info(
            "session %s migrated to %s (%d buffered ticks replayed)",
            sess.session_id, new_owner, replayed)

    def _reopen_for_restart(self, worker_id: str) -> None:
        """A live worker id came back as a fresh process (revive inside
        the heartbeat window): every session it hosted lost its carried
        state.  Reopen them fresh on their table owner — usually the
        same id, now the new incarnation — through the same counted
        path a detected death takes."""
        for sess in list(self._sessions.values()):
            if sess.owner != worker_id:
                continue
            if sess.mig is not None:
                self.metrics.count("migrations_aborted")
            self._reopen_lost(sess, self.table.owner_of(sess.session_id))

    def _reopen_lost(self, sess: _Session, new_owner: Optional[str]) -> None:
        """The owner died with the session's carried state: reopen fresh
        on the new owner (state restarts from zero — counted, documented
        in the failure matrix) and forward any buffered ticks so the
        stream keeps flowing."""
        if sess.owner is not None:
            # an ownerless session was already counted lost when its
            # owner died; re-entering here on a later rebalance (a
            # worker finally joined) is placement, not a second loss
            # lint: ignore[counted-loss] counts lost SESSION STATE, not ticks — the identity gate uses it to exclude these sessions from bit-identity, never as a summed term
            self.metrics.count("sessions_lost_state")
            self.lost_state_sessions.add(sess.session_id)
        sess.mig = None
        sess.pending_state = None
        if new_owner is None:
            # no workers at all: buffer until one joins
            sess.owner = None
            self._set_status(sess, "migrating")
            return
        # resume the seq stream at the first tick the new owner will
        # actually serve, so (session, seq) never collides
        resume_seq = (sess.buffer[0]["seq"] if sess.buffer
                      else sess.next_seq)
        sess.owner = new_owner
        self._set_status(sess, "active")
        self._enqueue(new_owner, {
            "kind": "open",
            "session": sess.session_id,
            "norm": sess.norm_wire,
            "seq": resume_seq,
            "wire": 2,
        })
        while sess.buffer:
            self._enqueue(new_owner, sess.buffer.popleft())
        log.warning(
            "session %s reopened on %s with FRESH state (previous owner "
            "died undrained)", sess.session_id, new_owner)

    # -- shutdown / introspection -------------------------------------------

    def stop_workers(self, *, graceful: bool = True) -> None:
        """Tell every live worker to exit: ``graceful`` serves every
        queued tick before exiting (final stats arrive with the
        goodbye; carried state is NOT exported — a topology stop ends
        the streams); otherwise a bare stop."""
        self._stopping = True
        kind = "drain_all" if graceful else "stop"
        for wid in sorted(self.membership.workers):  # leaving ones too
            self._enqueue(wid, {"kind": kind})
        self._exchange_data()

    def close(self) -> None:
        """Release every data-plane link (shutdown)."""
        for wid in list(self._links):
            self._close_link(wid)

    @property
    def outstanding_ticks(self) -> int:
        """Routed ticks not yet answered (or aged into a counter)."""
        return len(self._inflight)

    @property
    def migrating_sessions(self) -> int:
        """Sessions whose ticks are buffering (a migration or orphaned
        reopen in flight) — the chaos soak's recovery barrier keys on
        this reaching zero before it probes post-chaos serving."""
        return len(self._migrating)

    def open_session_ids(self) -> List[str]:
        """Ids of every registered session (chaos-soak introspection)."""
        return list(self._sessions)

    def worker_stats(self) -> Dict[str, dict]:
        """Latest heartbeat-carried stats per worker (live + departed)."""
        out = {}
        for wid, info in {**self.membership.departed,
                          **self.membership.workers}.items():
            out[wid] = dict(info.stats)
        return out

    def summary(self) -> Dict[str, object]:
        out = {
            **self.metrics.summary(),
            "table_version": self.table.version,
            "workers": self.membership.live(),
            "worker_stats": self.worker_stats(),
        }
        if self._worker_weights:
            versions = [self._worker_weights.get(w, 0)
                        for w in self.membership.live()]
            out["weights_versions"] = dict(self._worker_weights)
            # 0 spread = no mixed-version window open anywhere
            out["weights_version_spread"] = (
                (max(versions) - min(versions)) if versions else 0)
        return out
