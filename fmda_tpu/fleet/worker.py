"""A fleet worker process: one FleetGateway/SessionPool behind an inbox.

The worker is today's single-process fleet runtime embedded unchanged —
the same :class:`~fmda_tpu.runtime.gateway.FleetGateway` admission/
batching/publish path, the same :class:`~fmda_tpu.runtime.session_pool
.SessionPool` carried state — driven by its **inbox topic** instead of
direct calls.  Everything the router sends (opens, ticks, closes,
migration drains) arrives on one FIFO topic and is applied in offset
order, which is the whole ordering argument (see
:mod:`fmda_tpu.fleet.router`); results flow back on the shared
prediction topic exactly as in-process serving publishes them.

This module is worker-role code: jax (via the runtime) is imported
freely — it runs on hosts that own accelerators.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional

import numpy as np

from fmda_tpu.chaos.inject import default_chaos
from fmda_tpu.config import (
    FleetTopologyConfig,
    RuntimeConfig,
    TOPIC_FLEET_CONTROL,
    fleet_worker_topic,
)
from fmda_tpu.stream import codec
from fmda_tpu.fleet.membership import Heartbeater
from fmda_tpu.fleet.state import (
    decode_norm,
    decode_param_tree,
    decode_row,
    decode_session_state,
    encode_array,
    encode_session_state,
    to_legacy,
)
from fmda_tpu.runtime.batcher import BatcherConfig
from fmda_tpu.runtime.gateway import FleetGateway
from fmda_tpu.runtime.session_pool import PoolExhausted, SessionPool

log = logging.getLogger("fmda_tpu.fleet")

#: chaos injection (fmda_tpu.chaos): disabled = one branch per step
_CHAOS = default_chaos()


class FleetWorker:
    """Owns one slot-range of the session space; serves its inbox."""

    def __init__(
        self,
        worker_id: str,
        bus,
        model_cfg,
        params,
        *,
        config: Optional[FleetTopologyConfig] = None,
        runtime: Optional[RuntimeConfig] = None,
        capacity: Optional[int] = None,
        control_topic: str = TOPIC_FLEET_CONTROL,
        clock: Callable[[], float] = time.monotonic,
        precompile: bool = True,
        gateway_kwargs: Optional[dict] = None,
        data_bus=None,
        data_address: Optional[str] = None,
        reconnect_fn: Optional[Callable[[], object]] = None,
        qos=None,
    ) -> None:
        self.worker_id = worker_id
        self.bus = bus
        #: the worker's data plane: its inbox + its results.  Defaults
        #: to the control bus (one shared broker).  The scaling shape is
        #: a **worker-hosted** data bus (``data_bus`` = a local bus this
        #: process serves to the router via BusServer, ``data_address``
        #: announced in every heartbeat): the serving hot path then
        #: never crosses a socket — only the router's pump does, once
        #: per worker — so adding workers adds data-plane capacity
        #: instead of contending for one broker.
        self.data_bus = data_bus if data_bus is not None else bus
        self._split = self.data_bus is not bus
        self.cfg = config or FleetTopologyConfig()
        rc = runtime or RuntimeConfig()
        capacity = capacity if capacity is not None else rc.capacity
        self.pool = SessionPool(
            model_cfg, params, capacity=capacity, window=rc.window)
        kwargs = dict(
            batcher_config=BatcherConfig(
                bucket_sizes=tuple(rc.bucket_sizes),
                max_linger_s=rc.max_linger_ms / 1e3),
            queue_bound=rc.queue_bound,
            pipeline_depth=rc.pipeline_depth,
        )
        kwargs.update(gateway_kwargs or {})
        # on a shared SocketBus, everything this worker publishes
        # (results, heartbeats, migration state) buffers and rides the
        # step's ONE batched frame together with the inbox read — round
        # trips, not bytes, are the transport's cost (fmda_tpu.fleet
        # .wire).  With a worker-hosted data bus, publishes are local
        # and only the rare control messages cross the socket.
        self._batch_bus = (
            bus if not self._split and hasattr(bus, "batch") else None)
        if self._batch_bus is not None:
            from fmda_tpu.fleet.wire import BufferedPublisher

            self._pub = BufferedPublisher(bus)
        else:
            self._pub = bus  # control messages go straight out
        # dynamic topic creation (ROADMAP (c)): a worker joining beyond
        # the bus's launch-time topic set brings its own inbox (and the
        # shared results topic) with it — NativeBus/InProcessBus/KafkaBus
        # and the wire transport all speak add_topic; buses without it
        # keep the old contract (topics pre-created at construction)
        from fmda_tpu.config import TOPIC_FLEET_PREDICTION

        add_topic = getattr(self.data_bus, "add_topic", None)
        if add_topic is not None:
            for topic in (fleet_worker_topic(worker_id),
                          TOPIC_FLEET_PREDICTION):
                if topic not in self.data_bus.topics():
                    add_topic(topic)
        self.gateway = FleetGateway(
            self.pool,
            self.data_bus if self._split else self._pub,
            **kwargs)
        self.metrics = self.gateway.metrics
        if qos is not None:
            # per-tenant QoS policy (fmda_tpu.control.qos): overload
            # shedding at THIS gateway becomes class-aware — sessions
            # arrive labeled via the router's open messages
            self.gateway.attach_qos(qos)
        self._inbox = self.data_bus.consumer(fleet_worker_topic(worker_id))
        announce = {"address": data_address} if data_address else None
        self.heartbeater = Heartbeater(
            self._pub, worker_id, control_topic=control_topic,
            interval_s=self.cfg.heartbeat_interval_s,
            capacity=capacity, clock=clock, announce=announce)
        self.control_topic = control_topic
        self.clock = clock
        self.stopped = False
        #: next inbox offset we expect (gap ⇒ records evicted unread)
        self._next_offset: Optional[int] = None
        #: rebuilds the control-bus connection after a transport failure
        #: (the CLI passes a SocketBus re-dial); None = no reconnect
        self._reconnect_fn = reconnect_fn
        #: control plane currently unreachable (beats failing) — the
        #: worker keeps serving its local data plane and re-dials on a
        #: cadence; a reconnect re-hellos WITH the session report, which
        #: is how a restarted router adopts this worker's sessions
        self._control_down = False
        #: migrations whose exported state never left this process
        #: (control publish failed): session -> (mig id, requester wire
        #: capability), re-drained and re-exported once the control
        #: plane answers again — without this the router would wait on
        #: a ``session_state`` that is never coming and the session
        #: would buffer forever
        self._failed_drains: Dict[str, tuple] = {}
        self._last_reconnect: float = float("-inf")
        self._first_bus_error: Optional[float] = None
        if precompile:
            # one padding-only flush per bucket: every program the tick
            # path can need exists before the first real tick, so
            # compile_count stays len(bucket_sizes) forever (the
            # multihost bench gates on exactly this)
            feats = model_cfg.n_features
            for b in self.gateway.batcher.config.bucket_sizes:
                self.pool.step(
                    np.full(b, self.pool.padding_slot, np.int32),
                    np.zeros((b, feats), np.float32))
            # warmup is over: any further compile is an *unexpected
            # recompile* — counted by the compile ledger, evented, and
            # SLO-alertable; the chaos/elastic soaks hard-gate zero
            # (fmda_tpu.obs.device)
            self.pool.mark_warm()
        # device memory attribution: this pool's live tree, sampled on
        # the worker loop at the monitor's cadence (one clock read per
        # step when not due)
        from fmda_tpu.obs.device import (
            default_ledger,
            default_memory_monitor,
        )

        self._ledger = default_ledger()
        self._memory = default_memory_monitor()
        self._memory.register_owner(
            f"session_pool:{worker_id}", self.pool.live_tree)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Announce membership (the router rebalances on the hello).
        The hello carries this worker's open-session report, so a
        router that restarted while we kept serving rebuilds its
        registry from the re-hello alone (failover, docs/chaos.md)."""
        self._hello_with_report()
        if self._batch_bus is not None:
            self._pub.flush()  # the hello must not wait for a step

    def _hello_with_report(self) -> None:
        """Hello carrying the open-session report — the router-failover
        handshake (start, shared-bus retry, and control re-dial all
        announce this worker the same way; a new or restarted router
        rebuilds its registry from exactly this message)."""
        report = self.session_report()
        self.heartbeater.hello(
            self.stats(), extra={"sessions": report} if report else None)

    def _control_is_json(self) -> bool:
        """Did the control link negotiate down to the JSON fallback?
        Then array payloads this worker exports (session reports,
        migrated state) are lowered to the pre-v2 base64 envelopes too
        — the peer may genuinely predate the raw-array shapes.  In-
        process buses have no negotiation: same-code peers, full v2.
        Router-originated requests additionally declare their own
        capability in a ``wire`` field (broker-mediated topologies:
        this link's format says nothing about the router's age) — the
        request handlers check both signals."""
        return getattr(self.bus, "negotiated_format", None) == "json"

    def session_report(
        self, *, legacy: Optional[bool] = None
    ) -> Dict[str, dict]:
        """Authoritative open-session map: id → next result ``seq`` +
        normalization stats (wire form).  This is what router failover
        rebuilds the session registry from — the workers, not the dead
        router, own the truth about what is being served."""
        out: Dict[str, dict] = {}
        for sid in self.pool.session_ids():
            handle = self.pool.handle_for(sid)
            x_min, x_range = self.pool.slot_norm(handle)
            out[sid] = {
                "seq": self.gateway.session_seq(sid),
                "norm": {
                    "x_min": encode_array(x_min),
                    "x_max": encode_array(x_min + x_range),
                },
            }
            tenant = self.gateway.session_tenant(sid)
            if tenant is not None:
                # the QoS class survives router failover with the rest
                # of the session truth this report rebuilds
                out[sid]["tenant"] = tenant
            if self.gateway.weights_version is not None:
                # which checkpoint generation served this session last —
                # makes mixed-version windows visible in the report a
                # failover rebuilds from (pre-swap reports stay
                # byte-identical: the key only appears after a swap)
                out[sid]["weights_version"] = self.gateway.weights_version
        if legacy is None:
            legacy = self._control_is_json()
        if out and legacy:
            out = to_legacy(out)
        return out

    def stats(self) -> Dict[str, object]:
        """The serving stats every heartbeat carries."""
        c = self.metrics.counters
        out = {
            "active_sessions": self.pool.n_active,
            "ticks_served": c.get("ticks_served", 0),
            "flushes": c.get("flushes", 0),
            "shed_oldest": c.get("shed_oldest", 0),
            # rides the beat so the router (and the bench's zero-loss
            # gate) can see a worker-side inbox overrun — the counter
            # lives in this process, not the router's
            "inbox_records_lost": c.get("inbox_records_lost", 0),
            "compile_count": self.pool.compile_count,
            "queue_depth": len(self.gateway.batcher),
            # device/compiler telemetry (fmda_tpu.obs.device): the beat
            # carries the recompile + memory truth so the router-side
            # SLO engine can alert fleet-wide without scraping
            "recompiles_after_warmup": self.pool.recompiles_after_warmup,
            "compile_seconds": round(
                self._ledger.compile_seconds_total, 6),
            "live_bytes": self._memory.live_bytes,
            "memory_watermark_bytes": self._memory.watermark_bytes,
            "memory_leak_suspected": (
                1 if self._memory.leak_suspected else 0),
            "device_mfu": self._ledger.mfu(),
        }
        if self.gateway.weights_version is not None:
            # the beat carries the serving checkpoint generation, so
            # the router-side summary can report the fleet's version
            # spread without an extra round trip
            out["weights_version"] = self.gateway.weights_version
        version_ticks = self.gateway.version_ticks
        if version_ticks:
            # per-checkpoint serving attribution (quality plane): which
            # version served how many of this worker's ticks — keys as
            # strings so the stats dict stays JSON/wire-clean
            out["version_ticks"] = {
                str(v): n for v, n in sorted(version_ticks.items())}
        # per-class admit/shed attribution (fmda_tpu.control QoS): the
        # gateway counts these in this process; the beat carries them so
        # the control plane can fold fleet-wide per-tenant rates
        tenant_counters = {
            k: v for k, v in c.items()
            if k.startswith(("admitted_class_", "shed_class_"))}
        if tenant_counters:
            out["tenant_counters"] = tenant_counters
        return out

    def step(self) -> int:
        """One worker cycle: apply a bounded slice of the inbox, pump
        the gateway, heartbeat if due.  Returns an activity count
        (inbox records applied + results published) — zero means idle,
        which the run loop's poll backoff keys on."""
        if _CHAOS.enabled:
            # injection point "worker.step": delay/hang stalls the loop
            # (the false-reap / late-heartbeat shape); kill raises a
            # ConnectionError the run loop's hardening absorbs
            _CHAOS.check("worker.step")
        # beat first: a long pump last cycle must not push two beats
        # more than one step duration apart
        self._beat_counted()
        # device memory cadence: one clock read per step when not due
        self._memory.maybe_sample()
        if self._failed_drains and not self._control_down:
            self._retry_failed_drains()
        processed = 0
        for rec in self._poll_inbox():
            processed += 1
            if self._next_offset is not None and rec.offset > self._next_offset:
                # records fell off the inbox's retention before we read
                # them (backlog outran the bus arena) — the contract is
                # counted degradation, never a silent skip
                lost = rec.offset - self._next_offset
                self.metrics.count("inbox_records_lost", lost)
                log.error(
                    "worker %s: %d inbox records evicted unread "
                    "(offsets %d..%d) — raise the bus arena or slow "
                    "the producer", self.worker_id, lost,
                    self._next_offset, rec.offset - 1)
            self._next_offset = rec.offset + 1
            self._apply(rec.value)
            if self.stopped:
                break
        served = len(self.gateway.pump())
        return processed + served

    def _beat_counted(self) -> None:
        """Heartbeat with the control plane's failure absorbed: a worker
        whose router (or broker) vanished keeps serving its local data
        plane — counted degradation, never abort.  While down, the
        control bus is re-dialed on a cadence; success re-hellos with
        the session report (a restarted router adopts us from it)."""
        try:
            if self._control_down:
                self._maybe_reconnect_control()
                return
            self.heartbeater.beat(self.stats())
        except (ConnectionError, OSError) as e:
            self.metrics.count("control_errors")
            if not self._control_down:
                log.warning(
                    "worker %s: control plane unreachable (%s) — serving "
                    "continues, re-dialing%s", self.worker_id, e,
                    "" if self._reconnect_fn else " on the same bus")
            self._control_down = True

    def _maybe_reconnect_control(self) -> None:
        now = self.clock()
        if now - self._last_reconnect < self.cfg.control_retry_s:
            return
        self._last_reconnect = now
        if self._reconnect_fn is None:
            # no transport to rebuild (shared-broker worker): retry the
            # SAME bus on the cadence — one transient publish error must
            # not mute a healthy worker's heartbeats forever (the router
            # would falsely reap it and lose real carried state).  The
            # re-hello carries the session report, same as a re-dial.
            try:
                self._hello_with_report()
            except (ConnectionError, OSError):
                self.metrics.count("control_reconnect_failures")
                return
            self._control_down = False
            self.metrics.count("control_reconnects")
            log.info(
                "worker %s: control plane recovered", self.worker_id)
            return
        try:
            new_bus = self._reconnect_fn()
        except (ConnectionError, OSError):
            self.metrics.count("control_reconnect_failures")
            return
        old = self.bus
        self.bus = new_bus
        # reconnect is a split-topology feature (the data plane is local,
        # only control traffic rides this bus); a shared-bus worker that
        # lost its one broker exits after the grace instead (run loop)
        self._pub = new_bus
        self.heartbeater.bus = new_bus
        # re-bind the obs series to the LIVE link: without this the
        # registry's wire collector keeps sampling the dead SocketBus
        # (frozen frames_*_total, stale wire_format_binary) and the new
        # link's publishes go uncounted
        registry = getattr(old, "metrics_registry", None)
        if registry is not None:
            bind = getattr(new_bus, "bind_metrics", None)
            if bind is not None:
                try:
                    bind(registry)
                # loss-free: metrics re-binding must never turn a
                # reconnect fatal; the stale collector only skews obs
                except (ConnectionError, OSError):
                    pass
        self._control_down = False
        self.metrics.count("control_reconnects")
        log.info("worker %s: control plane reconnected", self.worker_id)
        close = getattr(old, "close", None)
        if close is not None:
            try:
                close()
            except OSError:  # loss-free: teardown of the dead control bus
                pass
        # re-hello with the session report: a NEW router on the other
        # end rebuilds its registry from exactly this message
        self._hello_with_report()

    def _poll_inbox(self):
        """Inbox records for this step.  Over a batched SocketBus, one
        frame carries every buffered publish (last pump's results,
        heartbeats, migration state — in publish order) AND the inbox
        read; otherwise a plain consumer poll."""
        if self._batch_bus is None:
            return self._inbox.poll(
                max_records=self.cfg.worker_poll_max_records)
        bus = self._batch_bus
        ops = self._pub.take_ops()
        read_op = {
            "op": "read",
            "topic": self._inbox.topic,
            "offset": self._inbox.offset,
            "max_records": self.cfg.worker_poll_max_records,
        }
        ops.append(read_op)
        resps = bus.batch(ops)
        for op, resp in zip(ops[:-1], resps[:-1]):
            if "err" in resp:
                # a failed publish loses results — counted, never silent
                self.metrics.count(
                    "publish_errors", len(op.get("values", ())))
                log.error("worker %s: batched publish to %r failed: %s",
                          self.worker_id, op.get("topic"), resp["err"])
        rows = bus.unwrap_op(read_op, resps[-1])
        from fmda_tpu.stream.bus import Record

        records = [Record(self._inbox.topic, int(o), v) for o, v in rows]
        if records:
            self._inbox.offset = records[-1].offset + 1
        return records

    def run(
        self,
        *,
        poll_interval_s: float = 0.0005,
        duration_s: Optional[float] = None,
        should_stop: Optional[Callable[[], bool]] = None,
        sleep_fn: Callable[[float], None] = time.sleep,
    ) -> Dict[str, object]:
        """Serve until a ``stop``/``drain_all`` arrives (or the optional
        duration/should_stop safety valves fire); returns final stats."""
        self.start()
        deadline = (self.clock() + duration_s
                    if duration_s is not None else None)
        idle_sleep = poll_interval_s
        while not self.stopped:
            if should_stop is not None and should_stop():
                self._shutdown()
                break
            if deadline is not None and self.clock() >= deadline:
                log.warning(
                    "worker %s exiting on duration safety valve",
                    self.worker_id)
                self._shutdown()
                break
            try:
                activity = self.step()
            except (ConnectionError, OSError) as e:
                # the shared bus (inbox + results in one broker) went
                # away mid-step: counted, retried under a grace window,
                # and — if the broker never returns — a CLEAN exit, not
                # a crash (the never-abort contract; a split-topology
                # worker instead keeps serving through _beat_counted)
                self.metrics.count("bus_errors")
                now = self.clock()
                if self._first_bus_error is None:
                    self._first_bus_error = now
                    log.warning(
                        "worker %s: bus transport failed (%s); retrying "
                        "for %.0fs", self.worker_id, e,
                        self.cfg.bus_error_grace_s)
                if now - self._first_bus_error > self.cfg.bus_error_grace_s:
                    lost = len(self.gateway.batcher)
                    if lost:
                        self.metrics.count("ticks_lost_on_exit", lost)
                    log.error(
                        "worker %s: bus unreachable for %.0fs — exiting "
                        "cleanly (%d queued ticks lost, counted)",
                        self.worker_id, now - self._first_bus_error, lost)
                    self.stopped = True
                    break
                sleep_fn(min(0.5, poll_interval_s * 50 + 0.05))
                continue
            self._first_bus_error = None
            if activity:
                idle_sleep = poll_interval_s
            else:
                # adaptive idle backoff: an idle worker polling flat-out
                # is pure load on the broker (N workers × empty reads);
                # back off to a few ms, snap back on the first record
                idle_sleep = min(idle_sleep * 2, 0.005)
                sleep_fn(idle_sleep)
        return self.stats()

    def _shutdown(self) -> None:
        """Serve everything queued, say goodbye with final stats, stop.
        The goodbye is best-effort: a router that sends ``stop`` and
        tears its bus server down immediately (or died outright) must
        not turn this worker's clean exit into a crash."""
        self.gateway.drain()
        try:
            self.heartbeater.goodbye(self.stats())
            if self._batch_bus is not None:
                self._pub.flush()  # last results + goodbye actually leave
        except (ConnectionError, OSError) as e:
            self.metrics.count("goodbye_failed")
            log.warning(
                "worker %s: goodbye publish failed (%s) — router gone; "
                "exiting anyway", self.worker_id, e)
        self.stopped = True

    # -- inbox handlers ------------------------------------------------------

    def _apply(self, msg: dict) -> None:
        kind = msg.get("kind")
        wire_v = int(msg.get("wire", 0))
        if kind == "tick_block" or wire_v >= 2:
            # v2 evidence: only a v2 router sends columnar tick blocks
            # or stamps ``wire: 2`` into its control messages — results
            # may flow back as columnar blocks from here on (a pre-v2
            # router, which could not parse them, never shows either
            # signal, so it keeps getting per-tick dicts)
            self.gateway.result_blocks = True
        elif wire_v < 2 and kind in (
                "open", "drain_session", "report_sessions"):
            # DOWNGRADE evidence: these are exactly the kinds a v2
            # router always stamps, so their absence means the live
            # router is pre-v2 — a takeover by an older binary while
            # this worker kept serving (docs/chaos.md) must roll the
            # result dialect back or every multi-tick flush would be
            # dropped as foreign records on the other end
            self.gateway.result_blocks = False
        if kind == "tick":
            self._on_tick(msg)
        elif kind == "tick_block":
            self._on_tick_block(msg)
        elif kind == "open":
            self._on_open(msg)
        elif kind == "close":
            self._on_close(msg)
        elif kind == "drain_session":
            self._on_drain_session(msg)
        elif kind == "report_sessions":
            # a router that restarted mid-serve asks for the session map
            # it lost; the reply is the same shape the hello carries —
            # lowered to pre-v2 envelopes unless the REQUEST declared a
            # v2 requester (the link format only describes the broker)
            self._publish_control_counted({
                "kind": "session_report",
                "worker": self.worker_id,
                "sessions": self.session_report(
                    legacy=(self._control_is_json()
                            or int(msg.get("wire", 1)) < 2)),
            })
            self.metrics.count("session_reports")
        elif kind == "retune":
            # batching-controller actuation (fmda_tpu.control): swap the
            # gateway's linger/bucket knobs in place — never a compile,
            # never a dropped tick, applies between two pump cycles
            linger = msg.get("max_linger_ms")
            cap = msg.get("bucket_cap")
            self.gateway.retune(
                max_linger_ms=float(linger) if linger is not None else None,
                bucket_cap=int(cap) if cap is not None else None)
        elif kind == "hot_swap":
            self._on_hot_swap(msg)
        # lint: ignore[wire-protocol] operator entry point: published by hand (or tooling) onto a worker inbox — nothing in the package produces it by design
        elif kind == "leave":
            # operator-initiated graceful leave: tell the router, which
            # migrates our sessions off and stops us when none remain
            self._publish_control_counted({
                "kind": "leaving", "worker": self.worker_id})
            self.metrics.count("leave_requested")
        elif kind in ("drain_all", "stop"):
            self._shutdown()
        else:
            self.metrics.count("unknown_inbox_messages")
            log.warning(
                "worker %s: unknown inbox message kind %r",
                self.worker_id, kind)

    def _on_hot_swap(self, msg: dict) -> None:
        """Land a router-broadcast checkpoint into the live gateway.

        The gateway's swap barrier publishes every old-weights result
        before the version flips, and FIFO inbox ordering means every
        tick already queued behind this message is served by the new
        weights — the worker's mixed-version window is exactly the one
        flush in flight at swap time.  A refused checkpoint (structure
        or shape drift) is counted and logged, never fatal: serving the
        old weights beats serving nothing."""
        try:
            params = decode_param_tree(msg["params"])
            version = self.gateway.hot_swap(
                params, version=msg.get("version"))
        except Exception as e:  # noqa: BLE001 — loss-free: a bad
            # checkpoint must degrade to "swap refused, old weights
            # keep serving", visibly, never crash the serving loop
            self.metrics.count("hot_swap_errors")
            log.error(
                "worker %s: hot swap refused: %s", self.worker_id, e)
            return
        self._publish_control_counted({
            "kind": "weights_swapped",
            "worker": self.worker_id,
            "version": int(version),
        })

    def _publish_control_counted(self, msg: dict) -> bool:
        """Control-topic publish with the transport failure absorbed
        (counted ``control_errors``); returns whether it landed.  The
        chaos contract: losing a control message degrades the fleet
        visibly — it must never crash the serving loop."""
        try:
            self._pub.publish(self.control_topic, msg)
            return True
        except (ConnectionError, OSError) as e:
            self.metrics.count("control_errors")
            self._control_down = True
            log.warning(
                "worker %s: control publish (%s) failed: %s",
                self.worker_id, msg.get("kind"), e)
            return False

    def _on_open(self, msg: dict) -> None:
        sid = msg["session"]
        if self.pool.handle_for(sid) is not None:
            state = msg.get("state")
            if (state is not None
                    and self.gateway.session_seq(sid) > int(state["seq"])):
                # a requeued duplicate of an open this session already
                # served past (the original frame landed but its response
                # read failed): re-importing the snapshot would silently
                # roll the carried state back — keep the newer state
                self.metrics.count("duplicate_opens_stale")
                log.warning(
                    "worker %s: stale duplicate open(+state) for %s "
                    "(snapshot seq %d < live seq %d) — ignored",
                    self.worker_id, sid, int(state["seq"]),
                    self.gateway.session_seq(sid))
                return
            # a duplicate open is a protocol violation upstream; recover
            # by replacing (the router's registry is authoritative)
            self.metrics.count("duplicate_opens")
            log.warning(
                "worker %s: duplicate open for %s — replacing",
                self.worker_id, sid)
            self.gateway.close_session(sid)
        try:
            if msg.get("state") is not None:
                state = decode_session_state(msg["state"])
                if msg.get("tenant") is not None:
                    # the router's registry label wins when the exporting
                    # gateway never learned the class (an adopted session)
                    state.setdefault("tenant", msg["tenant"])
                self.gateway.import_session(sid, state)
                self.metrics.count("sessions_migrated_in")
            else:
                self.gateway.open_session(
                    sid, decode_norm(msg.get("norm")),
                    seq=int(msg.get("seq", 0)),
                    tenant=msg.get("tenant"))
        except PoolExhausted:
            # counted at the gateway too (rejected_sessions); tell the
            # router so the failure is visible fleet-wide
            self._publish_control_counted({
                "kind": "open_failed",
                "worker": self.worker_id,
                "session": sid,
                "error": f"pool exhausted ({self.pool.capacity} slots)",
            })

    def _on_tick(self, msg: dict) -> None:
        self._submit_tick(
            msg["session"], msg["row"], msg.get("seq"), msg.get("trace"))

    def _on_tick_block(self, msg: dict) -> None:
        """A columnar run of ticks (fmda_tpu.stream.codec): the rows
        arrive as ONE contiguous (B, F) float32 array — on a binary
        link a zero-copy view into the received frame — and each tick's
        staging copy in :meth:`FleetGateway.submit` is the first copy
        the row ever pays on this host."""
        for sid, row, seq, trace in codec.iter_ticks(msg):
            self._submit_tick(sid, row, seq, trace)

    def _submit_tick(self, sid: str, row_wire, seq, trace) -> None:
        if self.pool.handle_for(sid) is None:
            # close/tick race or an open that failed: visible skip
            self.metrics.count("ticks_for_unknown_session")
            return
        row = decode_row(row_wire, self.pool.cfg.n_features)
        if self.gateway.saturated:
            # well-behaved consumer: serve the backlog instead of
            # racing the gateway's shedder (no tick is ever dropped on
            # the floor by the worker itself)
            self.gateway.pump(force=True)
            self.metrics.count("forced_pumps")
        if (seq is not None
                and self.gateway.session_seq(sid) != seq):
            # the streams diverged — ticks were lost in transit (a
            # partitioned link's frame, counted router-side).  Resync
            # to the router's counter: without this, every later
            # result would match the WRONG in-flight tick forever;
            # with it, exactly the lost ticks age out as
            # results_missing and the stream re-aligns.  Counted —
            # divergence is a failure event, never silent.
            self.metrics.count("seq_resyncs")
            self.gateway.resync_seq(sid, int(seq))
        self.gateway.submit(sid, row, wire=trace)

    def _on_close(self, msg: dict) -> None:
        sid = msg["session"]
        if self.pool.handle_for(sid) is None:
            self.metrics.count("close_for_unknown_session")
            return
        self.gateway.close_session(sid)

    def _on_drain_session(self, msg: dict) -> None:
        """Migration source side: serve everything queued, export the
        session bit-exact, hand the state to the router via the control
        topic, release the slot."""
        sid = msg["session"]
        self._failed_drains.pop(sid, None)
        if self.pool.handle_for(sid) is None:
            self.metrics.count("drain_for_unknown_session")
            log.warning(
                "worker %s: drain_session for unknown %s",
                self.worker_id, sid)
            return
        # drain the WHOLE gateway: the batcher may hold this session's
        # ticks behind other sessions', and a flush is all-or-nothing —
        # serving everything queued guarantees the exported state is
        # current and every pre-drain result is published
        self.gateway.drain()
        state = encode_session_state(self.gateway.export_session(sid))
        if self._control_is_json() or int(msg.get("wire", 1)) < 2:
            state = to_legacy(state)  # pre-v2 envelopes for an old peer
        # buffered AFTER the drained results, so the broker lands every
        # pre-drain result before the state (the router's ordering
        # argument leans on exactly this)
        landed = self._publish_control_counted({
            "kind": "session_state",
            "worker": self.worker_id,
            "session": sid,
            "mig": msg.get("mig"),
            "state": state,
        })
        if landed and self._batch_bus is not None:
            # over a batched SocketBus the publish above only QUEUED the
            # state in the BufferedPublisher — push the frame out now
            # and find out whether it actually landed.  Closing the
            # session on a buffered-but-unsent export would destroy the
            # only copy the moment the next batch frame failed.
            landed = self._flush_control_batched()
        if not landed:
            # the exported state never left this process: closing the
            # session now would destroy the only copy.  Keep serving it
            # and retry from the step loop once the control plane is
            # back (a retry re-drains and re-exports, so the state is
            # current; the stale mig id on any late duplicate is
            # ignored router-side)
            self.metrics.count("drain_export_failed")
            self._failed_drains[sid] = (
                msg.get("mig"), int(msg.get("wire", 1)))
            return
        self.gateway.close_session(sid)
        self.metrics.count("sessions_migrated_out")

    def _flush_control_batched(self) -> bool:
        """Flush the BufferedPublisher in one batched frame and report
        whether every control-topic op landed.  Values in failed ops are
        lost — counted exactly like ``_poll_inbox``'s batched-publish
        failures (the dropped results age into ``results_missing``
        router-side)."""
        ops = self._pub.take_ops()
        if not ops:
            return True
        try:
            resps = self._batch_bus.batch(ops)
        except (ConnectionError, OSError) as e:
            self.metrics.count("control_errors")
            self.metrics.count(
                "publish_errors",
                sum(len(op.get("values", ())) for op in ops))
            log.warning(
                "worker %s: control flush failed: %s", self.worker_id, e)
            return False
        ok = True
        for op, resp in zip(ops, resps):
            if "err" in resp:
                self.metrics.count(
                    "publish_errors", len(op.get("values", ())))
                log.error(
                    "worker %s: batched publish to %r failed: %s",
                    self.worker_id, op.get("topic"), resp["err"])
                if op.get("topic") == self.control_topic:
                    ok = False
        return ok

    def _retry_failed_drains(self) -> None:
        """Re-run the drain for every migration whose state export
        failed, now that the control plane answers again.  Each retry
        re-exports fresh state (the session kept serving meanwhile), so
        the router never imports a stale snapshot."""
        for sid, (mig, wire) in list(self._failed_drains.items()):
            if self.pool.handle_for(sid) is None:
                self._failed_drains.pop(sid, None)  # closed meanwhile
                continue
            self.metrics.count("drain_export_retries")
            self._on_drain_session(
                {"session": sid, "mig": mig, "wire": wire})
            if sid in self._failed_drains:
                return  # control plane still down — keep the rest queued
