"""Bit-exact wire codec for migrated session state and tick rows.

Session migration's contract is *bit identity*: a session served on its
new owner must produce exactly the float stream it would have produced
unmigrated.  Since the binary data plane (ISSUE 12, :mod:`fmda_tpu
.stream.codec`) the state export moves **raw arrays**: dtype/shape/raw
IEEE bytes frames on a binary link, tagged base64 only when a link
negotiated down to the JSON fallback — either way no float→decimal→
float round trip, and the encode side is format-independent (the wire
layer lowers arrays per link at frame time).  The decoders also accept
the pre-v2 ``{"d", "sh", "b"}`` base64 envelope, so state exported by
an old peer (or parked in an old router's registry) still imports.

numpy only — this runs in the router process (bus-only host, no jax).
"""

from __future__ import annotations

import base64
from typing import Optional, Union

import numpy as np

WireArray = Union[np.ndarray, dict]


def encode_array(a: np.ndarray) -> np.ndarray:
    """Array -> wire form: the contiguous array itself.  The transport
    codec carries it raw (binary links) or tagged base64 (JSON links);
    in-process buses pass it through untouched."""
    return np.ascontiguousarray(a)


def decode_array(d: WireArray) -> np.ndarray:
    """Wire form -> array.  Accepts the raw array (v2 wire, possibly a
    read-only view into a received frame — treat as immutable) and the
    legacy base64 envelope."""
    if isinstance(d, np.ndarray):
        return d
    a = np.frombuffer(base64.b64decode(d["b"]), dtype=np.dtype(d["d"]))
    return a.reshape(d["sh"]).copy()  # own the buffer (frombuffer is RO)


def encode_row(row: np.ndarray) -> np.ndarray:
    """A (F,) float32 tick row in wire form (the tick hot path).  The
    copy makes the outgoing queue own the row — the caller may reuse
    its buffer the moment submit returns."""
    return np.array(row, np.float32)


def decode_row(wire: Union[np.ndarray, str], n_features: int) -> np.ndarray:
    """Wire form -> (F,) float32 row; accepts the raw array (v2, a
    zero-copy view) and the legacy bare-base64 string."""
    if isinstance(wire, np.ndarray):
        row = np.asarray(wire, np.float32)
    else:
        row = np.frombuffer(base64.b64decode(wire), dtype=np.float32)
    if row.shape != (n_features,):
        raise ValueError(
            f"tick row decodes to shape {row.shape}, expected "
            f"({n_features},)")
    return row


def legacy_array(a: np.ndarray) -> dict:
    """Array -> the pre-v2 base64 envelope, bit-exact (raw bytes b64)."""
    a = np.ascontiguousarray(a)
    return {
        "d": a.dtype.str,
        "sh": list(a.shape),
        "b": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def to_legacy(value):
    """Deep-lower every raw array in a wire value to the pre-v2 base64
    envelope.  Senders apply this on links that negotiated down to JSON
    (docs/multihost.md "Wire format v2"): the frame *encoding* already
    fell back at negotiation, but a genuinely pre-v2 peer also needs
    the pre-v2 payload *shapes* — v2 decoders accept both, so lowering
    on every JSON link is safe whatever the peer's age."""
    if isinstance(value, np.ndarray):
        return legacy_array(value)
    if isinstance(value, dict):
        return {k: to_legacy(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [to_legacy(v) for v in value]
    return value


def legacy_tick(msg: dict) -> dict:
    """A v2 tick message in pre-v2 form: bare-base64 row (the old
    ``encode_row`` output — no envelope; both ends know the schema)."""
    out = dict(msg)
    out["row"] = base64.b64encode(
        np.ascontiguousarray(out["row"], np.float32).tobytes()
    ).decode("ascii")
    return out


def to_legacy_msgs(msgs) -> list:
    """Lower a router's outgoing batch for a JSON link: per-tick
    messages with base64 rows (no columnar blocks — an old worker has
    no ``tick_block`` handler) and enveloped arrays everywhere else
    (opens carry norm stats, forwarded migrations carry state)."""
    return [legacy_tick(m) if m.get("kind") == "tick" else to_legacy(m)
            for m in msgs]


def encode_norm(norm) -> Optional[dict]:
    """NormParams -> wire dict (None passes through: default stats)."""
    if norm is None:
        return None
    return {
        "x_min": encode_array(np.asarray(norm.x_min, np.float32)),
        "x_max": encode_array(np.asarray(norm.x_max, np.float32)),
    }


def decode_norm(msg: Optional[dict]):
    if msg is None:
        return None
    from fmda_tpu.data.normalize import NormParams

    return NormParams(
        decode_array(msg["x_min"]), decode_array(msg["x_max"]))


def encode_param_tree(tree):
    """A checkpoint params tree (nested dicts/lists with array leaves)
    -> wire form: structure preserved, every leaf a contiguous array
    (raw on binary links; :func:`to_legacy` lowers per-link on JSON
    fallbacks).  numpy-only on purpose — the router broadcasts hot
    swaps without ever importing jax."""
    if isinstance(tree, dict):
        return {k: encode_param_tree(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [encode_param_tree(v) for v in tree]
    return encode_array(np.asarray(tree))


def decode_param_tree(tree):
    """Wire form -> params tree.  A dict is a structure node unless it
    is the legacy ``{"d", "sh", "b"}`` base64 envelope — the only dict
    shape :func:`decode_array` accepts — so pre-v2 lowered trees decode
    to the same leaves bit-exact."""
    if isinstance(tree, dict):
        if set(tree.keys()) == {"d", "sh", "b"}:
            return decode_array(tree)
        return {k: decode_param_tree(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [decode_param_tree(v) for v in tree]
    return decode_array(tree)


def encode_session_state(state: dict) -> dict:
    """:meth:`FleetGateway.export_session` output -> wire form."""
    out = {
        "carry": [
            [encode_array(part) for part in layer]
            for layer in state["carry"]
        ],
        "ring": encode_array(state["ring"]),
        "pos": int(state["pos"]),
        "x_min": encode_array(state["x_min"]),
        "x_range": encode_array(state["x_range"]),
        "seq": int(state["seq"]),
    }
    if state.get("tenant") is not None:
        # the QoS class migrates with the session (fmda_tpu.control);
        # pre-v2 decoders simply drop the extra key
        out["tenant"] = str(state["tenant"])
    return out


def decode_session_state(msg: dict) -> dict:
    """Wire form -> :meth:`FleetGateway.import_session` input."""
    out = {
        "carry": [
            [decode_array(part) for part in layer]
            for layer in msg["carry"]
        ],
        "ring": decode_array(msg["ring"]),
        "pos": int(msg["pos"]),
        "x_min": decode_array(msg["x_min"]),
        "x_range": decode_array(msg["x_range"]),
        "seq": int(msg["seq"]),
    }
    if msg.get("tenant") is not None:
        out["tenant"] = str(msg["tenant"])
    return out
