"""Bit-exact wire codec for migrated session state and tick rows.

Session migration's contract is *bit identity*: a session served on its
new owner must produce exactly the float stream it would have produced
unmigrated.  JSON float lists round-trip doubles exactly but are slow
and 4-5x the size for float32 data, so arrays cross the bus as
``{"d": dtype, "sh": shape, "b": base64(raw bytes)}`` — raw IEEE bytes,
no textual re-parse, decoded with ``np.frombuffer``.  The same encoding
carries every tick's feature row: at fleet tick rates the row codec IS
the router's hot path, and base64 of 432 raw bytes beats a 108-element
JSON float list by ~4x in both bytes and CPU.

numpy only — this runs in the router process (bus-only host, no jax).
"""

from __future__ import annotations

import base64
from typing import Optional

import numpy as np


def encode_array(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {
        "d": a.dtype.str,
        "sh": list(a.shape),
        "b": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def decode_array(d: dict) -> np.ndarray:
    a = np.frombuffer(base64.b64decode(d["b"]), dtype=np.dtype(d["d"]))
    return a.reshape(d["sh"]).copy()  # own the buffer (frombuffer is RO)


def encode_row(row: np.ndarray) -> str:
    """A (F,) float32 tick row as bare base64 (the tick hot path — no
    dtype/shape envelope; both ends know the schema)."""
    return base64.b64encode(
        np.ascontiguousarray(row, np.float32).tobytes()).decode("ascii")


def decode_row(b64: str, n_features: int) -> np.ndarray:
    row = np.frombuffer(base64.b64decode(b64), dtype=np.float32)
    if row.shape != (n_features,):
        raise ValueError(
            f"tick row decodes to shape {row.shape}, expected "
            f"({n_features},)")
    return row


def encode_norm(norm) -> Optional[dict]:
    """NormParams -> wire dict (None passes through: default stats)."""
    if norm is None:
        return None
    return {
        "x_min": encode_array(np.asarray(norm.x_min, np.float32)),
        "x_max": encode_array(np.asarray(norm.x_max, np.float32)),
    }


def decode_norm(msg: Optional[dict]):
    if msg is None:
        return None
    from fmda_tpu.data.normalize import NormParams

    return NormParams(
        decode_array(msg["x_min"]), decode_array(msg["x_max"]))


def encode_session_state(state: dict) -> dict:
    """:meth:`FleetGateway.export_session` output -> wire form."""
    return {
        "carry": [
            [encode_array(part) for part in layer]
            for layer in state["carry"]
        ],
        "ring": encode_array(state["ring"]),
        "pos": int(state["pos"]),
        "x_min": encode_array(state["x_min"]),
        "x_range": encode_array(state["x_range"]),
        "seq": int(state["seq"]),
    }


def decode_session_state(msg: dict) -> dict:
    """Wire form -> :meth:`FleetGateway.import_session` input."""
    return {
        "carry": [
            [decode_array(part) for part in layer]
            for layer in msg["carry"]
        ],
        "ring": decode_array(msg["ring"]),
        "pos": int(msg["pos"]),
        "x_min": decode_array(msg["x_min"]),
        "x_range": decode_array(msg["x_range"]),
        "seq": int(msg["seq"]),
    }
