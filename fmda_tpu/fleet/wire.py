"""Cross-process bus transport: a MessageBus served over TCP.

The framework's local bus backends live inside one process (InProcessBus
is Python objects, NativeBus a C++ arena in process memory); Kafka is
the cross-process answer in production but demands an external broker.
This module is the framework-owned middle: the fleet **router** hosts
its bus (NativeBus when buildable, InProcessBus otherwise) and serves it
on a socket with :class:`BusServer`; every worker connects a
:class:`SocketBus` — the same :class:`~fmda_tpu.stream.bus.MessageBus`
contract, so gateways/engines/consumers run unchanged over it.

Framing: every request and response is one length-prefixed frame —
4-byte big-endian length, then that many bytes of payload.  Since wire
format v2 (docs/multihost.md) a payload is either UTF-8 JSON **or** a
binary codec frame (:mod:`fmda_tpu.stream.codec` — magic-byte-first, so
every receiver auto-detects per frame); clients negotiate the binary
format with a ``hello`` op at connect and fall back to JSON against a
server that does not (or is configured not to) speak it, so old and new
peers interoperate and ``wire_format=json`` is the rollback switch.  A
connection's requests are strictly serialized by the client (one lock
around request→response), and the server handles each connection on its
own thread against the thread-safe backing bus — so two processes
publishing concurrently can interleave *records* (fine: offsets stay
monotonic, each process's order is preserved) but never *frames* (a
torn frame would corrupt every later message on the connection; the
router↔worker transport contract test asserts both properties).

Error taxonomy (symmetric across formats): **transport** errors —
socket failures, EOF mid-frame, a length prefix past the frame limit —
kill the connection (``ConnectionError``); **decode** errors — a
well-framed payload that is not valid JSON or a valid codec frame —
surface as :class:`FrameDecodeError`, are counted
(``frames_malformed_total``), and leave the connection usable: the
frame was fully consumed, so framing alignment is intact and one
confused peer's message can no longer kill the link.

No jax anywhere near this module: a router host is a bus-only host.
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from fmda_tpu.chaos.inject import default_chaos
from fmda_tpu.stream import codec
from fmda_tpu.obs.trace import default_tracer, stamp_message, stamp_messages
from fmda_tpu.stream.bus import Consumer, Record

log = logging.getLogger("fmda_tpu.fleet")

_TRACER = default_tracer()
#: chaos injection (fmda_tpu.chaos): disabled = one branch per request
_CHAOS = default_chaos()

#: Frame-size ceiling (4-byte length prefix allows 4 GiB; a frame this
#: large is a bug, not a batch).
MAX_FRAME_BYTES = 64 * 1024 * 1024

#: ``wire_format`` knob values (config ``[fleet] wire_format``):
#: ``auto`` negotiates binary and falls back, ``binary`` insists (still
#: falls back, loudly), ``json`` never negotiates — the rollback and
#: debug format.
WIRE_FORMATS = ("auto", "binary", "json")

_LEN = struct.Struct(">I")


class FrameDecodeError(RuntimeError):
    """A well-framed payload that failed to decode (not JSON, not a
    valid codec frame).  The frame was consumed whole, so the
    connection's framing alignment is intact — callers treat this as a
    lost *message* (counted), never a lost *link*."""


def _check_wire_format(wire_format: str) -> str:
    if wire_format not in WIRE_FORMATS:
        raise ValueError(
            f"wire_format {wire_format!r} not one of {WIRE_FORMATS}")
    return wire_format


class _FrameIO:
    """Buffered length-prefixed framing over one socket.

    Receives into a process-side buffer with large ``recv`` calls, so a
    frame costs O(frame/1MB) syscalls instead of one per header/body —
    on sandboxed kernels a syscall runs ~100µs, and syscall count IS the
    transport's latency budget.  One ``sendall`` per outgoing frame.

    Payloads are JSON text or binary codec frames; ``recv_frame``
    auto-detects per frame (``last_binary`` reports which) and
    ``counts`` tracks per-format frame totals plus malformed payloads.
    """

    __slots__ = ("sock", "_buf", "counts", "last_binary")

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self._buf = bytearray()
        self.counts: Dict[str, int] = {
            "binary": 0, "json": 0, "malformed": 0}
        #: format of the most recently decoded incoming frame
        self.last_binary = False

    def send_frame(self, obj: object, *, binary: bool = False) -> None:
        payload = codec.encode_payload(obj, binary=binary)
        if len(payload) > MAX_FRAME_BYTES:
            raise RuntimeError(
                f"frame of {len(payload)}B exceeds the {MAX_FRAME_BYTES}B "
                "transport limit")
        self.counts["binary" if binary else "json"] += 1
        self.sock.sendall(_LEN.pack(len(payload)) + payload)

    def _fill(self, need: int) -> bool:
        """Grow the buffer to ``need`` bytes; False on clean EOF with an
        empty buffer, raises on EOF mid-frame."""
        while len(self._buf) < need:
            chunk = self.sock.recv(1 << 20)
            if not chunk:
                if not self._buf:
                    return False
                raise ConnectionError(
                    f"peer closed mid-frame ({len(self._buf)}/{need} "
                    "bytes)")
            self._buf += chunk
        return True

    def recv_frame(self) -> Optional[object]:
        if not self._fill(_LEN.size):
            return None
        (length,) = _LEN.unpack(self._buf[:_LEN.size])
        if length > MAX_FRAME_BYTES:
            # transport-level: the framing itself cannot be trusted
            # past this point, so unlike a payload decode error this
            # DOES kill the connection
            raise ConnectionError(
                f"peer announced a {length}B frame (> {MAX_FRAME_BYTES}B "
                "limit) — stream corrupt or not speaking this protocol")
        total = _LEN.size + length
        if not self._fill(total):
            raise ConnectionError("peer closed between header and body")
        body = bytes(self._buf[_LEN.size:total])
        del self._buf[:total]
        # the frame is consumed whole BEFORE decoding: a malformed
        # payload costs one message, never the connection's alignment
        try:
            obj, was_binary = codec.decode_payload(body)
        except codec.CodecError as e:
            self.counts["malformed"] += 1
            raise FrameDecodeError(
                f"malformed {length}B frame: {e}") from e
        self.last_binary = was_binary
        self.counts["binary" if was_binary else "json"] += 1
        return obj


def parse_address(address: str) -> Tuple[str, int]:
    """``"host:port"`` -> (host, port); bare ``":port"`` means localhost."""
    host, sep, port = address.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(
            f"bus address {address!r} is not of the form host:port")
    return host or "127.0.0.1", int(port)


class BusServer:
    """Serves a backing MessageBus to SocketBus clients.

    One accept-loop thread plus one thread per connection; every op maps
    1:1 onto the backing bus's method, so the server adds transport, not
    semantics.  Op errors travel back as ``{"err", "kind"}`` frames and
    re-raise client-side; transport errors drop only the one connection;
    decode errors (a malformed frame from a confused peer) are counted
    and answered with an error frame — the connection survives.

    Responses mirror the request's format (a binary request gets a
    binary response) unless ``wire_format="json"`` pins everything to
    JSON; the ``hello`` op tells negotiating clients which formats this
    server will answer in.
    """

    def __init__(
        self, bus, *, host: str = "127.0.0.1", port: int = 0,
        wire_format: str = "auto",
    ) -> None:
        self.bus = bus
        self._host = host
        self._requested_port = port
        self._wire_format = _check_wire_format(wire_format)
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._ios: set = set()
        self._lock = threading.Lock()
        self._closing = False
        #: frame totals folded in from closed connections
        self._frame_totals: Dict[str, int] = {
            "binary": 0, "json": 0, "malformed": 0}

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "BusServer":
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind((self._host, self._requested_port))
        listener.listen(64)
        self._listener = listener
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fmda-bus-server", daemon=True)
        self._accept_thread.start()
        log.info("bus server listening on %s:%d", self._host, self.port)
        return self

    @property
    def port(self) -> int:
        if self._listener is None:
            raise RuntimeError("server not started")
        return self._listener.getsockname()[1]

    @property
    def address(self) -> str:
        return f"{self._host}:{self.port}"

    def stop(self) -> None:
        self._closing = True
        if self._listener is not None:
            # shutdown BEFORE close: on Linux, closing an fd does not
            # wake a thread blocked in accept() on it (stop() used to
            # eat the full 5s join timeout per server — multiplied
            # across every test teardown and topology shutdown);
            # shutdown interrupts the accept with an error immediately
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:  # loss-free: teardown; close() follows
                pass  # some platforms refuse shutdown on a listener
            try:
                self._listener.close()
            except OSError:  # loss-free: teardown of a dead listener
                pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:  # loss-free: teardown; close() follows
                pass
            try:
                conn.close()
            except OSError:  # loss-free: teardown of a dying connection
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)

    def frame_stats(self) -> Dict[str, int]:
        """Frame totals across every connection this server ever had
        (live connections sampled in place) — ``binary``/``json``/
        ``malformed``, the server side of the obs counters."""
        with self._lock:
            out = dict(self._frame_totals)
            ios = list(self._ios)
        for io in ios:
            for k, v in io.counts.items():
                out[k] += v
        return out

    # -- the serve loops ----------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._listener.accept()
            # loss-free: the listener died or stop() closed it — no
            # frame was in flight on the not-yet-accepted connection
            except OSError:
                return  # listener closed (stop)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_client, args=(conn,),
                name="fmda-bus-client", daemon=True).start()

    def _serve_client(self, conn: socket.socket) -> None:
        io = _FrameIO(conn)
        with self._lock:
            self._ios.add(io)
        try:
            while True:
                try:
                    req = io.recv_frame()
                except FrameDecodeError as e:
                    # one malformed frame from a confused peer used to
                    # kill the whole link (it was caught with the
                    # transport errors); decode errors are now counted
                    # and answered — the connection survives
                    log.warning("malformed frame (connection kept): %s", e)
                    try:
                        io.send_frame({"err": str(e),
                                       "kind": "FrameDecodeError"})
                    # loss-free: the error answer failed — the peer is
                    # gone; the malformed frame itself was already
                    # counted (frames_malformed_total) in recv_frame
                    except (OSError, RuntimeError):
                        return
                    continue
                # loss-free: transport death ends the connection; every
                # client hardens against it (link_errors / bus_errors
                # are counted by the owner that loses the link)
                except (ConnectionError, OSError):
                    return
                if req is None:
                    return  # clean disconnect
                # respond in the request's format: binary for binary
                # peers, JSON for JSON peers and hand-crafted debug
                # frames — unless this server is pinned to JSON
                binary = io.last_binary and self._wire_format != "json"
                resp = self._respond(req)
                try:
                    io.send_frame(resp, binary=binary)
                except codec.CodecError:
                    # a response value the negotiated format cannot
                    # carry — answer with an error frame instead of
                    # killing the link
                    try:
                        io.send_frame({"err": "unencodable response",
                                       "kind": "FrameDecodeError"})
                    # loss-free: peer gone mid-apology — the op already
                    # executed; the client re-counts on its side
                    except (OSError, RuntimeError):
                        return
                # loss-free: transport death; the client's request
                # raises ConnectionError and its owner counts the loss
                except (OSError, RuntimeError):
                    return
        finally:
            with self._lock:
                self._conns.discard(conn)
                self._ios.discard(io)
                for k, v in io.counts.items():
                    self._frame_totals[k] += v
            try:
                conn.close()
            except OSError:  # loss-free: teardown of a finished connection
                pass

    def _respond(self, req: dict) -> dict:
        try:
            return {"ok": self._dispatch(req)}
        # loss-free: nothing is swallowed by either handler — the
        # failure is converted to an err frame and re-raised client-side
        # by SocketBus._unwrap
        except KeyError as e:
            return {"err": str(e), "kind": "KeyError"}
        except Exception as e:  # noqa: BLE001 — loss-free: op failure is
            # the client's problem (re-raised there); the connection
            # stays usable
            return {"err": f"{e!r}", "kind": type(e).__name__}

    def _dispatch(self, req: dict) -> object:
        op = req.get("op")
        bus = self.bus
        if op == "batch":
            # several ops, one frame, one round trip: on high-syscall-
            # latency hosts the RT count — not bytes or CPU — is the
            # throughput ceiling, so router pumps and worker steps ride
            # one frame each.  Sub-ops run in order; each fails alone.
            return [self._respond(sub) for sub in req["ops"]]
        if op == "publish":
            return bus.publish(req["topic"], req["value"])
        if op == "publish_many":
            return bus.publish_many(req["topic"], req["values"])
        if op == "read":
            records = bus.read(
                req["topic"], int(req["offset"]), req.get("max_records"))
            return [[r.offset, r.value] for r in records]
        if op == "end_offset":
            return bus.end_offset(req["topic"])
        if op == "add_topic":
            add = getattr(bus, "add_topic", None)
            if add is None:
                raise RuntimeError(
                    f"backing bus {type(bus).__name__} cannot create "
                    f"topic {req['topic']!r} dynamically")
            add(req["topic"])
            return True
        if op == "base_offset":
            base = getattr(bus, "base_offset", None)
            return base(req["topic"]) if base is not None else 0
        if op == "topics":
            return list(bus.topics())
        if op == "ping":
            return "pong"
        if op == "hello":
            # wire-format negotiation (v2): the client lists the formats
            # it speaks; the server picks.  Old servers answer this op
            # with an unknown-op error, which the client reads as "JSON
            # only" — old and new peers interoperate either way.
            formats = req.get("formats") or ()
            chosen = ("binary" if self._wire_format != "json"
                      and "binary" in formats else "json")
            return {"format": chosen, "version": codec.CODEC_VERSION}
        raise RuntimeError(f"unknown bus op {op!r}")


class SocketBus:
    """MessageBus client over one BusServer connection.

    Same contract as InProcessBus/NativeBus/KafkaBus — topics, monotonic
    offsets, independent consumers — with each call one request/response
    round trip (reads are batched server-side, so a backlogged consumer
    drains hundreds of records per round trip).  Thread-safe: a lock
    serializes frames on the connection.  No auto-reconnect — a broken
    connection raises, and the owner (worker loop) decides whether that
    is fatal (it is: a worker that lost its router must stop serving).

    ``wire_format`` selects the frame encoding: ``auto`` (default)
    negotiates the binary codec via a ``hello`` op and falls back to
    JSON against a server that does not offer it; ``binary`` does the
    same but logs the fallback as a warning; ``json`` skips negotiation
    entirely (the rollback switch — docs/multihost.md "Wire format v2").
    ``negotiated_format`` reports the outcome.
    """

    def __init__(
        self, host: str, port: int, *, timeout_s: Optional[float] = 60.0,
        wire_format: str = "auto",
    ) -> None:
        wire_format = _check_wire_format(wire_format)
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._io = _FrameIO(self._sock)
        self._lock = threading.Lock()
        self._topics: Optional[Tuple[str, ...]] = None
        self._publish_counters = None
        self._consumed_cb = None
        self.address = f"{host}:{port}"
        self._binary = False
        self.negotiated_format = "json"
        if wire_format != "json":
            self._negotiate(wire_format)

    @classmethod
    def connect(cls, address: str, **kwargs) -> "SocketBus":
        host, port = parse_address(address)
        return cls(host, port, **kwargs)

    def _negotiate(self, wire_format: str) -> None:
        """One ``hello`` round trip at connect: switch the connection to
        binary frames when the server offers them, JSON otherwise.
        Transport failures propagate (the connection is unusable); an
        op-level error means an old server — fall back silently on
        ``auto``, loudly on ``binary``."""
        try:
            resp = self._request({
                "op": "hello",
                "formats": ["binary", "json"],
                "version": codec.CODEC_VERSION,
            })
        except (ConnectionError, OSError):
            raise
        # loss-free: negotiation fallback — the connection continues on
        # JSON frames, no message existed yet to lose
        except (RuntimeError, KeyError):
            resp = None  # pre-v2 server: unknown op
        if isinstance(resp, dict) and resp.get("format") == "binary":
            self._binary = True
            self.negotiated_format = "binary"
        elif wire_format == "binary":
            log.warning(
                "bus server at %s does not speak the binary wire format "
                "— falling back to JSON frames", self.address)

    def close(self) -> None:
        with self._lock:
            try:
                self._sock.close()
            except OSError:  # loss-free: teardown of a dead socket
                pass

    def __enter__(self) -> "SocketBus":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def frame_stats(self) -> Dict[str, int]:
        """This connection's ``binary``/``json``/``malformed`` frame
        totals (the client side of the obs counters)."""
        return dict(self._io.counts)

    def bind_metrics(self, registry) -> None:
        """Same per-topic publish/consume counters as the other
        backends, counted client-side, plus the wire-format series:
        ``frames_binary_total``/``frames_json_total``/
        ``frames_malformed_total`` and the negotiated-format gauge
        ``wire_format_binary`` (1 = binary frames on this link)."""
        #: remembered so the owner can re-bind a REPLACEMENT connection
        #: to the same registry (worker control re-dial): the "wire"
        #: collector registration replaces the old one by name, so the
        #: series follow the live link instead of freezing on the dead
        self.metrics_registry = registry
        topics = self.topics()
        self._publish_counters = {
            t: registry.counter("bus_published_total", topic=t)
            for t in topics
        }
        consume_counters = {
            t: registry.counter("bus_consumed_total", topic=t)
            for t in topics
        }
        self._consumed_cb = (
            lambda topic, n: consume_counters[topic].inc(n)
        )

        def wire_families():
            counts = self.frame_stats()
            return {
                "counters": [
                    {"name": "frames_binary_total", "labels": {},
                     "value": counts["binary"]},
                    {"name": "frames_json_total", "labels": {},
                     "value": counts["json"]},
                    {"name": "frames_malformed_total", "labels": {},
                     "value": counts["malformed"]},
                ],
                "gauges": [
                    {"name": "wire_format_binary", "labels": {},
                     "value": 1.0 if self._binary else 0.0},
                ],
            }

        registry.register_collector("wire", wire_families)

    # -- request plumbing ---------------------------------------------------

    def _request(self, req: dict) -> object:
        if _CHAOS.enabled:
            # injection point "wire.request": a kill/partition window
            # raises ChaosFault (a ConnectionError — exactly the failure
            # every caller already hardens against); delay windows sleep
            _CHAOS.check("wire.request")
        with self._lock:
            try:
                self._io.send_frame(req, binary=self._binary)
                resp = self._io.recv_frame()
            except OSError as e:
                raise ConnectionError(
                    f"bus connection to {self.address} failed: {e}") from e
        if resp is None:
            raise ConnectionError(
                f"bus server at {self.address} closed the connection")
        return self._unwrap(req, resp)

    @staticmethod
    def _unwrap(req: dict, resp: dict) -> object:
        if "err" in resp:
            if resp.get("kind") == "KeyError":
                raise KeyError(resp["err"])
            raise RuntimeError(
                f"bus op {req.get('op')!r} failed remotely: {resp['err']}")
        return resp["ok"]

    def batch(self, ops: List[dict]) -> List[dict]:
        """Execute several ops in order in ONE round trip; returns the
        raw per-op ``{"ok": ...}`` / ``{"err", "kind"}`` dicts (each op
        fails alone — callers unwrap with :meth:`unwrap_op`).  The
        round-trip count is the transport's real cost on high-syscall-
        latency hosts, so hot loops bundle their whole cycle here."""
        if not ops:
            return []
        return self._request({"op": "batch", "ops": ops})

    def unwrap_op(self, op: dict, resp: dict) -> object:
        return self._unwrap(op, resp)

    # -- MessageBus ---------------------------------------------------------

    def publish(self, topic: str, value: dict) -> int:
        if _TRACER.enabled:  # in-band trace context, like every backend
            value = stamp_message(value)
        offset = self._request(
            {"op": "publish", "topic": topic, "value": value})
        if self._publish_counters is not None:
            counter = self._publish_counters.get(topic)
            if counter is not None:
                counter.inc()
        return int(offset)

    def publish_many(self, topic: str, values) -> List[int]:
        values = list(values)
        if not values:
            return []
        if _TRACER.enabled:
            values = stamp_messages(values)
        offsets = self._request(
            {"op": "publish_many", "topic": topic, "values": values})
        if self._publish_counters is not None and offsets:
            counter = self._publish_counters.get(topic)
            if counter is not None:
                counter.inc(len(offsets))
        return [int(o) for o in offsets]

    def read(
        self, topic: str, offset: int, max_records: Optional[int] = None
    ) -> List[Record]:
        rows = self._request({
            "op": "read", "topic": topic, "offset": int(offset),
            "max_records": max_records,
        })
        return [Record(topic, int(o), v) for o, v in rows]

    def end_offset(self, topic: str) -> int:
        return int(self._request({"op": "end_offset", "topic": topic}))

    def base_offset(self, topic: str) -> int:
        return int(self._request({"op": "base_offset", "topic": topic}))

    def add_topic(self, topic: str) -> None:
        """Create a topic on the served bus (idempotent; raises if the
        backing bus cannot create topics dynamically)."""
        self._request({"op": "add_topic", "topic": topic})
        self._topics = None  # the cached layout just changed

    def topics(self) -> Sequence[str]:
        if self._topics is None:
            self._topics = tuple(self._request({"op": "topics"}))
        return self._topics

    def consumer(self, topic: str, *, from_end: bool = False) -> Consumer:
        c = Consumer(self, topic)
        if from_end:
            c.seek_to_end()
        return c

    def ping(self) -> bool:
        return self._request({"op": "ping"}) == "pong"


class BufferedPublisher:
    """A publish-only bus front that coalesces into batch ops.

    The fleet worker's gateway publishes one ``publish_many`` per flush
    and its heartbeater one ``publish`` per beat; over a SocketBus each
    would be its own round trip.  This buffer queues them (preserving
    call order) and the worker's step flushes everything — plus its
    inbox read — in one batched frame.  Same ``publish``/
    ``publish_many``/``topics`` surface the gateway already speaks, so
    it drops in unchanged.  Values are queued as-is — pre-encoded
    column blocks and raw arrays included — and encoded exactly once,
    when the batched frame leaves on the negotiated wire format.
    """

    def __init__(self, bus: SocketBus) -> None:
        self._bus = bus
        #: (topic, [values]) in call order — order across topics is
        #: preserved (the migration protocol publishes results BEFORE
        #: the exported state; the broker must apply them that way)
        self._pending: List[Tuple[str, List[dict]]] = []

    def topics(self) -> Sequence[str]:
        return self._bus.topics()

    def publish(self, topic: str, value: dict) -> None:
        if _TRACER.enabled:
            value = stamp_message(value)
        self._pending.append((topic, [value]))

    def publish_many(self, topic: str, values) -> None:
        values = list(values)
        if not values:
            return
        if _TRACER.enabled:
            values = stamp_messages(values)
        self._pending.append((topic, values))

    @property
    def pending(self) -> int:
        return sum(len(v) for _, v in self._pending)

    def take_ops(self) -> List[dict]:
        """Drain the buffer into batch ops (coalescing consecutive
        same-topic entries into one publish_many)."""
        ops: List[dict] = []
        for topic, values in self._pending:
            if ops and ops[-1]["topic"] == topic:
                ops[-1]["values"].extend(values)
            else:
                ops.append({"op": "publish_many", "topic": topic,
                            "values": list(values)})
        self._pending.clear()
        return ops

    def flush(self) -> None:
        """Publish everything buffered in one round trip (shutdown and
        migration-export paths call this directly)."""
        ops = self.take_ops()
        for op, resp in zip(ops, self._bus.batch(ops)):
            self._bus.unwrap_op(op, resp)
