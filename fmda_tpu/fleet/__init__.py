"""fmda_tpu.fleet — the multi-host distributed serving tier.

N worker processes (each embedding the single-process fleet runtime:
:class:`~fmda_tpu.runtime.gateway.FleetGateway` +
:class:`~fmda_tpu.runtime.session_pool.SessionPool`) each own a
contiguous slot-range of the session hash space
(:mod:`~fmda_tpu.fleet.hashring`), fronted by a
:class:`~fmda_tpu.fleet.router.FleetRouter` that hashes session → owner
over the cross-process bus (:mod:`~fmda_tpu.fleet.wire` serves the
router's NativeBus/InProcessBus to SocketBus workers; KafkaBus slots in
for prod), with heartbeat membership (:mod:`~fmda_tpu.fleet.membership`)
and live session migration that never drops, duplicates, or reorders a
tick (:mod:`~fmda_tpu.fleet.state` carries the state bit-exact).
``python -m fmda_tpu serve-fleet --role router|worker|local`` runs the
topology.  Architecture: docs/multihost.md.

Router-role names import **without jax** — a router is a bus-only host;
the tier-1 hygiene check pins that.  :class:`FleetWorker` and the local
launcher (which builds worker models) resolve lazily.
"""

from fmda_tpu.fleet.hashring import OwnershipTable, hash_session
from fmda_tpu.fleet.membership import Heartbeater, MembershipView
from fmda_tpu.fleet.router import FleetRouter, NoLiveWorkers
from fmda_tpu.fleet.wire import BusServer, SocketBus

#: worker/launcher names — lazy: they pull jax via the runtime
_LAZY = {
    "FleetWorker": "fmda_tpu.fleet.worker",
    "LocalFleet": "fmda_tpu.fleet.launcher",
    "launch_local_fleet": "fmda_tpu.fleet.launcher",
    "spawn_supported": "fmda_tpu.fleet.launcher",
}

__all__ = sorted([
    "OwnershipTable",
    "hash_session",
    "Heartbeater",
    "MembershipView",
    "FleetRouter",
    "NoLiveWorkers",
    "BusServer",
    "SocketBus",
    *_LAZY,
])


def __getattr__(name: str):
    module_name = _LAZY.get(name)
    if module_name is None:
        raise AttributeError(
            f"module 'fmda_tpu.fleet' has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(module_name), name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
