"""Session → owner mapping: a versioned table of contiguous hash ranges.

The session space is a fixed hash ring of ``space`` points; every live
worker owns one **contiguous** slot-range of it (equal shares, remainder
spread one point at a time over the first workers).  Contiguous ranges —
rather than consistent-hashing's scattered virtual nodes — keep the
table tiny (one ``(worker, lo, hi)`` row per worker), make "which
sessions move on membership change" a range intersection, and mirror how
the in-process :class:`~fmda_tpu.runtime.session_pool.SessionPool`
shards its slot axis across chips: the fleet is the same idea one level
up, processes instead of devices (PAPERS.md, pjit mesh topology).

Hashing is :func:`zlib.crc32` — stable across processes and Python
runs (``hash()`` is per-process salted, which would route the same
session to different owners from different processes).

The table is **versioned**: the router bumps the version on every
membership change and announces the new table on the control topic, so
a worker (or an operator reading ``status``) can tell a stale
announcement from the current one.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

#: Default hash-ring size (FleetTopologyConfig.hash_space).
DEFAULT_HASH_SPACE = 1 << 16


def hash_session(session_id: str, space: int = DEFAULT_HASH_SPACE) -> int:
    """Deterministic session hash in ``[0, space)`` — identical from
    every process, every run."""
    return zlib.crc32(session_id.encode("utf-8")) % space


@dataclass(frozen=True)
class OwnershipTable:
    """One immutable version of the session-space partition."""

    version: int
    #: ``(worker_id, lo, hi)`` half-open ranges, ascending, disjoint,
    #: covering ``[0, space)`` exactly (empty when no workers live).
    ranges: Tuple[Tuple[str, int, int], ...]
    space: int = DEFAULT_HASH_SPACE

    @classmethod
    def derive(
        cls, version: int, worker_ids: Sequence[str],
        space: int = DEFAULT_HASH_SPACE,
    ) -> "OwnershipTable":
        """Equal contiguous shares over the sorted live workers.  Sorting
        makes the table a pure function of the membership set — every
        observer derives the same partition from the same workers."""
        workers = sorted(set(worker_ids))
        if not workers:
            return cls(version, (), space)
        n = len(workers)
        share, rem = divmod(space, n)
        ranges = []
        lo = 0
        for i, wid in enumerate(workers):
            hi = lo + share + (1 if i < rem else 0)
            ranges.append((wid, lo, hi))
            lo = hi
        return cls(version, tuple(ranges), space)

    def owner_of_point(self, point: int) -> Optional[str]:
        for wid, lo, hi in self.ranges:
            if lo <= point < hi:
                return wid
        return None

    def owner_of(self, session_id: str) -> Optional[str]:
        """The live owner of a session, or None when no workers exist."""
        if not self.ranges:
            return None
        return self.owner_of_point(hash_session(session_id, self.space))

    @property
    def workers(self) -> Tuple[str, ...]:
        return tuple(w for w, _, _ in self.ranges)

    # -- wire form (control-topic announcements) ----------------------------

    def to_wire(self) -> dict:
        return {
            "version": self.version,
            "space": self.space,
            "ranges": [list(r) for r in self.ranges],
        }

    @classmethod
    def from_wire(cls, msg: dict) -> "OwnershipTable":
        return cls(
            int(msg["version"]),
            tuple((str(w), int(lo), int(hi)) for w, lo, hi in msg["ranges"]),
            int(msg.get("space", DEFAULT_HASH_SPACE)),
        )
