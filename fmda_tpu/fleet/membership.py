"""Heartbeat-based fleet membership over the control topic.

Workers announce themselves (``hello``), prove liveness on a cadence
(``heartbeat``), and leave gracefully (``goodbye``); the router folds
those into a live set and declares a worker dead after
``heartbeat_timeout_s`` of silence.  Two disciplines keep this honest
across processes:

- **Receipt-time clocks.**  Liveness is judged on the *router's* clock
  at message receipt, never on the sender's timestamp — cross-process
  clock skew can therefore delay a death verdict but never mis-kill a
  healthy worker (and tests drive the whole protocol with a fake clock).
- **Stats ride the heartbeat.**  Every beat carries the worker's
  serving counters (active sessions, ticks served, compile count), so
  the router — and ``status`` — always has a fleet-wide view without a
  second RPC surface.

No jax: membership is router-role code (a bus-only host).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

log = logging.getLogger("fmda_tpu.fleet")

#: control-message kinds a worker emits
HELLO = "hello"
HEARTBEAT = "heartbeat"
GOODBYE = "goodbye"


@dataclass
class WorkerInfo:
    """What the router knows about one worker."""

    worker_id: str
    #: router-clock stamp of the last message received from it
    last_seen: float
    #: router-clock stamp of the hello (join time)
    joined_at: float
    #: advertised session capacity (admission headroom planning)
    capacity: int = 0
    #: the newest stats dict its heartbeat carried
    stats: Dict[str, object] = field(default_factory=dict)
    #: the worker's announced metrics endpoint (``host:port`` of its
    #: /snapshot scrape surface), when it runs one — the fleet
    #: aggregator (fmda_tpu.obs.aggregate) scrapes exactly these
    metrics: Optional[str] = None


class MembershipView:
    """The router's fold over control-topic worker messages."""

    def __init__(
        self,
        timeout_s: float,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.timeout_s = timeout_s
        self.clock = clock
        self.workers: Dict[str, WorkerInfo] = {}
        #: last known info of departed workers (goodbye or timeout) —
        #: their final stats stay inspectable after the process exits
        self.departed: Dict[str, WorkerInfo] = {}
        #: workers gracefully draining out: still heartbeating (and
        #: still addressable — they serve their drain markers) but
        #: excluded from :meth:`live`, so ownership derivation stops
        #: assigning them sessions
        self.leaving: set = set()

    def observe(self, msg: dict, now: Optional[float] = None) -> Optional[str]:
        """Fold one control message; returns ``"join"``/``"leave"`` when
        the live set changed, else None.  Unknown kinds are ignored (the
        control topic also carries ownership announcements and migrated
        session state)."""
        kind = msg.get("kind")
        wid = msg.get("worker")
        if kind not in (HELLO, HEARTBEAT, GOODBYE) or not wid:
            return None
        now = self.clock() if now is None else now
        if kind == GOODBYE:
            info = self.workers.pop(wid, None)
            was_leaving = wid in self.leaving
            self.leaving.discard(wid)
            if info is None:
                return None
            info.last_seen = now
            if isinstance(msg.get("stats"), dict):
                info.stats = msg["stats"]
            self.departed[wid] = info
            log.info("worker %s left the fleet (goodbye)", wid)
            # a leaving worker was already out of live(); its goodbye
            # changes nothing the router must react to
            return None if was_leaving else "leave"
        info = self.workers.get(wid)
        joined = info is None
        rejoined = False
        if kind == HELLO:
            # an explicit (re)hello cancels a pending leave — and
            # cancelling re-enters live(), which the router must treat
            # exactly like a join (rebalance), or the worker is left in
            # the live set owning no hash range forever
            rejoined = wid in self.leaving
            self.leaving.discard(wid)
        if joined:
            info = self.workers[wid] = WorkerInfo(
                worker_id=wid, last_seen=now, joined_at=now)
            self.departed.pop(wid, None)
            log.info("worker %s joined the fleet (%s)", wid, kind)
        info.last_seen = now
        if "capacity" in msg:
            info.capacity = int(msg["capacity"])
        if isinstance(msg.get("stats"), dict):
            info.stats = msg["stats"]
        if kind == HELLO:
            # a (re)hello defines the incarnation's announce outright: a
            # replacement started WITHOUT a metrics endpoint must clear
            # the dead incarnation's URL, or the aggregator scrapes a
            # dead address forever
            info.metrics = (str(msg["metrics"])
                            if msg.get("metrics") else None)
        elif msg.get("metrics"):
            info.metrics = str(msg["metrics"])
        return "join" if joined or rejoined else None

    def reap(self, now: Optional[float] = None) -> List[str]:
        """Declare-and-remove every worker silent past the timeout;
        returns their ids (the router rebalances when non-empty)."""
        now = self.clock() if now is None else now
        dead = [
            wid for wid, info in self.workers.items()
            if now - info.last_seen > self.timeout_s
        ]
        for wid in dead:
            info = self.workers.pop(wid)
            self.leaving.discard(wid)
            self.departed[wid] = info
            log.warning(
                "worker %s declared dead (last heartbeat %.1fs ago)",
                wid, now - info.last_seen)
        return dead

    def mark_leaving(self, worker_id: str) -> bool:
        """Exclude a worker from live() while it drains out; returns
        whether anything changed."""
        if worker_id not in self.workers or worker_id in self.leaving:
            return False
        self.leaving.add(worker_id)
        return True

    def live(self) -> List[str]:
        return sorted(set(self.workers) - self.leaving)

    def __len__(self) -> int:
        return len(self.live())


class Heartbeater:
    """Worker-side liveness announcer (hello → heartbeats → goodbye)."""

    def __init__(
        self,
        bus,
        worker_id: str,
        *,
        control_topic: str,
        interval_s: float,
        capacity: int = 0,
        clock: Callable[[], float] = time.monotonic,
        announce: Optional[dict] = None,
    ) -> None:
        self.bus = bus
        self.worker_id = worker_id
        self.control_topic = control_topic
        self.interval_s = interval_s
        self.capacity = capacity
        self.clock = clock
        #: extra fields stamped into EVERY liveness message — the
        #: worker's data-plane address rides here, and it must ride the
        #: heartbeats too (a reaped worker re-joins via its next beat,
        #: and the router must be able to re-link it)
        self.announce = dict(announce or {})
        self._last_beat: Optional[float] = None

    def _publish(
        self, kind: str, stats: Optional[dict],
        extra: Optional[dict] = None,
    ) -> None:
        msg = {
            "kind": kind,
            "worker": self.worker_id,
            "capacity": self.capacity,
            # wire-dialect capability (docs/multihost.md "Wire format
            # v2"): in broker-mediated topologies the router cannot see
            # the consumer's age from its own broker link, so every
            # liveness message declares it — absent (pre-v2 senders)
            # means v1, and the router lowers that worker's payloads
            "wire": 2,
            **self.announce,
        }
        if stats is not None:
            msg["stats"] = stats
        if extra:
            msg.update(extra)
        self.bus.publish(self.control_topic, msg)

    def hello(
        self, stats: Optional[dict] = None,
        extra: Optional[dict] = None,
    ) -> None:
        """Announce membership.  ``extra`` fields ride the hello only —
        the worker's open-session report (id → seq + norm) goes here, so
        a router restarted mid-serve rebuilds its registry from the
        re-hello without a second RPC surface (router failover,
        docs/chaos.md)."""
        self._last_beat = self.clock()
        self._publish(HELLO, stats, extra)

    def beat(
        self, stats: Optional[dict] = None, *, force: bool = False
    ) -> bool:
        """Publish a heartbeat when one is due (or ``force``); returns
        whether one was sent.  Call from the worker loop every step —
        the cadence check is one clock read."""
        now = self.clock()
        if (not force and self._last_beat is not None
                and now - self._last_beat < self.interval_s):
            return False
        self._last_beat = now
        self._publish(HEARTBEAT, stats)
        return True

    def goodbye(self, stats: Optional[dict] = None) -> None:
        self._publish(GOODBYE, stats)
