"""fmda_tpu.stream — message bus, streaming engine, warehouse.

Exports resolve lazily (PEP 562): the warehouse/engine pull the jax
feature kernels at import, while the multi-host fleet's router-role code
(fmda_tpu.fleet) needs only the bus contract from this package and must
import on a bus-only host without the accelerator stack.
"""

_EXPORTS = {
    "Record": "fmda_tpu.stream.bus",
    "Consumer": "fmda_tpu.stream.bus",
    "MessageBus": "fmda_tpu.stream.bus",
    "InProcessBus": "fmda_tpu.stream.bus",
    "Warehouse": "fmda_tpu.stream.warehouse",
    "StreamEngine": "fmda_tpu.stream.engine",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(
            f"module 'fmda_tpu.stream' has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(module_name), name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
