from fmda_tpu.stream.bus import Consumer, InProcessBus, MessageBus, Record
from fmda_tpu.stream.warehouse import Warehouse
from fmda_tpu.stream.engine import StreamEngine

__all__ = [
    "Record",
    "Consumer",
    "MessageBus",
    "InProcessBus",
    "Warehouse",
    "StreamEngine",
]
