"""Feature warehouse: embedded columnar store + derived-feature views.

Replaces the reference's MariaDB layer (create_database.py): the joined
feature table is an embedded SQLite database whose DDL is *generated from
the feature config* — the reference's load-bearing config→schema property
(create_database.py:29-70) — and the derived-feature "views" (MAs,
Bollinger, stochastic, ATR, price change, targets; create_database.py:76-190)
are computed by the vectorized kernels in :mod:`fmda_tpu.ops.indicators`
instead of SQL window functions, with results cached until new rows land.

The warehouse implements the :class:`~fmda_tpu.data.source.FeatureSource`
protocol, so the trainer and the serving layer read it directly — the
equivalent of the reference's ``join_statement`` query path
(create_database.py:240-258 → sql_pytorch_dataloader / predict.py).
"""

from __future__ import annotations

import sqlite3
import threading
import time as _time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from fmda_tpu.chaos.inject import default_chaos
from fmda_tpu.config import FeatureConfig, TARGET_COLUMNS, WarehouseConfig
from fmda_tpu.ops.indicators import build_targets, derived_features

#: chaos injection singleton, captured once at import: a fault window on
#: ``warehouse.append`` makes every landing raise — the "warehouse is
#: unreachable" outage the write-ahead journal survives (docs/chaos.md)
_CHAOS = default_chaos()


def _quote(col: str) -> str:
    return f'"{col}"'


class Warehouse:
    """SQLite-backed joined feature table + in-memory derived views."""

    def __init__(
        self,
        features: FeatureConfig,
        config: Optional[WarehouseConfig] = None,
    ) -> None:
        self.features = features
        self.config = config or WarehouseConfig()
        if self.config.backend != "sqlite":
            raise NotImplementedError(
                f"backend {self.config.backend!r}; the embedded backend is "
                "'sqlite' (a MariaDB adapter can wrap the same interface)"
            )
        self.table = self.config.table_name
        self._columns: Tuple[str, ...] = self.features.table_columns()
        self._conn = sqlite3.connect(self.config.path, check_same_thread=False)
        # RLock: guards both the SQL connection and the derived caches;
        # _refresh_derived re-enters through __len__/_fetch_rows_after.
        self._lock = threading.RLock()
        self._create_table()
        # Incrementally-maintained caches: the raw table matrix plus the
        # derived views/targets, extended (not recomputed) as rows land.
        # Derived views follow the reference's ``OVER (ORDER BY Timestamp)``
        # window semantics (create_database.py:78-190), NOT insertion order:
        # caches live in *timestamp-sorted* position space, with
        # ``_sorted_idx`` (sorted position -> row index) / ``_rank`` (row
        # index -> sorted position) translating to/from ID space.  Rows
        # landing in order extend the caches incrementally; a late row
        # triggers a full recompute over the sorted view (rare — the engine
        # emits in commit order — and logged).
        self._cache_rows = 0
        self._matrix = np.empty((0, len(self._columns)), np.float64)
        self._ids = np.empty(0, np.int64)  # row IDs, insertion (ID) order
        self._ts: List[str] = []
        self._sorted_idx = np.empty(0, np.int64)
        self._rank = np.empty(0, np.int64)
        self._derived: Dict[str, np.ndarray] = {
            c: np.empty(0, np.float64) for c in self.features.derived_columns()
        }
        self._targets = np.empty((0, len(TARGET_COLUMNS)), np.float64)
        # fmda_tpu.obs instruments, populated by bind_metrics; None =
        # uninstrumented (direct constructions pay nothing)
        self._obs_write_hist = None
        self._obs_query_hist = None
        self._obs_rows_counter = None

    def bind_metrics(self, registry) -> None:
        """Report write/query latency + rows landed through a
        :class:`~fmda_tpu.obs.registry.MetricsRegistry`."""
        self._obs_write_hist = registry.histogram("warehouse_write_seconds")
        self._obs_query_hist = registry.histogram("warehouse_query_seconds")
        self._obs_rows_counter = registry.counter(
            "warehouse_rows_written_total")

    def healthy(self) -> bool:
        """Probe that the store still accepts work: take (and release) a
        write lock.  False the moment the connection is closed or the
        file went read-only — the ``/healthz`` warehouse check."""
        try:
            with self._lock:
                self._conn.execute("BEGIN IMMEDIATE")
                self._conn.execute("ROLLBACK")
            return True
        except Exception:  # noqa: BLE001 — loss-free: a health probe; any failure IS the "unhealthy" signal
            return False

    # -- DDL (config -> schema codegen) -------------------------------------

    def _create_table(self) -> None:
        cols = ", ".join(f"{_quote(c)} REAL" for c in self._columns)
        ddl = (
            f"CREATE TABLE IF NOT EXISTS {self.table} "
            f"(ID INTEGER PRIMARY KEY AUTOINCREMENT, Timestamp TEXT, {cols})"
        )
        with self._lock:
            self._conn.execute(ddl)
            # timestamp lookups are on the serving and dedupe hot paths
            self._conn.execute(
                f"CREATE INDEX IF NOT EXISTS idx_{self.table}_ts "
                f"ON {self.table}(Timestamp)"
            )
            self._conn.commit()

    # -- writes --------------------------------------------------------------

    def insert_rows(self, rows: Sequence[Dict[str, float]]) -> int:
        """Append joined feature rows; unknown keys rejected, missing keys
        stored as 0 (the engine's fillna(0), spark_consumer.py:480).
        Each row dict must carry 'Timestamp'."""
        if _CHAOS.enabled:
            # raised BEFORE any DB work, like a connection drop at call
            # time: nothing partial commits, the caller's spill/journal
            # path owns the rows
            _CHAOS.check("warehouse.append")
        if not rows:
            return 0
        cols = self._columns
        placeholders = ", ".join(["?"] * (1 + len(cols)))
        col_list = "Timestamp, " + ", ".join(_quote(c) for c in cols)
        known = frozenset(cols) | {"Timestamp"}
        values = []
        for row in rows:
            # issuperset over the dict view: per-key hash probes, no
            # per-row set construction (this is the landing hot path)
            if not known.issuperset(row.keys()):
                unknown = sorted(set(row) - known)
                raise KeyError(f"unknown feature columns: {unknown}")
            get = row.get
            values.append(
                [get("Timestamp")]
                + [float(get(c) or 0.0) for c in cols]
            )
        t0 = _time.perf_counter() if self._obs_write_hist is not None else 0.0
        with self._lock:
            self._conn.executemany(
                f"INSERT INTO {self.table} ({col_list}) VALUES ({placeholders})",
                values,
            )
            self._conn.commit()
        if self._obs_write_hist is not None:
            self._obs_write_hist.observe(_time.perf_counter() - t0)
            self._obs_rows_counter.inc(len(values))
        return len(values)

    # -- raw reads -----------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            (n,) = self._conn.execute(
                f"SELECT COUNT(ID) FROM {self.table}"
            ).fetchone()
        return int(n)

    def timestamps(self) -> List[str]:
        with self._lock:
            rows = self._conn.execute(
                f"SELECT Timestamp FROM {self.table} ORDER BY ID"
            ).fetchall()
        return [r[0] for r in rows]

    def timestamps_after(self, position: int) -> List[Tuple[int, str]]:
        """``(position, timestamp)`` pairs of rows past ``position``, in
        row order — the tail-follow query (serving daemons polling a
        shared file).

        Positions are 1-based dense ordinals in ID order (the space every
        read API of this class speaks — see :meth:`fetch`); they are
        gap-free by construction even when the underlying autoincrement
        IDs have holes, so a cursor advanced to the last returned
        position can never desync into re-serving.  Pure SQL — always
        fresh, independent of the derived caches (tail-followers poll a
        file another process is writing)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT pos, Timestamp FROM (SELECT ROW_NUMBER() OVER "
                f"(ORDER BY ID) AS pos, Timestamp FROM {self.table}) "
                "WHERE pos > ? ORDER BY pos",
                (max(0, int(position)),),
            ).fetchall()
        return [(int(r[0]), r[1]) for r in rows]

    def recent_timestamps(self, limit: int) -> List[str]:
        """Timestamps of the newest ``limit`` rows (newest-first) — the
        engine seeds its landed-tick dedupe set from this without loading
        a long history."""
        with self._lock:
            rows = self._conn.execute(
                f"SELECT Timestamp FROM {self.table} ORDER BY ID DESC "
                "LIMIT ?",
                (int(limit),),
            ).fetchall()
        return [r[0] for r in rows]

    def raw_rows_for(self, ts_list: Sequence[str]) -> Dict[str, Tuple]:
        """Raw landed table values keyed by timestamp (newest row per
        timestamp), straight from SQL — no derived views, no caches.
        This is the bit-identity surface chaos soaks compare: a clean
        row's *landed* bytes must match an unfaulted replay even when a
        degraded neighbor legitimately shifts the windowed views."""
        ts_list = list(ts_list)
        if not ts_list:
            return {}
        cols = ", ".join(_quote(c) for c in self._columns)
        qmarks = ", ".join("?" * len(ts_list))
        with self._lock:
            rows = self._conn.execute(
                f"SELECT Timestamp, {cols} FROM {self.table} "
                f"WHERE Timestamp IN ({qmarks}) ORDER BY ID",
                ts_list,
            ).fetchall()
        return {r[0]: tuple(r[1:]) for r in rows}

    def iter_row_chunks(
        self,
        start_ts: Optional[str] = None,
        end_ts: Optional[str] = None,
        chunk: int = 4096,
        *,
        follow: int = 0,
        poll_wait: Optional[Any] = None,
    ) -> Iterator[Tuple[List[str], np.ndarray]]:
        """Bulk history reader: the landed table in ID order as
        ``(timestamps, (B, F) float64 matrix)`` chunks — ONE keyset-
        paginated range query per chunk, never a per-timestamp lookup.
        The replay driver streams backfills through this, and the
        trainer's chunked loading can ride the same reader.

        Values are the raw landed columns (the same bit-identity
        surface as :meth:`raw_rows_for`): both warehouse backends must
        hand back identical bits for the same landed rows — tests
        assert embedded-vs-MySQL chunk parity bit-for-bit.  ``start_ts``
        / ``end_ts`` bound the scan by the lexicographic timestamp
        column (inclusive both ends); the lock is held per chunk, not
        across the whole scan, so ingest keeps landing while a backfill
        reads.  Rows landing behind the cursor mid-scan are picked up;
        this is a reader, not a snapshot.

        ``follow > 0`` turns the scan into a *bounded tail-follow* (the
        continuous trainer's change-data-capture feed): a short page no
        longer ends the scan; on an empty page the reader waits
        (``poll_wait()`` — injectable, so tests never wall-sleep; the
        default sleeps 50 ms) and re-issues the same keyset query, and
        only ``follow`` *consecutive* empty polls conclude the writer
        has quiesced.  The cursor survives the waits — rows landed
        between polls resume exactly after the last yielded ID, never
        re-reading or skipping a row.  ``follow=0`` is the seed
        behavior, bit-for-bit."""
        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        cols = ", ".join(_quote(c) for c in self._columns)
        conds = ["ID > ?"]
        bounds: List[Any] = []
        if start_ts is not None:
            conds.append("Timestamp >= ?")
            bounds.append(start_ts)
        if end_ts is not None:
            conds.append("Timestamp <= ?")
            bounds.append(end_ts)
        where = " AND ".join(conds)
        last_id = 0
        idle = 0
        while True:
            with self._lock:
                rows = self._conn.execute(
                    f"SELECT ID, Timestamp, {cols} FROM {self.table} "
                    f"WHERE {where} ORDER BY ID LIMIT ?",
                    (last_id, *bounds, int(chunk)),
                ).fetchall()
            if not rows:
                if follow <= 0 or idle >= int(follow):
                    return
                idle += 1
                if poll_wait is not None:
                    poll_wait()
                else:
                    _time.sleep(0.05)
                continue
            idle = 0
            last_id = int(rows[-1][0])
            matrix = np.asarray(
                [r[2:] for r in rows], np.float64
            ).reshape(len(rows), len(self._columns))
            yield [r[1] or "" for r in rows], matrix
            if len(rows) < chunk and follow <= 0:
                return

    def joined_row_transform(self):
        """Fresh stateful mapper from :meth:`iter_row_chunks`' raw landed
        chunks to the joined ``x_fields`` rows :meth:`fetch` serves —
        pass (the bound method, as a factory) wherever a replay over
        this warehouse must feed a model sized to the joined view (e.g.
        ``ShadowEvaluator(row_transform=wh.joined_row_transform)``)."""
        from fmda_tpu.ops.indicators import landed_row_transform

        return landed_row_transform(self._columns, self.features)

    def has_timestamp(self, ts: str) -> bool:
        """Point-indexed existence check — the engine's dedupe fallback
        wants only membership, not the position (the positional COUNT in
        :meth:`id_for_timestamp` walks an index range, too heavy to run
        once per replayed row)."""
        with self._lock:
            row = self._conn.execute(
                f"SELECT 1 FROM {self.table} WHERE Timestamp = ? LIMIT 1",
                (ts,),
            ).fetchone()
        return row is not None

    def id_for_timestamp(self, ts: str) -> Optional[int]:
        """Row *position* of a timestamp (predict.py:144 lookup path) —
        1-based dense ordinal in ID order, the same space :meth:`fetch`
        indexes, so ``fetch(range(pos - window + 1, pos + 1))`` is always
        the trailing window even if autoincrement IDs have holes."""
        with self._lock:
            row = self._conn.execute(
                f"SELECT ID FROM {self.table} WHERE Timestamp = ? "
                "ORDER BY ID DESC LIMIT 1",
                (ts,),
            ).fetchone()
            if row is None:
                return None
            # rank of the ID = its 1-based position; one indexed query,
            # no cache refresh (this is the dedupe/serving hot path)
            (pos,) = self._conn.execute(
                f"SELECT COUNT(*) FROM {self.table} WHERE ID <= ?",
                (int(row[0]),),
            ).fetchone()
            return int(pos)

    def ids_for_timestamps(
        self, ts_list: Sequence[str]
    ) -> List[Optional[int]]:
        """Batched :meth:`id_for_timestamp`: positions for a whole flush
        of signal timestamps in ONE indexed query plus one sorted-array
        lookup against the row-ID cache — the fleet predictor gateway's
        per-flush replacement for B per-signal lookup queries.  Unknown
        timestamps map to None (the caller skips them, visibly)."""
        ts_list = list(ts_list)
        if not ts_list:
            return []
        qmarks = ", ".join("?" * len(ts_list))
        with self._lock:
            # the cache refresh guarantees _ids covers every committed
            # row the query can return (signals fire after commit)
            self._refresh_derived()
            rows = self._conn.execute(
                f"SELECT Timestamp, MAX(ID) FROM {self.table} "
                f"WHERE Timestamp IN ({qmarks}) GROUP BY Timestamp",
                ts_list,
            ).fetchall()
            by_ts = {r[0]: int(r[1]) for r in rows}
            # _ids is strictly increasing (insertion order), so the rank
            # of an ID — its 1-based position, the space fetch() speaks —
            # is one searchsorted away
            return [
                int(np.searchsorted(self._ids, by_ts[ts])) + 1
                if ts in by_ts else None
                for ts in ts_list
            ]

    def fetch_windows(
        self, row_ids: Sequence[int], window: int
    ) -> np.ndarray:
        """Batched trailing-window gather: ``(B, window, F)`` feature
        windows ending at each 1-based ``row_ids`` position, from one
        cache refresh and one vectorized gather — the batched-serving
        replacement for B per-signal ``fetch(range(...))`` calls.  Bit-
        identical to stacking :meth:`fetch` windows (same gather, same
        NaN policy; tests assert it).  Raises IndexError when any window
        would reach before row 1 or past the newest row."""
        t0 = _time.perf_counter() if self._obs_query_hist is not None else 0.0
        try:
            return self._fetch_windows(row_ids, window)
        finally:
            if self._obs_query_hist is not None:
                self._obs_query_hist.observe(_time.perf_counter() - t0)

    def _fetch_windows(
        self, row_ids: Sequence[int], window: int
    ) -> np.ndarray:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        pos = np.asarray(list(row_ids), np.int64)
        if pos.size == 0:
            return np.zeros((0, window, len(self.x_fields)), np.float32)
        # (B, window) 1-based positions of each trailing window, through
        # the ONE existing gather (:meth:`_fetch`) — bit-identity with
        # stacked per-signal fetches holds by construction, and the NaN
        # policy / derived-column layout live in exactly one place
        flat = (pos[:, None]
                - np.arange(window - 1, -1, -1)[None, :]).reshape(-1)
        return self._fetch(flat).reshape(len(pos), window, -1)

    def _fetch_rows_after(
        self, row_id: int
    ) -> Tuple[np.ndarray, np.ndarray, List[str]]:
        cols = ", ".join(_quote(c) for c in self._columns)
        with self._lock:
            rows = self._conn.execute(
                f"SELECT ID, Timestamp, {cols} FROM {self.table} "
                "WHERE ID > ? ORDER BY ID",
                (row_id,),
            ).fetchall()
        ids = np.asarray([r[0] for r in rows], np.int64)
        matrix = np.asarray(
            [r[2:] for r in rows], np.float64
        ).reshape(len(rows), len(self._columns))
        return ids, matrix, [r[1] or "" for r in rows]

    # -- derived views -------------------------------------------------------

    def _refresh_derived(self) -> None:
        """Extend the derived-view caches to cover newly landed rows.

        Views are computed over *timestamp order* — the reference's
        ``OVER (ORDER BY Timestamp)`` (create_database.py:78-190) — so a row
        landing late (older timestamp than the newest cached row, e.g. a
        pending engine join that matched after a newer row committed) cannot
        permanently poison the rolling windows.

        In-order arrivals take the incremental path: only the tail is
        recomputed.  Trailing-window views for a row need at most
        ``max_lookback-1`` context rows before it; target labels of the last
        ``max_lead`` cached rows can still change as LEAD rows arrive, so the
        recompute region starts there.  Results are bit-identical to a full
        recompute (verified in tests) at O(new+const) per refresh instead of
        O(total).  Out-of-order arrivals trigger a full recompute over the
        sorted view (logged; rare — the engine emits in commit order).

        Caller must hold ``self._lock`` (writers mutate the shared caches;
        concurrent readers would otherwise observe torn state).
        """
        n = len(self)
        old_n = self._cache_rows
        if n == old_n:
            return
        if n < old_n:  # table replaced/truncated externally: full rebuild
            old_n = 0
            self._matrix = self._matrix[:0]
            self._ids = self._ids[:0]
            self._ts = []
            self._sorted_idx = self._sorted_idx[:0]
            self._rank = self._rank[:0]
        # anchor on the max cached ID, not the cached row count: IDs can
        # have gaps (a rolled-back insert burns autoincrement rowids)
        last_id = int(self._ids[-1]) if len(self._ids) else 0
        new_ids, new_rows, new_ts = self._fetch_rows_after(last_id)
        self._matrix = np.concatenate([self._matrix, new_rows])
        self._ids = np.concatenate([self._ids, new_ids])
        self._ts.extend(new_ts)

        in_order = old_n == 0 or (
            len(self._sorted_idx)
            and min(new_ts) >= self._ts[self._sorted_idx[-1]]
        )
        # order among the new rows themselves: by (Timestamp, ID)
        new_order = old_n + np.lexsort(
            (np.arange(len(new_ts)), np.asarray(new_ts))
        )
        if in_order:
            recompute_start = max(0, old_n - self.features.max_lead)
            self._sorted_idx = np.concatenate([self._sorted_idx, new_order])
            # incremental rank extension: new sorted positions are
            # old_n..n-1, scattered to the new rows' insertion order
            new_rank = np.empty(len(new_ts), np.int64)
            new_rank[new_order - old_n] = np.arange(old_n, n)
            self._rank = np.concatenate([self._rank, new_rank])
        else:
            import logging

            logging.getLogger("fmda_tpu.stream").warning(
                "out-of-timestamp-order row landed (new min ts %s < cached "
                "max ts %s): full derived-view recompute over sorted order",
                min(new_ts), self._ts[self._sorted_idx[-1]],
            )
            recompute_start = 0
            self._sorted_idx = np.lexsort(
                (np.arange(n), np.asarray(self._ts))
            )
            self._rank = np.empty(n, np.int64)
            self._rank[self._sorted_idx] = np.arange(n)

        fc = self.features
        context_start = max(0, recompute_start - (fc.max_lookback - 1))
        rows = self._sorted_idx[context_start:n]
        table = {c: self._matrix[rows, i] for i, c in enumerate(self._columns)}
        derived = derived_features(table, fc)
        offset = recompute_start - context_start
        for c in self.features.derived_columns():
            self._derived[c] = np.concatenate(
                [self._derived[c][:recompute_start], derived[c][offset:]]
            )
        if self._has_ohlc():
            targets = build_targets(table, fc)
            self._targets = np.concatenate(
                [self._targets[:recompute_start], targets[offset:]]
            )
        self._cache_rows = n

    def _has_ohlc(self) -> bool:
        return {"2_high", "3_low", "4_close"} <= set(self._columns)

    # -- FeatureSource protocol ----------------------------------------------

    @property
    def x_fields(self) -> Tuple[str, ...]:
        """Joined column set — table columns then derived views, the
        reference join_statement order (create_database.py:240-241)."""
        return self._columns + self.features.derived_columns()

    def _positions(self, ids: Sequence[int]) -> np.ndarray:
        """Validate 1-based row positions -> 0-based cache indices.

        The read API speaks *positions* (dense ordinals in ID order), not
        raw autoincrement IDs: positions are what the chunk/window math
        all over the framework derives from ``len(source)``, and they
        stay dense even when a rolled-back insert burns a rowid (the
        cache maps position -> actual ID internally, ``_ids``).  Matches
        the reference, whose dataloader also indexes ``1..COUNT(ID)``
        (sql_pytorch_dataloader.py:65-78).  Caller must hold the lock
        with refreshed caches."""
        idx = np.asarray(list(ids), np.int64) - 1
        n = self._cache_rows
        if idx.size and (idx.min() < 0 or idx.max() >= n):
            raise IndexError(f"row positions out of range 1..{n}")
        return idx

    def fetch(self, ids: Sequence[int]) -> np.ndarray:
        """Feature rows (1-based positions) with NaN->0 (IFNULL parity,
        sql_pytorch_dataloader.py:219)."""
        t0 = _time.perf_counter() if self._obs_query_hist is not None else 0.0
        try:
            return self._fetch(ids)
        finally:
            if self._obs_query_hist is not None:
                self._obs_query_hist.observe(_time.perf_counter() - t0)

    def _fetch(self, ids: Sequence[int]) -> np.ndarray:
        with self._lock:
            self._refresh_derived()
            idx = self._positions(ids)
            derived_cols = self.features.derived_columns()
            out = np.empty((len(idx), len(self.x_fields)), np.float64)
            out[:, : len(self._columns)] = self._matrix[idx]
            pos = self._rank[idx]  # derived caches live in sorted-ts space
            for j, c in enumerate(derived_cols):
                out[:, len(self._columns) + j] = self._derived[c][pos]
        return np.nan_to_num(out, nan=0.0).astype(np.float32)

    def fetch_targets(self, ids: Sequence[int]) -> np.ndarray:
        if not self._has_ohlc():
            raise ValueError(
                "movement targets need the OHLCV feed: enable "
                "FeatureConfig.get_stock_volume (the target view derives "
                "from 4_close/ATR, create_database.py:179-190)"
            )
        with self._lock:
            self._refresh_derived()
            idx = self._positions(ids)
            return np.asarray(self._targets[self._rank[idx]], np.float32)

    def close(self) -> None:
        self._conn.close()
