"""Message bus: the framework-owned transport replacing Kafka.

The reference moves every feed through external Kafka brokers (7 topics,
config.py:15; producers at producer.py:103 and in each scraper pipeline;
consumers in spark_consumer.py and predict.py).  Here the data plane is a
framework-owned bus with Kafka-compatible *semantics* — append-only topics,
monotonically increasing offsets, independent consumer positions, seek — but
no external processes:

- :class:`InProcessBus` — thread-safe Python ring buffers (default);
- the native C++ ring-buffer backend (``fmda_tpu.stream.native_bus``)
  exposes the same interface for cross-process use;
- an optional adapter to real Kafka brokers can wrap ``kafka-python`` when
  that package is installed (gated import, parity deployments only).

Values are wire-serialisable dicts (:mod:`fmda_tpu.stream.codec`): the
JSON data model plus raw ndarrays/bytes, so the hot path carries packed
binary columns instead of the reference's ``json.dumps(...)`` text
(arrays on the bus are treated immutable — decoded wire arrays are
read-only views already).

Trace context (:mod:`fmda_tpu.obs.trace`) rides **in-band**: a compact
``trace`` field stamped into the value dict on publish when a trace is
active, carried through every backend's value round-trip, read back by
consumers via ``record.value.get("trace")``.  With tracing disabled the
publish hot path pays exactly one branch.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Protocol, Sequence

from fmda_tpu.obs.trace import default_tracer, stamp_message, stamp_messages
from fmda_tpu.stream import codec

#: Captured once — configure_tracing mutates this singleton in place.
_TRACER = default_tracer()


@dataclass(frozen=True)
class Record:
    """One message on a topic."""

    topic: str
    offset: int
    value: dict


class Consumer:
    """A positioned reader of one topic (Kafka-consumer analog)."""

    def __init__(self, bus: "MessageBus", topic: str, offset: int = 0) -> None:
        self._bus = bus
        self.topic = topic
        self.offset = offset

    def poll(self, max_records: Optional[int] = None) -> List[Record]:
        records = self._bus.read(self.topic, self.offset, max_records)
        if records:
            self.offset = records[-1].offset + 1
            # consume accounting on bound backends (fmda_tpu.obs); the
            # getattr only runs when something was actually read
            consumed = getattr(self._bus, "_consumed_cb", None)
            if consumed is not None:
                consumed(self.topic, len(records))
        return records

    def seek(self, offset: int) -> None:
        self.offset = offset

    def seek_to_end(self) -> None:
        """Skip everything already published (predict.py:30 parity)."""
        self.offset = self._bus.end_offset(self.topic)


class MessageBus(Protocol):
    """Topic transport contract shared by all backends."""

    def publish(self, topic: str, value: dict) -> int:
        """Append a message; returns its offset."""
        ...

    def publish_many(self, topic: str, values: Sequence[dict]) -> List[int]:
        """Append a batch of messages in order; returns their offsets.

        Semantically ``[publish(topic, v) for v in values]`` with the
        per-call overhead (lock churn, native-call setup) paid once — the
        fleet gateway publishes a whole flush through this.
        """
        ...

    def read(
        self, topic: str, offset: int, max_records: Optional[int] = None
    ) -> List[Record]:
        """Read records with offsets >= ``offset`` (bounded by retention)."""
        ...

    def end_offset(self, topic: str) -> int:
        """Offset one past the last published record."""
        ...

    def topics(self) -> Sequence[str]:
        ...

    def consumer(self, topic: str, *, from_end: bool = False) -> Consumer:
        ...


class InProcessBus:
    """Thread-safe in-process bus with per-topic ring retention."""

    def __init__(
        self, topics: Iterable[str], capacity: int = 1 << 16
    ) -> None:
        self._capacity = capacity
        self._lock = threading.Lock()
        self._logs: Dict[str, List[Record]] = {t: [] for t in topics}
        self._base: Dict[str, int] = {t: 0 for t in self._logs}
        self._next: Dict[str, int] = {t: 0 for t in self._logs}
        #: per-topic publish counters + consume callback, populated by
        #: :meth:`bind_metrics` (fmda_tpu.obs); None = uninstrumented
        self._publish_counters = None
        self._consumed_cb = None
        self._metrics_registry = None

    def bind_metrics(self, registry) -> None:
        """Report publish/consume totals per topic through a
        :class:`~fmda_tpu.obs.registry.MetricsRegistry`.  Counters are
        created once here, so the publish hot path pays one dict lookup
        and one lock-guarded increment; topics added later
        (:meth:`add_topic`) get their counters on first touch."""
        self._metrics_registry = registry
        with self._lock:  # add_topic can race a live-fleet bind
            topics = tuple(self._logs)
        self._publish_counters = {
            t: registry.counter("bus_published_total", topic=t)
            for t in topics
        }
        consume_counters = {
            t: registry.counter("bus_consumed_total", topic=t)
            for t in topics
        }

        def consumed(topic: str, n: int) -> None:
            counter = consume_counters.get(topic)
            if counter is None:
                counter = consume_counters[topic] = registry.counter(
                    "bus_consumed_total", topic=topic)
            counter.inc(n)

        self._consumed_cb = consumed

    def _check_topic_locked(self, topic: str) -> None:
        """Caller must hold ``self._lock`` (reads the topic map)."""
        if topic not in self._logs:
            raise KeyError(
                f"unknown topic {topic!r}; configured: {sorted(self._logs)}"
            )

    def add_topic(self, topic: str) -> None:
        """Create a topic after construction (idempotent) — dynamic
        membership needs this: a fleet worker joining beyond the
        launch-time set brings its own inbox topic (ROADMAP (c)).  The
        shared contract (all backends + the wire transport): an existing
        topic keeps its log and offsets untouched."""
        with self._lock:
            if topic in self._logs:
                return
            self._logs[topic] = []
            self._base[topic] = 0
            self._next[topic] = 0
        if self._publish_counters is not None:
            registry = self._metrics_registry
            self._publish_counters[topic] = registry.counter(
                "bus_published_total", topic=topic)

    def publish(self, topic: str, value: dict) -> int:
        if _TRACER.enabled:  # in-band trace context + a bus-stage span
            value = stamp_message(value)
            with _TRACER.span("bus_publish", "bus"):
                return self._publish(topic, value)
        return self._publish(topic, value)

    def _publish(self, topic: str, value: dict) -> int:
        # structural copy to enforce wire-serialisability (and decouple
        # the stored value from caller-side mutation), like a real
        # broker — without the old JSON text round trip, and with raw
        # arrays passing through uncopied (the binary-data-plane value
        # model; arrays on the bus are treated immutable)
        value = codec.wire_copy(value)
        with self._lock:
            self._check_topic_locked(topic)
            offset = self._next[topic]
            self._next[topic] = offset + 1
            log = self._logs[topic]
            log.append(Record(topic, offset, value))
            if len(log) > self._capacity:  # retention: drop oldest
                drop = len(log) - self._capacity
                del log[:drop]
                self._base[topic] += drop
        self._count_published(topic, 1)
        return offset

    def _count_published(self, topic: str, n: int) -> None:
        """Publish-counter bump with create-on-first-touch: a topic
        added concurrently with ``bind_metrics`` can miss the snapshot
        on either side, and the hot path must count it, never KeyError
        (the never-abort contract reaches down to here)."""
        counters = self._publish_counters
        if counters is None:
            return
        counter = counters.get(topic)
        if counter is None:
            counter = counters[topic] = self._metrics_registry.counter(
                "bus_published_total", topic=topic)
        counter.inc(n)

    def publish_many(self, topic: str, values) -> List[int]:
        """Batched :meth:`publish`: one JSON round-trip and one lock
        acquisition for the whole batch (the fleet gateway's per-flush
        publish path).  Per-message ``trace`` fields (the gateway stamps
        each tick's own context) pass through untouched; messages
        without one inherit the active context."""
        if _TRACER.enabled:
            values = stamp_messages(values)
        values = [codec.wire_copy(v) for v in values]
        if not values:
            return []
        offsets: List[int] = []
        with self._lock:
            self._check_topic_locked(topic)
            log = self._logs[topic]
            offset = self._next[topic]
            for value in values:
                log.append(Record(topic, offset, value))
                offsets.append(offset)
                offset += 1
            self._next[topic] = offset
            if len(log) > self._capacity:  # retention: drop oldest
                drop = len(log) - self._capacity
                del log[:drop]
                self._base[topic] += drop
        self._count_published(topic, len(offsets))
        return offsets

    def read(
        self, topic: str, offset: int, max_records: Optional[int] = None
    ) -> List[Record]:
        with self._lock:
            self._check_topic_locked(topic)
            base = self._base[topic]
            start = max(offset - base, 0)
            log = self._logs[topic]
            stop = len(log) if max_records is None else start + max_records
            return log[start:stop]

    def end_offset(self, topic: str) -> int:
        with self._lock:
            self._check_topic_locked(topic)
            return self._next[topic]

    def topics(self) -> Sequence[str]:
        with self._lock:  # concurrent add_topic must not tear the walk
            return tuple(self._logs)

    def consumer(self, topic: str, *, from_end: bool = False) -> Consumer:
        c = Consumer(self, topic)
        if from_end:
            c.seek_to_end()
        return c
