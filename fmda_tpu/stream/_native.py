"""Shared on-demand build/load bootstrap for the native C++ components.

Both ctypes bindings (ring bus, join scheduler) build the same `native/`
tree with make and load a shared library from `native/build/`; keeping the
bootstrap in one place means timeout/error-shaping fixes can't drift
between them.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Dict, Type

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)

_loaded: Dict[str, ctypes.CDLL] = {}


def build_and_load(lib_name: str, exc_cls: Type[Exception]) -> ctypes.CDLL:
    """Build (if needed) and load ``native/build/<lib_name>``; cached.

    Raises ``exc_cls`` with the compiler's stderr tail when the toolchain
    is missing or the build fails.
    """
    if lib_name in _loaded:
        return _loaded[lib_name]
    lib_path = os.path.join(_NATIVE_DIR, "build", lib_name)
    if not os.path.exists(lib_path):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True, capture_output=True, timeout=120,
            )
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                OSError) as e:
            detail = ""
            if isinstance(e, subprocess.CalledProcessError):
                detail = f": {e.stderr.decode(errors='replace')[-500:]}"
            raise exc_cls(f"cannot build {lib_name} ({e}){detail}") from e
        if not os.path.exists(lib_path):
            raise exc_cls(f"build succeeded but {lib_name} missing")
    try:
        lib = ctypes.CDLL(lib_path)
    except OSError as e:  # stale/foreign .so
        raise exc_cls(f"cannot load {lib_path}: {e}") from e
    _loaded[lib_name] = lib
    return lib
