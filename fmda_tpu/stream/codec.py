"""Versioned binary wire codec: columnar tick framing for the data plane.

Every hot-path boundary in the serving tier used to be JSON text: wire
frames, bus message values, the migration state codec (base64-in-JSON),
and the warehouse journal.  At fleet tick rates the serialize/parse pass
is the tax on every tick — a 108-float row became ~2.5KB of decimal
text, re-parsed float by float on the far side.  This module is the
binary answer, shared by the whole data plane:

- a **fixed frame header** — magic ``0xFB``, version, op, flags — in
  front of a tagged little-endian value encoding (``None``/bool/i64/
  f64/str/bytes/list/dict/ndarray).  The magic byte can never begin a
  JSON text (or any UTF-8 sequence), so binary and JSON frames coexist
  on one connection and every receiver auto-detects per frame;
- **zero-copy arrays**: an ndarray crosses as dtype/shape/raw IEEE
  bytes and decodes as a read-only ``np.frombuffer`` view into the
  received frame — no base64, no float→decimal→float round trip, no
  per-element boxing.  Treat decoded arrays as immutable (they are:
  the views are read-only); copy before mutating;
- **columnar tick blocks** (:func:`pack_ticks` / :func:`iter_ticks`):
  a run of routed ticks coalesces into one message whose rows are a
  single contiguous ``(B, F)`` float32 block and whose seqs are one
  int64 column — a gateway flush's batch decodes straight into the
  arrays the jitted step's staging buffers copy from;
- a **JSON fallback** (:func:`dumps` / :func:`loads`) carrying the
  same value model as tagged base64 (``{"__nd__": ...}``), negotiated
  per connection (docs/multihost.md "Wire format v2") — the debug and
  rollback format, and the only place base64 survives.

numpy only, no jax: this runs in the router process (bus-only host).
"""

from __future__ import annotations

import base64
import json
import struct
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

#: First payload byte of every binary frame.  0xFB is not a legal first
#: byte of any UTF-8 sequence, so a binary frame can never be mistaken
#: for JSON text (and vice versa: JSON starts '{', '[', '"', a digit…).
MAGIC = 0xFB

#: Bumped on any incompatible layout change; decoders reject unknown
#: versions loudly instead of mis-parsing.
CODEC_VERSION = 1

#: Frame ops (header byte 3).  One op today — the generic value frame —
#: with the byte reserved so future layouts don't need a version bump.
OP_VALUE = 0

_HEADER = struct.Struct("<BBBB")  # magic, version, op, flags
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")

# value tags
_T_NONE = 0x00
_T_FALSE = 0x01
_T_TRUE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_STR = 0x05
_T_BYTES = 0x06
_T_LIST = 0x07
_T_DICT = 0x08
_T_ARRAY = 0x09


class CodecError(ValueError):
    """A buffer that is not a well-formed frame (truncated, bad magic or
    version, unknown tag, trailing garbage) or a value outside the wire
    data model.  Decode errors are *content* errors: the transport
    framing around the payload is intact, so connections survive them
    (counted ``frames_malformed_total`` — fmda_tpu.fleet.wire)."""


# ---------------------------------------------------------------------------
# binary encode
# ---------------------------------------------------------------------------


def _encode_value(out: bytearray, value: Any) -> None:
    if value is None:
        out.append(_T_NONE)
    elif value is True:
        out.append(_T_TRUE)
    elif value is False:
        out.append(_T_FALSE)
    elif isinstance(value, int):
        out.append(_T_INT)
        try:
            out += _I64.pack(value)
        except struct.error as e:
            raise CodecError(f"int {value} exceeds i64 range") from e
    elif isinstance(value, float):
        out.append(_T_FLOAT)
        out += _F64.pack(value)
    elif isinstance(value, str):
        raw = value.encode("utf-8")
        out.append(_T_STR)
        out += _U32.pack(len(raw))
        out += raw
    elif isinstance(value, (bytes, bytearray, memoryview)):
        raw = bytes(value)
        out.append(_T_BYTES)
        out += _U32.pack(len(raw))
        out += raw
    elif isinstance(value, np.ndarray):
        _encode_array(out, value)
    elif isinstance(value, (list, tuple)):
        out.append(_T_LIST)
        out += _U32.pack(len(value))
        for item in value:
            _encode_value(out, item)
    elif isinstance(value, dict):
        out.append(_T_DICT)
        out += _U32.pack(len(value))
        for k, v in value.items():
            if not isinstance(k, str):
                k = _coerce_key(k)
            raw = k.encode("utf-8")
            out += _U32.pack(len(raw))
            out += raw
            _encode_value(out, v)
    elif isinstance(value, (np.integer, np.floating, np.bool_)):
        _encode_value(out, value.item())
    else:
        raise CodecError(
            f"value of type {type(value).__name__} is not wire-encodable")


def _encode_array(out: bytearray, a: np.ndarray) -> None:
    if a.dtype.hasobject:
        raise CodecError("object-dtype arrays are not wire-encodable")
    a = np.ascontiguousarray(a)
    dt = a.dtype.str.encode("ascii")  # e.g. b"<f4" — byte order explicit
    out.append(_T_ARRAY)
    out.append(len(dt))
    out += dt
    out.append(a.ndim)
    for dim in a.shape:
        out += _I64.pack(dim)
    raw = a.tobytes()  # one memcpy; the only copy on the encode side
    out += _U32.pack(len(raw))
    out += raw


def _coerce_key(k: Any) -> str:
    """Match ``json.dumps`` key coercion so the binary format accepts
    exactly the dicts the JSON fallback accepts."""
    if k is True:
        return "true"
    if k is False:
        return "false"
    if k is None:
        return "null"
    if isinstance(k, (int, float)):
        return repr(k)
    raise CodecError(f"dict key of type {type(k).__name__} is not "
                     "wire-encodable")


def encode(value: Any, *, op: int = OP_VALUE) -> bytes:
    """``value`` as one self-contained binary frame (header + body)."""
    out = bytearray(_HEADER.pack(MAGIC, CODEC_VERSION, op, 0))
    _encode_value(out, value)
    return bytes(out)


# ---------------------------------------------------------------------------
# binary decode
# ---------------------------------------------------------------------------
#
# The decoder is written flat — (buf, pos) in, (value, pos) out, struct
# ``unpack_from`` against the buffer, no reader object — because its
# per-value overhead IS the hot path: a 256-tick block decodes a few
# hundred values, and method-call dispatch per value was the difference
# between beating the C json module 2x and 4x (wire_codec_bench).

_u32_from = _U32.unpack_from
_i64_from = _I64.unpack_from
_f64_from = _F64.unpack_from


def _decode_value(buf: bytes, pos: int, end: int) -> Tuple[Any, int]:
    if pos >= end:
        raise CodecError("truncated frame: missing value tag")
    tag = buf[pos]
    pos += 1
    if tag == _T_STR:
        (n,) = _u32_from(buf, pos)
        pos += 4
        stop = pos + n
        if stop > end:
            raise CodecError("truncated frame: short string")
        try:
            return buf[pos:stop].decode("utf-8"), stop
        except UnicodeDecodeError as e:
            raise CodecError(f"malformed utf-8 in string: {e}") from e
    if tag == _T_INT:
        (v,) = _i64_from(buf, pos)
        return v, pos + 8
    if tag == _T_FLOAT:
        (v,) = _f64_from(buf, pos)
        return v, pos + 8
    if tag == _T_DICT:
        (n,) = _u32_from(buf, pos)
        pos += 4
        out: Dict[str, Any] = {}
        for _ in range(n):
            (kn,) = _u32_from(buf, pos)
            pos += 4
            kstop = pos + kn
            if kstop > end:
                raise CodecError("truncated frame: short dict key")
            key = buf[pos:kstop].decode("utf-8")
            out[key], pos = _decode_value(buf, kstop, end)
        return out, pos
    if tag == _T_LIST:
        (n,) = _u32_from(buf, pos)
        pos += 4
        items = []
        append = items.append
        for _ in range(n):
            v, pos = _decode_value(buf, pos, end)
            append(v)
        return items, pos
    if tag == _T_ARRAY:
        return _decode_array(buf, pos, end)
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_BYTES:
        (n,) = _u32_from(buf, pos)
        pos += 4
        stop = pos + n
        if stop > end:
            raise CodecError("truncated frame: short bytes")
        return buf[pos:stop], stop
    raise CodecError(f"unknown value tag 0x{tag:02x}")


def _decode_array(buf: bytes, pos: int, end: int) -> Tuple[np.ndarray, int]:
    dn = buf[pos]
    pos += 1
    try:
        dtype = np.dtype(buf[pos:pos + dn].decode("ascii"))
    except (TypeError, ValueError, UnicodeDecodeError) as e:
        raise CodecError(f"bad array dtype: {e}") from e
    pos += dn
    ndim = buf[pos]
    pos += 1
    shape = []
    for _ in range(ndim):
        (d,) = _i64_from(buf, pos)
        pos += 8
        if d < 0:
            raise CodecError(f"negative array dimension {d}")
        shape.append(d)
    (nbytes,) = _u32_from(buf, pos)
    pos += 4
    stop = pos + nbytes
    if stop > end:
        raise CodecError("truncated frame: short array payload")
    count = 1
    for d in shape:
        count *= d
    if count * dtype.itemsize != nbytes:
        raise CodecError(
            f"array payload {nbytes}B does not match shape "
            f"{tuple(shape)} of {dtype}")
    # zero-copy: a read-only view into the received frame buffer —
    # callers that need to mutate copy; everything else reads in place
    a = np.frombuffer(buf, dtype=dtype, count=count, offset=pos)
    return a.reshape(shape), stop


def decode(buf: bytes) -> Any:
    """Inverse of :func:`encode`; raises :class:`CodecError` on any
    malformed input (truncation, trailing bytes, bad magic/version)."""
    if not isinstance(buf, bytes):
        buf = bytes(buf)
    if len(buf) < _HEADER.size:
        raise CodecError(f"frame of {len(buf)}B is shorter than a header")
    magic, version, op, _flags = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise CodecError(f"bad magic 0x{magic:02x} (not a binary frame)")
    if version != CODEC_VERSION:
        raise CodecError(
            f"frame version {version} unknown (this codec speaks "
            f"{CODEC_VERSION})")
    if op != OP_VALUE:
        raise CodecError(f"unknown frame op {op}")
    end = len(buf)
    try:
        value, pos = _decode_value(buf, _HEADER.size, end)
    except (struct.error, IndexError) as e:  # read past the end
        raise CodecError(f"truncated frame: {e}") from e
    except UnicodeDecodeError as e:  # malformed utf-8 in a dict key or
        # dtype string (string VALUES convert in place; this is the
        # backstop) — a content error, never a connection-killer
        raise CodecError(f"malformed utf-8 in frame: {e}") from e
    if pos != end:
        raise CodecError(
            f"{end - pos} trailing byte(s) after the value")
    return value


def is_binary(payload: bytes) -> bool:
    """Does this payload start a binary frame (vs JSON text)?"""
    return bool(payload) and payload[0] == MAGIC


# ---------------------------------------------------------------------------
# the JSON fallback (negotiated debug/control format)
# ---------------------------------------------------------------------------


def to_jsonable(value: Any) -> Any:
    """The wire value model lowered to plain JSON types: arrays become
    ``{"__nd__": [dtype, shape, base64]}``, bytes ``{"__b64__": ...}``.
    base64 survives ONLY here — the binary format carries raw bytes."""
    if isinstance(value, np.ndarray):
        a = np.ascontiguousarray(value)
        if a.dtype.hasobject:
            raise CodecError("object-dtype arrays are not wire-encodable")
        return {"__nd__": [
            a.dtype.str, list(a.shape),
            base64.b64encode(a.tobytes()).decode("ascii")]}
    if isinstance(value, (bytes, bytearray, memoryview)):
        return {"__b64__": base64.b64encode(bytes(value)).decode("ascii")}
    if isinstance(value, dict):
        return {k: to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [to_jsonable(v) for v in value]
    if isinstance(value, (np.integer, np.floating, np.bool_)):
        return value.item()
    return value


def from_jsonable(value: Any) -> Any:
    if isinstance(value, dict):
        if len(value) == 1:
            if "__nd__" in value:
                tagged = value["__nd__"]
                if isinstance(tagged, list) and len(tagged) == 3:
                    dtype, shape, b64 = tagged
                    a = np.frombuffer(
                        base64.b64decode(b64), dtype=np.dtype(dtype))
                    return a.reshape(shape)
            if "__b64__" in value and isinstance(value["__b64__"], str):
                return base64.b64decode(value["__b64__"])
        return {k: from_jsonable(v) for k, v in value.items()}
    if isinstance(value, list):
        return [from_jsonable(v) for v in value]
    return value


def dumps(value: Any) -> bytes:
    """The JSON wire format: UTF-8 text, arrays/bytes tagged base64."""
    return json.dumps(to_jsonable(value)).encode("utf-8")


def loads(data: bytes) -> Any:
    try:
        return from_jsonable(json.loads(data))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CodecError(f"malformed JSON frame: {e}") from e


# ---------------------------------------------------------------------------
# one payload surface for both formats
# ---------------------------------------------------------------------------


def encode_payload(value: Any, *, binary: bool) -> bytes:
    """``value`` in the requested wire format (the sender's negotiated
    choice); either output decodes through :func:`decode_payload`."""
    return encode(value) if binary else dumps(value)


def decode_payload(payload: bytes) -> Tuple[Any, bool]:
    """Auto-detecting decode: ``(value, was_binary)``.  Raises
    :class:`CodecError` on malformed content in either format."""
    if is_binary(payload):
        return decode(payload), True
    return loads(payload), False


def wire_copy(value: Any) -> Any:
    """Structural copy + serializability check for in-process buses.

    Replaces the old ``json.loads(json.dumps(value))`` defensive copy
    (which both validated and decoupled the stored record from caller
    mutation) without the text round trip: containers are copied,
    scalars pass through, and arrays pass through UNCOPIED — a value
    that crossed the codec is a read-only view already, and the bus
    contract treats array payloads as immutable.  Raises
    :class:`CodecError` for values the wire could not carry."""
    if isinstance(value, (np.integer, np.floating, np.bool_)):
        return value.item()  # before the plain-scalar test: np.float64
        # IS a float subclass, but must leave the bus as a python float
    if value is None or isinstance(value, (bool, int, float, str, bytes)):
        return value
    if isinstance(value, np.ndarray):
        if value.dtype.hasobject:
            raise CodecError("object-dtype arrays are not wire-encodable")
        return np.ascontiguousarray(value)
    if isinstance(value, dict):
        return {
            (k if isinstance(k, str) else _coerce_key(k)): wire_copy(v)
            for k, v in value.items()
        }
    if isinstance(value, (list, tuple)):
        return [wire_copy(v) for v in value]
    raise CodecError(
        f"bus value of type {type(value).__name__} is not wire-encodable")


def contains_array(value: Any) -> bool:
    """Does this value carry an ndarray anywhere?  (Backends that store
    opaque bytes pick the binary layout exactly when it pays.)"""
    if isinstance(value, np.ndarray):
        return True
    if isinstance(value, dict):
        return any(contains_array(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return any(contains_array(v) for v in value)
    return False


# ---------------------------------------------------------------------------
# columnar tick blocks
# ---------------------------------------------------------------------------


def pack_ticks(msgs: Sequence[dict]) -> dict:
    """A run of per-tick router messages as ONE columnar block message.

    ``msgs`` are ``{"kind": "tick", "session", "row", "seq"[, "trace"]}``
    dicts with ndarray rows.  The block stacks the rows into a
    contiguous ``(B, F)`` float32 array and the seqs into one int64
    column; session ids are dictionary-encoded (the unique ids plus an
    int32 index column — a pool of S sessions repeats each id B/S times
    per block, so the string column would dominate the frame and the
    per-tick decode cost otherwise)."""
    rows = np.stack([m["row"] for m in msgs])
    if rows.dtype != np.float32:
        rows = rows.astype(np.float32)
    uniq: Dict[str, int] = {}
    ids: List[str] = []
    idx: List[int] = []
    seqs: List[int] = []
    for m in msgs:
        s = m["session"]
        j = uniq.get(s)
        if j is None:
            j = uniq[s] = len(ids)
            ids.append(s)
        idx.append(j)
        seqs.append(m["seq"])
    block = {
        "kind": "tick_block",
        "ids": ids,
        "idx": np.asarray(idx, np.int32),
        "seqs": np.asarray(seqs, np.int64),
        "rows": rows,
    }
    traces = [m.get("trace") for m in msgs]
    if any(t is not None for t in traces):
        block["traces"] = traces
    return block


def iter_ticks(block: dict) -> Iterator[Tuple[str, np.ndarray, int, Optional[str]]]:
    """``(session, row_view, seq, trace)`` per tick of a block.  Rows
    are views into the block's contiguous array (zero copy — the
    gateway's staging copy is the first and only one)."""
    ids = block["ids"]
    idx = np.asarray(block["idx"]).tolist()  # one C pass, not B boxes
    rows = np.asarray(block["rows"], np.float32)
    seqs = np.asarray(block["seqs"]).tolist()
    traces = block.get("traces")
    for i, j in enumerate(idx):
        yield (ids[j], rows[i], seqs[i],
               None if traces is None else traces[i])


#: below this run length a block's envelope costs more than it saves
MIN_BLOCK_TICKS = 2


def coalesce_ticks(msgs: List[dict]) -> List[dict]:
    """Collapse runs of consecutive ``tick`` messages into columnar
    blocks, preserving order with interleaved control messages (opens,
    closes, drain markers break runs — the inbox stays FIFO)."""
    out: List[dict] = []
    run: List[dict] = []

    def flush_run() -> None:
        if len(run) >= MIN_BLOCK_TICKS:
            out.append(pack_ticks(run))
        else:
            out.extend(run)
        run.clear()

    for m in msgs:
        if m.get("kind") == "tick":
            run.append(m)
        else:
            flush_run()
            out.append(m)
    flush_run()
    return out


# ---------------------------------------------------------------------------
# columnar result blocks (the return path's mirror of tick blocks)
# ---------------------------------------------------------------------------


def pack_results(msgs: Sequence[dict], label_vocab: Sequence[str]) -> dict:
    """A run of per-tick result messages as ONE columnar block.

    ``msgs`` are the gateway's published results
    (``{"session", "seq", "probabilities", "pred_labels",
    "prob_threshold"[, "trace"]}``).  The block stacks probabilities
    into one contiguous ``(B, C)`` float32 array (bit-exact: the
    per-tick path's float64 boxing of float32 values round-trips
    exactly, so both dialects hand back identical bits), seqs into one
    int64 column, dictionary-encodes session ids, and packs each
    result's label set as a bitmask over ``label_vocab`` — the
    gateway's ``y_fields``, whose order IS the per-tick label order, so
    decode reproduces the exact label lists.  The threshold is uniform
    per flush and stored once, as is the optional ``weights_version``
    a hot-swapping gateway stamps into its results — a run straddling
    a swap barrier mixes versions and is *not* packable (the gateway
    falls back to per-tick messages, which is exactly what bounds the
    mixed-version window to one flush)."""
    probs = np.asarray(
        [m["probabilities"] for m in msgs], np.float32)
    vid = {lab: j for j, lab in enumerate(label_vocab)}
    if len(vid) > 63:
        raise CodecError(
            f"label vocabulary of {len(vid)} does not fit an i64 mask")
    uniq: Dict[str, int] = {}
    ids: List[str] = []
    idx: List[int] = []
    seqs: List[int] = []
    masks: List[int] = []
    threshold = float(msgs[0]["prob_threshold"])
    weights_version = msgs[0].get("weights_version")
    for m in msgs:
        s = m["session"]
        j = uniq.get(s)
        if j is None:
            j = uniq[s] = len(ids)
            ids.append(s)
        idx.append(j)
        seqs.append(m["seq"])
        if float(m["prob_threshold"]) != threshold:
            raise CodecError(
                "result run mixes prob_threshold values — not packable")
        if m.get("weights_version") != weights_version:
            raise CodecError(
                "result run mixes weights_version values — not packable")
        mask = 0
        for lab in m["pred_labels"]:
            bit = vid.get(lab)
            if bit is None:
                raise CodecError(
                    f"label {lab!r} is not in the block vocabulary")
            mask |= 1 << bit
        masks.append(mask)
    block = {
        "kind": "result_block",
        "ids": ids,
        "idx": np.asarray(idx, np.int32),
        "seqs": np.asarray(seqs, np.int64),
        "probs": probs,
        "labels": list(label_vocab),
        "masks": np.asarray(masks, np.int64),
        "prob_threshold": threshold,
    }
    if weights_version is not None:
        block["weights_version"] = int(weights_version)
    traces = [m.get("trace") for m in msgs]
    if any(t is not None for t in traces):
        block["traces"] = traces
    return block


def iter_results(block: dict) -> Iterator[dict]:
    """Per-result messages (the per-tick wire shape) out of a block.
    Probability rows are views into the block's contiguous array —
    zero copy on a binary link, same bits on either dialect."""
    ids = block["ids"]
    idx = np.asarray(block["idx"]).tolist()
    probs = np.asarray(block["probs"], np.float32)
    seqs = np.asarray(block["seqs"]).tolist()
    masks = np.asarray(block["masks"]).tolist()
    vocab = list(block["labels"])
    threshold = block["prob_threshold"]
    weights_version = block.get("weights_version")
    traces = block.get("traces")
    for i, j in enumerate(idx):
        msg = {
            "session": ids[j],
            "seq": seqs[i],
            "probabilities": probs[i],
            "pred_labels": [
                lab for b, lab in enumerate(vocab) if masks[i] >> b & 1],
            "prob_threshold": threshold,
        }
        if weights_version is not None:
            msg["weights_version"] = weights_version
        if traces is not None and traces[i] is not None:
            msg["trace"] = traces[i]
        yield msg


# ---------------------------------------------------------------------------
# packed row columns (the warehouse journal's binary record layout)
# ---------------------------------------------------------------------------


def pack_rows(rows: Sequence[Dict[str, Any]]) -> dict:
    """Landing-row dicts as packed columns: every key whose value is a
    float in every row becomes one contiguous float64 column; everything
    else (timestamps, ints, missing keys) stays a per-row list.  f64
    columns carry the doubles bit-exact — the crash-replay dedupe
    compares what :func:`unpack_rows` returns against the store."""
    rows = list(rows)
    keys: List[str] = []
    seen = set()
    for row in rows:
        for k in row:
            if k not in seen:
                seen.add(k)
                keys.append(k)
    num: Dict[str, np.ndarray] = {}
    obj: Dict[str, List[Any]] = {}
    for k in keys:
        vals = [row.get(k) for row in rows]
        if all(type(v) is float for v in vals):
            num[k] = np.asarray(vals, np.float64)
        else:
            obj[k] = vals
    return {"n": len(rows), "num": num, "obj": obj}


def unpack_rows(block: dict) -> List[Dict[str, Any]]:
    n = int(block["n"])
    rows: List[Dict[str, Any]] = [{} for _ in range(n)]
    for k, col in block["obj"].items():
        for i, v in enumerate(col):
            if v is not None:
                rows[i][k] = v
    for k, col in block["num"].items():
        col = np.asarray(col, np.float64)
        for i in range(n):
            rows[i][k] = float(col[i])
    return rows
