"""ctypes binding for the native C++ ring-buffer bus (native/ringbus.cpp).

Implements the same :class:`~fmda_tpu.stream.bus.MessageBus` contract as
:class:`~fmda_tpu.stream.bus.InProcessBus` — topics, monotonic offsets,
independent consumers, bounded retention — on top of the C++ topic log.
The shared library is built on demand with the checked-in Makefile (g++ is
part of the toolchain); environments without a compiler fall back to the
Python bus.
"""

from __future__ import annotations

import ctypes
import logging
from typing import Iterable, List, Optional, Sequence

from fmda_tpu.obs.trace import default_tracer, stamp_message, stamp_messages
from fmda_tpu.stream import codec
from fmda_tpu.stream._native import build_and_load
from fmda_tpu.stream.bus import Consumer, Record

log = logging.getLogger("fmda_tpu.stream")

_TRACER = default_tracer()


class NativeBusUnavailable(RuntimeError):
    pass


def _load_library() -> ctypes.CDLL:
    lib = build_and_load("libringbus.so", NativeBusUnavailable)
    lib.rb_create.restype = ctypes.c_void_p
    lib.rb_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.rb_destroy.argtypes = [ctypes.c_void_p]
    lib.rb_topic.restype = ctypes.c_int64
    lib.rb_topic.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rb_publish.restype = ctypes.c_int64
    lib.rb_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
    ]
    lib.rb_read.restype = ctypes.c_int64
    lib.rb_read.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int64,
    ]
    lib.rb_end_offset.restype = ctypes.c_int64
    lib.rb_end_offset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.rb_base_offset.restype = ctypes.c_int64
    lib.rb_base_offset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    return lib


_lib: Optional[ctypes.CDLL] = None


def native_available() -> bool:
    try:
        _get_lib()
        return True
    except NativeBusUnavailable:  # loss-free: a capability probe
        return False


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load_library()
    return _lib


class NativeBus:
    """MessageBus over the C++ topic log."""

    READ_CHUNK = 256
    READ_BUF_BYTES = 1 << 20

    def __init__(
        self,
        topics: Iterable[str],
        arena_bytes: int = 1 << 22,
        max_records: int = 1 << 16,
    ) -> None:
        self._lib = _get_lib()
        self._handle = self._lib.rb_create(arena_bytes, max_records)
        if not self._handle:
            raise NativeBusUnavailable("rb_create failed")
        self._topic_ids = {}
        for name in topics:
            tid = self._lib.rb_topic(self._handle, name.encode())
            if tid < 0:
                raise NativeBusUnavailable(f"rb_topic({name!r}) failed")
            self._topic_ids[name] = tid
        #: host-side publish/consume accounting (fmda_tpu.obs), populated
        #: by :meth:`bind_metrics`; the C++ log itself is uninstrumented
        self._publish_counters = None
        self._consumed_cb = None
        self._metrics_registry = None

    def add_topic(self, topic: str) -> None:
        """Create a topic after construction (idempotent; the C++ side's
        ``rb_topic`` registers-or-looks-up under its own mutex) — the
        dynamic-membership entry point the fleet needs so a worker can
        join beyond the launch-time inbox set (ROADMAP (c))."""
        if topic in self._topic_ids:
            return
        tid = self._lib.rb_topic(self._handle, topic.encode())
        if tid < 0:
            raise NativeBusUnavailable(f"rb_topic({topic!r}) failed")
        self._topic_ids[topic] = tid
        if self._publish_counters is not None:
            self._publish_counters[topic] = self._metrics_registry.counter(
                "bus_published_total", topic=topic)

    def bind_metrics(self, registry) -> None:
        """Same per-topic publish/consume counters as
        :meth:`InProcessBus.bind_metrics` — counted in the Python wrapper,
        so cross-process writers bypassing this handle are not seen."""
        self._metrics_registry = registry
        self._publish_counters = {
            t: registry.counter("bus_published_total", topic=t)
            for t in self._topic_ids
        }
        consume_counters = {
            t: registry.counter("bus_consumed_total", topic=t)
            for t in self._topic_ids
        }

        def consumed(topic: str, n: int) -> None:
            counter = consume_counters.get(topic)
            if counter is None:
                counter = consume_counters[topic] = registry.counter(
                    "bus_consumed_total", topic=topic)
            counter.inc(n)

        self._consumed_cb = consumed

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.rb_destroy(handle)
            self._handle = None

    def _tid(self, topic: str) -> int:
        if topic not in self._topic_ids:
            raise KeyError(
                f"unknown topic {topic!r}; configured: {sorted(self._topic_ids)}"
            )
        return self._topic_ids[topic]

    # -- MessageBus ----------------------------------------------------------

    def _publish_one(self, tid: int, topic: str, value: dict) -> int:
        """Serialize + size-guard + rb_publish for one record (shared by
        :meth:`publish` and :meth:`publish_many`; counter bumps stay with
        the callers so a batch increments once)."""
        # the C++ log stores opaque length-prefixed blobs, so the value
        # layout is free: binary codec frames when the value carries an
        # array (packed columns — no base64, no text floats), JSON text
        # otherwise (inspectable in a debugger); readers auto-detect per
        # record off the codec magic byte
        payload = codec.encode_payload(
            value, binary=codec.contains_array(value))
        if len(payload) > self.READ_BUF_BYTES:
            # a record the read buffer can never return would wedge its
            # consumers forever — reject at the door
            raise RuntimeError(
                f"record of {len(payload)}B exceeds the bus record limit "
                f"({self.READ_BUF_BYTES}B)"
            )
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        offset = self._lib.rb_publish(self._handle, tid, buf, len(payload))
        if offset < 0:
            raise RuntimeError(
                f"publish to {topic!r} failed (record {len(payload)}B too "
                "large for the arena?)"
            )
        return offset

    def publish(self, topic: str, value: dict) -> int:
        if _TRACER.enabled:  # in-band trace context (fmda_tpu.obs.trace)
            value = stamp_message(value)
        offset = self._publish_one(self._tid(topic), topic, value)
        if self._publish_counters is not None:
            self._publish_counters[topic].inc()
        return offset

    def publish_many(self, topic: str, values) -> List[int]:
        """Batched :meth:`publish`: the topic id is resolved and the
        metrics counter bumped once for the whole batch; records land in
        the C++ log in order.  Messages without their own ``trace``
        field inherit the active trace context."""
        if _TRACER.enabled:
            values = stamp_messages(values)
        tid = self._tid(topic)
        offsets = [self._publish_one(tid, topic, v) for v in values]
        if self._publish_counters is not None and offsets:
            self._publish_counters[topic].inc(len(offsets))
        return offsets

    def read(
        self, topic: str, offset: int, max_records: Optional[int] = None
    ) -> List[Record]:
        tid = self._tid(topic)
        out: List[Record] = []
        remaining = max_records
        cursor = max(offset, 0)
        buf = (ctypes.c_uint8 * self.READ_BUF_BYTES)()
        offsets = (ctypes.c_uint64 * self.READ_CHUNK)()
        lengths = (ctypes.c_uint32 * self.READ_CHUNK)()
        while True:
            chunk = self.READ_CHUNK if remaining is None else min(
                self.READ_CHUNK, remaining)
            if chunk <= 0:
                break
            # Snapshot end BEFORE reading: if rb_read then returns 0 while
            # the snapshot shows a retained record at the cursor, that
            # record provably predates the read and didn't fit the buffer
            # (a publish racing after the snapshot can't trip this).
            end_snapshot = self.end_offset(topic)
            n = self._lib.rb_read(
                self._handle, tid, cursor, buf, self.READ_BUF_BYTES,
                offsets, lengths, chunk,
            )
            if n < 0:
                raise RuntimeError(f"rb_read failed on {topic!r}")
            if n == 0:
                if cursor < end_snapshot and cursor >= self.base_offset(topic):
                    raise RuntimeError(
                        f"record at {topic!r} offset {cursor} exceeds the "
                        f"read buffer ({self.READ_BUF_BYTES}B)"
                    )
                break
            pos = 0
            for i in range(n):
                raw = bytes(buf[pos : pos + lengths[i]])
                pos += lengths[i]
                out.append(Record(
                    topic, int(offsets[i]), codec.decode_payload(raw)[0]))
            cursor = int(offsets[n - 1]) + 1
            if remaining is not None:
                remaining -= n
                if remaining <= 0:
                    break
            # NOTE: n < chunk does NOT mean end-of-log — rb_read also stops
            # early when the byte buffer fills; loop until n == 0.
        return out

    def end_offset(self, topic: str) -> int:
        return int(self._lib.rb_end_offset(self._handle, self._tid(topic)))

    def base_offset(self, topic: str) -> int:
        return int(self._lib.rb_base_offset(self._handle, self._tid(topic)))

    def topics(self) -> Sequence[str]:
        return tuple(self._topic_ids)

    def consumer(self, topic: str, *, from_end: bool = False) -> Consumer:
        c = Consumer(self, topic)
        if from_end:
            c.seek_to_end()
        return c
