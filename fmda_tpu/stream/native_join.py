"""ctypes binding for the native C++ join scheduler (native/joincore.cpp).

The streaming engine's hot loop — watermarked interval matching of every
pending book row against every side stream — runs in C++ when this backend
is selected (``StreamEngine(..., join_backend="native")``); payloads stay
in Python keyed by timestamp, so only int64 scheduling state crosses the
boundary.  Bit-identical join decisions to the Python path (equivalence is
golden-day test-locked); the library builds on demand like the ring bus.
"""

from __future__ import annotations

import ctypes
import logging
from typing import List, Optional, Tuple

from fmda_tpu.stream._native import build_and_load

log = logging.getLogger("fmda_tpu.stream")


class NativeJoinUnavailable(RuntimeError):
    pass


def _load_library() -> ctypes.CDLL:
    lib = build_and_load("libjoincore.so", NativeJoinUnavailable)
    lib.jc_create.restype = ctypes.c_void_p
    lib.jc_create.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.jc_destroy.argtypes = [ctypes.c_void_p]
    lib.jc_add_side.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64]
    lib.jc_force_max_ts.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64]
    lib.jc_add_deep.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.jc_pending.restype = ctypes.c_int64
    lib.jc_pending.argtypes = [ctypes.c_void_p]
    lib.jc_step.restype = ctypes.c_int64
    lib.jc_step.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    return lib


_lib: Optional[ctypes.CDLL] = None


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load_library()
    return _lib


def native_join_available() -> bool:
    try:
        _get_lib()
        return True
    except NativeJoinUnavailable:  # loss-free: a capability probe
        return False


class NativeJoinCore:
    """Scheduler handle: add timestamps, step, read matched tuples."""

    def __init__(
        self, floor_s: int, tolerance_s: int, watermark_s: int, n_streams: int
    ) -> None:
        self._lib = _get_lib()
        self.n_streams = n_streams
        self._handle = self._lib.jc_create(
            floor_s, tolerance_s, watermark_s, n_streams)
        if not self._handle:
            raise NativeJoinUnavailable("jc_create failed")

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.jc_destroy(handle)
            self._handle = None

    def add_side(self, stream: int, ts: int) -> None:
        self._lib.jc_add_side(self._handle, stream, ts)

    def force_max_ts(self, stream: int, max_ts: int) -> None:
        self._lib.jc_force_max_ts(self._handle, stream, max_ts)

    def add_deep(self, ts: int) -> None:
        self._lib.jc_add_deep(self._handle, ts)

    @property
    def pending(self) -> int:
        return int(self._lib.jc_pending(self._handle))

    def step(self) -> Tuple[List[Tuple[int, ...]], List[int]]:
        """Run one micro-batch.  Returns (emitted, dropped):
        emitted = [(deep_ts, side_ts_0, ..., side_ts_{n-1}), ...] in
        timestamp order; dropped = [deep_ts, ...]."""
        cap = max(self.pending, 1)
        width = 1 + self.n_streams
        rows = (ctypes.c_int64 * (cap * width))()
        drops = (ctypes.c_int64 * cap)()
        n_dropped = ctypes.c_int64(0)
        n = int(self._lib.jc_step(
            self._handle, rows, cap, drops, cap, ctypes.byref(n_dropped)))
        if n < 0 or n > cap or n_dropped.value > cap:
            raise RuntimeError("jc_step overflow/failure")
        emitted = [
            tuple(rows[i * width : (i + 1) * width]) for i in range(n)
        ]
        dropped = [int(drops[i]) for i in range(n_dropped.value)]
        return emitted, dropped
