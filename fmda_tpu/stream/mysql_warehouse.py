"""MariaDB/MySQL warehouse adapter: reference-parity SQL codegen + client.

The embedded SQLite warehouse (:mod:`fmda_tpu.stream.warehouse`) is the
framework default; this module provides drop-in MariaDB deployment parity
with the reference's schema layer (create_database.py): the joined table
DDL, every windowed-indicator VIEW, the target VIEW, and the canonical
``join_statement`` X-query are **generated from the feature config** — the
same config→schema codegen property, emitting the same column names and
window-frame semantics (including the reference's 15-row ``14 PRECEDING``
frames for stochastic/ATR and the ``LEAD`` 8/15 targets).

All codegen is pure string construction (unit-tested without a server);
:class:`MySQLWarehouse` is the thin gated client that executes it when
``mysql.connector`` is installed.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from fmda_tpu.config import (
    COT_GROUPS,
    COT_VALUES,
    EVENT_VALUES,
    FeatureConfig,
    VOLUME_COLUMNS,
    WarehouseConfig,
)


# ---------------------------------------------------------------------------
# DDL codegen (create_database.py:29-73)
# ---------------------------------------------------------------------------


def create_table_sql(fc: FeatureConfig, table: str) -> str:
    """Joined-table DDL with the reference's MySQL column types."""
    cols: List[str] = []
    for i in range(fc.bid_levels):
        cols.append(f"bid_{i}_size MEDIUMINT NOT NULL")
    for i in range(1, fc.bid_levels):
        cols.append(f"bid_{i} FLOAT(6,2) NOT NULL")
    for i in range(fc.ask_levels):
        cols.append(f"ask_{i}_size MEDIUMINT NOT NULL")
    for i in range(1, fc.ask_levels):
        cols.append(f"ask_{i} FLOAT(6,2) NOT NULL")
    cols += [
        "bids_ord_WA FLOAT(6,4)",
        "asks_ord_WA FLOAT(6,4) NOT NULL",
        "vol_imbalance FLOAT(7,4) NOT NULL",
        "delta MEDIUMINT NOT NULL",
        "micro_price FLOAT(7,2) NOT NULL",
        "spread FLOAT(7,4) NOT NULL",
        "session_start TINYINT NOT NULL",
    ]
    cols += [f"day_{d} TINYINT NOT NULL" for d in range(1, 5)]
    cols += [f"week_{w} TINYINT NOT NULL" for w in range(1, 5)]
    if fc.get_vix:
        cols.append("VIX FLOAT(5,2) NOT NULL")
    if fc.get_stock_volume:
        for c in VOLUME_COLUMNS:
            kind = (
                "INT NOT NULL" if c == "5_volume"
                else "FLOAT(6,4) NOT NULL" if c == "wick_prct"
                else "FLOAT(6,2) NOT NULL"
            )
            cols.append(f"`{c}` {kind}")
    if fc.get_cot:
        for g in COT_GROUPS:
            for v in COT_VALUES:
                kind = (
                    "MEDIUMINT NOT NULL" if v.endswith("pos")
                    else "FLOAT(6,1) NOT NULL" if v.endswith("change")
                    else "FLOAT(4,1) NOT NULL"
                )
                cols.append(f"{g}_{v} {kind}")
    for event in fc.event_list_repl:
        for value in EVENT_VALUES:
            cols.append(f"{event}_{value} FLOAT(8,3) NOT NULL")
    body = ", ".join(cols)
    return (
        f"CREATE TABLE IF NOT EXISTS {table} "
        f"(ID MEDIUMINT KEY AUTO_INCREMENT, Timestamp DATETIME, {body});"
    )


# ---------------------------------------------------------------------------
# View codegen (create_database.py:76-190)
# ---------------------------------------------------------------------------


def _trailing_frame(preceding: int) -> str:
    return f"ROWS BETWEEN {preceding} PRECEDING AND CURRENT ROW"


def ma_view_sql(
    view: str, column: str, periods: Sequence[int], table: str, prefix: str
) -> str:
    """Moving-average view over a trailing ``period``-row frame."""
    selects = ", ".join(
        f"AVG(`{column}`) OVER (ORDER BY Timestamp {_trailing_frame(p - 1)}) "
        f"AS {prefix}{p}"
        for p in periods
    )
    names = ", ".join(f"{prefix}{p}" for p in periods)
    return (
        f"CREATE OR REPLACE VIEW {view}(Timestamp, {names}) AS "
        f"SELECT Timestamp, {selects} FROM {table};"
    )


def bollinger_view_sql(fc: FeatureConfig, table: str) -> str:
    n = fc.bollinger_std
    frame = _trailing_frame(fc.bollinger_period - 1)
    return (
        "CREATE OR REPLACE VIEW bollinger_bands"
        "(Timestamp, upper_BB_dist, lower_BB_dist) AS SELECT Timestamp, "
        f"(BB_avg + {n} * BB_std) - `4_close` AS upper_BB_dist, "
        f"`4_close` - (BB_avg - {n} * BB_std) AS lower_BB_dist "
        "FROM (SELECT Timestamp, `4_close`, "
        f"STD(`4_close`) OVER (ORDER BY Timestamp {frame}) AS BB_std, "
        f"AVG(`4_close`) OVER (ORDER BY Timestamp {frame}) AS BB_avg "
        f"FROM {table}) AS S;"
    )


def stochastic_view_sql(fc: FeatureConfig, table: str) -> str:
    frame = _trailing_frame(fc.stoch_preceding)
    return (
        "CREATE OR REPLACE VIEW stochastic_oscillator(Timestamp, stoch) AS "
        "SELECT Timestamp, ((`4_close` - mn) / (mx - mn)) AS stoch "
        "FROM (SELECT Timestamp, `4_close`, "
        f"MIN(`4_close`) OVER (ORDER BY Timestamp {frame}) AS mn, "
        f"MAX(`4_close`) OVER (ORDER BY Timestamp {frame}) AS mx "
        f"FROM {table}) AS S;"
    )


def price_change_view_sql(table: str) -> str:
    return (
        "CREATE OR REPLACE VIEW price_change(Timestamp, price_change) AS "
        "SELECT Timestamp, (`4_close` - LAG(`4_close`, 1) "
        f"OVER (ORDER BY Timestamp)) AS price_change FROM {table};"
    )


def atr_view_sql(fc: FeatureConfig, table: str) -> str:
    frame = _trailing_frame(fc.atr_preceding)
    return (
        "CREATE OR REPLACE VIEW ATR(Timestamp, ATR) AS SELECT Timestamp, "
        f"(AVG(`2_high` - `3_low`) OVER (ORDER BY Timestamp {frame})) AS ATR "
        f"FROM {table};"
    )


def target_view_sql(fc: FeatureConfig, table: str) -> str:
    n1, n2 = fc.target_n1, fc.target_n2
    l1, l2 = fc.target_lead1, fc.target_lead2
    return (
        "CREATE OR REPLACE VIEW target(Timestamp, ID, p0_close, "
        "p_lead1_close, p_lead2_close, ATR, up1, up2, down1, down2) AS "
        "SELECT Timestamp, ID, p0_close, p_lead1_close, p_lead2_close, ATR, "
        f"CASE WHEN p_lead1_close >= (p0_close + ({n1} * ATR)) THEN 1 ELSE 0 END AS up1, "
        f"CASE WHEN p_lead2_close >= (p0_close + ({n2} * ATR)) THEN 1 ELSE 0 END AS up2, "
        f"CASE WHEN p_lead1_close <= (p0_close - ({n1} * ATR)) THEN 1 ELSE 0 END AS down1, "
        f"CASE WHEN p_lead2_close <= (p0_close - ({n2} * ATR)) THEN 1 ELSE 0 END AS down2 "
        "FROM (SELECT sd.Timestamp, sd.ID, sd.`4_close` AS p0_close, ATR, "
        f"LEAD(sd.`4_close`, {l1}) OVER (ORDER BY Timestamp) AS p_lead1_close, "
        f"LEAD(sd.`4_close`, {l2}) OVER (ORDER BY Timestamp) AS p_lead2_close "
        f"FROM {table} sd JOIN ATR ON sd.Timestamp = ATR.Timestamp) AS T;"
    )


def all_view_sql(fc: FeatureConfig, table: str) -> List[str]:
    """Every view statement the schema needs, in dependency order."""
    out: List[str] = []
    has_ohlc = bool(fc.get_stock_volume)
    if has_ohlc and fc.volume_ma_periods:
        out.append(ma_view_sql("vol_MA", "5_volume", fc.volume_ma_periods,
                               table, "vol_MA"))
    if has_ohlc and fc.price_ma_periods:
        out.append(ma_view_sql("price_MA", "4_close", fc.price_ma_periods,
                               table, "price_MA"))
    if fc.delta_ma_periods:
        out.append(ma_view_sql("delta_MA", "delta", fc.delta_ma_periods,
                               table, "delta_MA"))
    if has_ohlc and fc.bollinger_period and fc.bollinger_std:
        out.append(bollinger_view_sql(fc, table))
    if has_ohlc and fc.stochastic_oscillator:
        out.append(stochastic_view_sql(fc, table))
    if has_ohlc:
        out.append(price_change_view_sql(table))
        out.append(atr_view_sql(fc, table))
        out.append(target_view_sql(fc, table))
    return out


def join_select_fields(fc: FeatureConfig) -> List[str]:
    """Select expressions of the canonical X-query, one per
    ``fc.x_fields()`` entry, in the same order (the structured form of the
    reference's introspected select list, create_database.py:240-241)."""
    has_ohlc = bool(fc.get_stock_volume)
    selects = [f"sd.`{c}`" for c in fc.table_columns()]
    if has_ohlc and fc.bollinger_period and fc.bollinger_std:
        selects += ["bb.upper_BB_dist", "bb.lower_BB_dist"]
    if has_ohlc and fc.volume_ma_periods:
        selects += [f"vol.vol_MA{p}" for p in fc.volume_ma_periods]
    if has_ohlc and fc.price_ma_periods:
        selects += [f"p.price_MA{p}" for p in fc.price_ma_periods]
    if fc.delta_ma_periods:
        selects += [f"d.delta_MA{p}" for p in fc.delta_ma_periods]
    if has_ohlc and fc.stochastic_oscillator:
        selects += ["so.stoch"]
    if has_ohlc:
        selects += ["ATR.ATR", "pc.price_change"]
    return selects


def join_from_clause(fc: FeatureConfig, table: str) -> str:
    """FROM + JOIN clause of the canonical X-query (no trailing ';')."""
    has_ohlc = bool(fc.get_stock_volume)
    joins = []
    if has_ohlc and fc.bollinger_period and fc.bollinger_std:
        joins.append("JOIN bollinger_bands bb ON sd.Timestamp = bb.Timestamp")
    if has_ohlc and fc.volume_ma_periods:
        joins.append("JOIN vol_MA vol ON sd.Timestamp = vol.Timestamp")
    if has_ohlc and fc.price_ma_periods:
        joins.append("JOIN price_MA p ON sd.Timestamp = p.Timestamp")
    if fc.delta_ma_periods:
        joins.append("JOIN delta_MA d ON sd.Timestamp = d.Timestamp")
    if has_ohlc and fc.stochastic_oscillator:
        joins.append(
            "JOIN stochastic_oscillator so ON sd.Timestamp = so.Timestamp")
    if has_ohlc:
        joins.append("JOIN ATR ON sd.Timestamp = ATR.Timestamp")
        joins.append("JOIN price_change pc ON sd.Timestamp = pc.Timestamp")
    return f"FROM {table} sd " + " ".join(joins)


def join_statement_sql(fc: FeatureConfig, table: str) -> str:
    """The canonical X-query selecting every table + view column — the
    reference's ``join_statement`` (create_database.py:240-258), generated
    directly from config instead of DESCRIBE introspection."""
    return (
        "SELECT " + ", ".join(join_select_fields(fc)) + " "
        + join_from_clause(fc, table) + ";"
    )


def insert_sql(fc: FeatureConfig, table: str) -> str:
    """Parameterized landing INSERT over the config-generated column set
    (the write half of the config→schema property: the same
    ``table_columns()`` order the DDL and the embedded warehouse use, so
    the engine can land through either backend)."""
    cols = fc.table_columns()
    col_list = "Timestamp, " + ", ".join(f"`{c}`" for c in cols)
    placeholders = ", ".join(["%s"] * (1 + len(cols)))
    return f"INSERT INTO {table} ({col_list}) VALUES ({placeholders});"


# ---------------------------------------------------------------------------
# Gated client
# ---------------------------------------------------------------------------


class MySQLWarehouse:
    """MariaDB-backed warehouse implementing the FeatureSource protocol.

    Requires ``mysql.connector`` (not bundled); the constructor raises a
    clear error otherwise.  Uses the codegen above for bootstrap, and the
    join statement with ``IFNULL(...,0)`` for fetches
    (sql_pytorch_dataloader.py:219 parity).
    """

    def __init__(
        self, features: FeatureConfig, config: Optional[WarehouseConfig] = None
    ) -> None:
        try:
            import mysql.connector  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "MySQLWarehouse needs the 'mysql-connector-python' package; "
                "use the embedded SQLite Warehouse otherwise"
            ) from e
        self.features = features
        self.config = config or WarehouseConfig(backend="mysql")
        self._cnx = mysql.connector.connect(
            host=self.config.hostname,
            port=self.config.port,
            user=self.config.user,
            password=self.config.password,
        )
        cur = self._cnx.cursor()
        cur.execute(
            f"CREATE DATABASE IF NOT EXISTS {self.config.database_name}")
        cur.execute(f"USE {self.config.database_name}")
        cur.execute(create_table_sql(features, self.config.table_name))
        for stmt in all_view_sql(features, self.config.table_name):
            cur.execute(stmt)
        self._cursor = cur

    @property
    def x_fields(self) -> Tuple[str, ...]:
        return self.features.x_fields()

    def __len__(self) -> int:
        self._cursor.execute(
            f"SELECT COUNT(ID) FROM {self.config.table_name}")
        return int(self._cursor.fetchone()[0])

    def insert_rows(self, rows: Sequence[dict]) -> int:
        """Land joined feature rows — same contract as the embedded
        Warehouse (unknown keys rejected, missing keys stored as 0), so
        the engine and the write-ahead journal front either backend."""
        if not rows:
            return 0
        cols = self.features.table_columns()
        known = frozenset(cols) | {"Timestamp"}
        values = []
        for row in rows:
            if not known.issuperset(row.keys()):
                unknown = sorted(set(row) - known)
                raise KeyError(f"unknown feature columns: {unknown}")
            get = row.get
            values.append(
                [get("Timestamp")] + [float(get(c) or 0.0) for c in cols])
        self._cursor.executemany(
            insert_sql(self.features, self.config.table_name), values)
        self._cnx.commit()
        return len(values)

    def has_timestamp(self, ts: str) -> bool:
        """Point existence probe (the engine dedupe / journal-drain
        idempotency hook)."""
        self._cursor.execute(
            f"SELECT 1 FROM {self.config.table_name} "
            "WHERE Timestamp = %s LIMIT 1;", (ts,))
        return self._cursor.fetchone() is not None

    def recent_timestamps(self, limit: int) -> List[str]:
        """Newest ``limit`` timestamps (the engine's landed-dedupe seed)."""
        self._cursor.execute(
            f"SELECT Timestamp FROM {self.config.table_name} "
            "ORDER BY ID DESC LIMIT %s;", (int(limit),))
        return [r[0] for r in self._cursor.fetchall()]

    def ids_for_timestamps(
        self, timestamps: Sequence[str],
    ) -> List[Optional[int]]:
        """1-based landed positions for each timestamp (``None`` when it
        never landed) — same contract as the embedded Warehouse's.  IDs
        double as positions under the table's append-only AUTO_INCREMENT
        assumption (the same one :meth:`fetch` leans on); duplicate
        landings resolve to the newest row, like the embedded backend.
        """
        ts_list = [str(t) for t in timestamps]
        if not ts_list:
            return []
        placeholders = ", ".join(["%s"] * len(set(ts_list)))
        self._cursor.execute(
            f"SELECT Timestamp, MAX(ID) FROM {self.config.table_name} "
            f"WHERE Timestamp IN ({placeholders}) GROUP BY Timestamp;",
            sorted(set(ts_list)))
        by_ts = {str(r[0]): int(r[1]) for r in self._cursor.fetchall()}
        return [by_ts.get(t) for t in ts_list]

    def iter_row_chunks(
        self,
        start_ts: Optional[str] = None,
        end_ts: Optional[str] = None,
        chunk: int = 4096,
        *,
        follow: int = 0,
        poll_wait=None,
    ):
        """Bulk history reader — the embedded backend's contract
        (:meth:`fmda_tpu.stream.warehouse.Warehouse.iter_row_chunks`)
        over a keyset-paginated MySQL ``SELECT``: ``WHERE ID > last``
        + ``ORDER BY ID LIMIT chunk`` per page, so a backfill over a
        large landed table never materialises an unbounded result set
        and never re-scans from offset 0 (OFFSET pagination is O(n²)
        over the scan).  Yields the raw landed columns as
        ``(timestamps, (B, F) float64)`` — bit-for-bit what the
        embedded backend yields for the same landed rows (tests
        assert parity through the fake server).

        ``follow > 0`` is the bounded tail-follow of the embedded
        contract: short pages keep scanning, empty pages wait
        (``poll_wait()``, injectable; default 50 ms sleep) and re-poll
        the same keyset cursor, and ``follow`` consecutive empty polls
        end the scan — identical stop/resume semantics on both
        backends, parity-tested."""
        import numpy as np

        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        cols = self.features.table_columns()
        col_list = ", ".join(f"`{c}`" for c in cols)
        conds = ["ID > %s"]
        bounds: list = []
        if start_ts is not None:
            conds.append("Timestamp >= %s")
            bounds.append(start_ts)
        if end_ts is not None:
            conds.append("Timestamp <= %s")
            bounds.append(end_ts)
        where = " AND ".join(conds)
        last_id = 0
        idle = 0
        while True:
            self._cursor.execute(
                f"SELECT ID, Timestamp, {col_list} "
                f"FROM {self.config.table_name} "
                f"WHERE {where} ORDER BY ID LIMIT %s;",
                (last_id, *bounds, int(chunk)),
            )
            rows = self._cursor.fetchall()
            if not rows:
                if follow <= 0 or idle >= int(follow):
                    return
                idle += 1
                if poll_wait is not None:
                    poll_wait()
                else:
                    import time as _time

                    _time.sleep(0.05)
                continue
            idle = 0
            last_id = int(rows[-1][0])
            matrix = np.asarray(
                [r[2:] for r in rows], np.float64
            ).reshape(len(rows), len(cols))
            yield [r[1] or "" for r in rows], matrix
            if len(rows) < chunk and follow <= 0:
                return

    def joined_row_transform(self):
        """Fresh stateful mapper from :meth:`iter_row_chunks`' raw landed
        chunks to the joined ``x_fields`` rows :meth:`fetch` serves —
        same contract as the embedded backend's method of the same name."""
        from fmda_tpu.ops.indicators import landed_row_transform

        return landed_row_transform(
            self.features.table_columns(), self.features)

    def healthy(self) -> bool:
        """Probe that the server still answers — the ``/healthz``
        warehouse check, same contract as the embedded backend."""
        try:
            self._cursor.execute("SELECT 1;")
            self._cursor.fetchone()
            return True
        except Exception:  # noqa: BLE001 — loss-free: a health probe; any failure IS the "unhealthy" signal
            return False

    def fetch(self, ids: Sequence[int]):
        """Feature rows in the *requested id order* (multi-join row order is
        otherwise unspecified — silently scrambled training windows on a
        real server; ADVICE r1).  Raises on ids the warehouse doesn't have,
        like the embedded Warehouse.

        Index-space note: the embedded Warehouse speaks dense 1-based
        *positions* mapped to IDs internally; this adapter queries raw
        MariaDB autoincrement IDs, which equal positions under the
        deployment's append-only, no-rollback writer (the reference's own
        dataloader makes the same assumption, indexing 1..COUNT(ID) —
        sql_pytorch_dataloader.py:65-78).  A burned rowid on a live server
        surfaces as the raise above, never as a silently shifted window."""
        import numpy as np

        ids = [int(i) for i in ids]
        fields = ", ".join(
            f"IFNULL({f}, 0)" for f in join_select_fields(self.features)
        )
        self._cursor.execute(
            f"SELECT sd.ID, {fields} "
            + join_from_clause(self.features, self.config.table_name)
            + f" WHERE sd.ID IN ({', '.join(map(str, set(ids)))})"
            " ORDER BY sd.ID;"
        )
        by_id = {int(r[0]): r[1:] for r in self._cursor.fetchall()}
        missing = [i for i in ids if i not in by_id]
        if missing:
            raise IndexError(
                f"warehouse has no rows for ids {missing[:10]}"
                f"{'...' if len(missing) > 10 else ''}"
            )
        return np.asarray([by_id[i] for i in ids], np.float32)

    def fetch_windows(self, row_ids: Sequence[int], window: int):
        """Batched trailing-window gather, ``(B, window, F)`` — the same
        contract as the embedded Warehouse's: one round-trip for the
        *union* of window ids (overlapping windows of a flush share most
        rows, and :meth:`fetch` already de-duplicates the IN list), then
        a host-side reshape per window.  Raises on any missing row, like
        :meth:`fetch`."""
        import numpy as np

        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        row_ids = [int(i) for i in row_ids]
        if not row_ids:
            return np.zeros(
                (0, window, len(self.features.x_fields())), np.float32)
        flat = [i - window + 1 + k for i in row_ids for k in range(window)]
        rows = self.fetch(flat)  # ONE IN-query over the de-duplicated ids
        return rows.reshape(len(row_ids), window, -1)

    def fetch_targets(self, ids: Sequence[int]):
        """Target labels in the requested id order (same contract as
        :meth:`fetch`)."""
        import numpy as np

        ids = [int(i) for i in ids]
        self._cursor.execute(
            "SELECT ID, up1, up2, down1, down2 FROM target WHERE ID IN "
            f"({', '.join(map(str, set(ids)))}) ORDER BY ID;"
        )
        by_id = {int(r[0]): r[1:] for r in self._cursor.fetchall()}
        missing = [i for i in ids if i not in by_id]
        if missing:
            raise IndexError(
                f"target view has no rows for ids {missing[:10]}"
                f"{'...' if len(missing) > 10 else ''}"
            )
        return np.asarray([by_id[i] for i in ids], np.float32)
