"""Write-ahead journal: warehouse-outage survival for the landing path.

The engine's contract is "land + signal, never abort" — but the
reference's only answer to an unreachable store is a crashed consumer
(spark_consumer.py has no write failure handling at all), and our own
``Warehouse.insert_rows`` raised straight through the engine step.
:class:`BufferedWarehouse` puts a bounded, *durable* write-ahead buffer
in front of any warehouse (embedded SQLite or the MariaDB adapter —
anything with ``insert_rows``/``has_timestamp``):

- a failed ``insert_rows`` **spills** the rows to a local JSONL journal
  file (counted, never silent) and reports success to the engine — the
  row is durable on disk, the signal still fires, serving skips the
  not-yet-landed row counted (``missing_rows``/``serve_errors``);
- a **backfill** drain re-lands journaled rows once the store answers
  again — called from the engine step loop (idle ticks drain too) and
  from every ``insert_rows`` (ordering: journaled rows are older than
  the rows being landed, so they go first);
- landing is **idempotent on timestamp**: every drained row is probed
  with ``has_timestamp`` before insert, so a crash between the store
  commit and the journal compaction replays into a counted skip, never
  a duplicate row;
- the journal is **bounded**: overflow sheds the oldest rows, counted
  (``shed_rows``) — same never-silent shedding contract as the fleet
  gateway queue;
- a process restart **recovers** the journal from disk (rows are
  flushed line-by-line; a torn trailing line from a mid-write kill is
  dropped, counted).

The file is the durability unit: each spill is flushed immediately;
compaction (after drains/sheds) rewrites through the ``tmp +
os.replace`` idiom so a crash mid-compact keeps the previous journal
intact.  ``flush()`` is OS-buffer durability (survives process death);
full fsync-per-row durability would serialize the landing hot path on
disk latency for a failure mode (kernel panic in the spill window) the
timestamp-idempotent replay already absorbs.

Two record layouts (``fmt``, config ``[warehouse] journal_format``):

- ``jsonl`` (default) — one JSON line per row, human-inspectable with
  ``tail -f``/``jq``: the debug format;
- ``binary`` — each spilled batch is one length-prefixed packed-column
  frame (:mod:`fmda_tpu.stream.codec`: float columns as contiguous f64
  arrays, no float→decimal→float round trip), the same layout the wire
  speaks — at fleet drain rates the journal's encode pass sits on the
  landing hot path exactly like the bus's did.

Recovery auto-detects per record, so a journal written under one
setting (or a mixed one after a config flip) always replays; torn or
corrupt trailing records are dropped, counted, in either layout.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
from typing import Dict, List, Optional, Sequence

from fmda_tpu.stream import codec

log = logging.getLogger("fmda_tpu.stream")

#: binary journal records: 4-byte big-endian length + one codec frame
_JLEN = struct.Struct(">I")

JOURNAL_FORMATS = ("jsonl", "binary")


def _parse_journal(data: bytes) -> tuple:
    """``(rows, n_corrupt)`` from raw journal bytes, auto-detecting the
    per-record layout: a ``{`` byte starts a JSONL row line, anything
    else a length-prefixed binary frame (whose payload must carry the
    codec magic).  A record that fails to parse is dropped and counted;
    a torn length/payload (mid-write kill) ends the scan — everything
    before it already parsed."""
    rows: List[Dict[str, float]] = []
    corrupt = 0
    i, n = 0, len(data)
    while i < n:
        b = data[i]
        if b in (0x0A, 0x0D):  # blank separator
            i += 1
            continue
        if b == 0x7B:  # '{' — a JSONL row line
            end = data.find(b"\n", i)
            line = data[i:n if end < 0 else end]
            i = n if end < 0 else end + 1
            try:
                # lint: ignore[hot-path-json] jsonl recovery — the sanctioned human-inspectable journal layout
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                corrupt += 1
            continue
        if i + _JLEN.size > n:
            corrupt += 1  # torn length prefix
            break
        (length,) = _JLEN.unpack_from(data, i)
        start = i + _JLEN.size
        if start + length > n:
            corrupt += 1  # torn trailing frame from a mid-write kill
            break
        payload = data[start:start + length]
        i = start + length
        try:
            rows.extend(codec.unpack_rows(codec.decode(payload)))
        except (codec.CodecError, KeyError, TypeError, ValueError):
            corrupt += 1
    return rows, corrupt


class BufferedWarehouse:
    """Warehouse proxy that journals rows the backing store rejects.

    Implements the full warehouse surface by delegation (``__getattr__``
    keeps it in lockstep with whatever the backing warehouse grows, the
    :class:`~fmda_tpu.chaos.wrap.ChaosWarehouse` discipline); the
    overrides below are exactly the methods whose answers must include
    journaled-but-unlanded rows so the engine's crash-replay dedupe
    stays exact across an outage.
    """

    def __init__(
        self,
        inner,
        journal_path: str,
        *,
        bound: int = 65536,
        fmt: str = "jsonl",
    ) -> None:
        if fmt not in JOURNAL_FORMATS:
            raise ValueError(
                f"journal format {fmt!r} not one of {JOURNAL_FORMATS}")
        self._inner = inner
        self._path = journal_path
        self._fmt = fmt
        self._bound = max(1, int(bound))
        # guards the pending list/set, the counters, and the file handle
        self._lock = threading.Lock()
        self._pending: List[Dict[str, float]] = []
        self._pending_ts: set = set()
        self._counters: Dict[str, int] = {
            "spilled_rows": 0,
            "backfilled_rows": 0,
            "shed_rows": 0,
            "dedupe_skipped": 0,
            "drain_failures": 0,
            "poison_rows": 0,
            "recovered_rows": 0,
            "corrupt_lines": 0,
        }
        self._fh = None
        with self._lock:
            self._recover_locked()

    # -- journal mechanics (callers hold self._lock) -------------------------

    def _recover_locked(self) -> None:
        """Load a journal left behind by a previous incarnation.
        Auto-detects the record layout byte by byte (JSONL lines start
        ``{``; binary records with a length prefix + codec magic), so a
        journal written under either ``journal_format`` — or a mix,
        after a config flip — always replays."""
        if not os.path.exists(self._path):
            return
        with open(self._path, "rb") as fh:
            data = fh.read()
        rows, corrupt = _parse_journal(data)
        # torn/corrupt records (a mid-write kill) are dropped, counted;
        # the rows re-land from bus replay through the dedupe
        self._counters["corrupt_lines"] += corrupt
        if len(rows) > self._bound:
            self._counters["shed_rows"] += len(rows) - self._bound
            rows = rows[-self._bound:]
        self._pending = rows
        self._pending_ts = {r.get("Timestamp") for r in rows}
        self._counters["recovered_rows"] += len(rows)
        if rows:
            log.warning(
                "recovered %d journaled row(s) from %s; backfill will "
                "drain them once the store answers", len(rows), self._path)
        # compact unconditionally: torn/shed lines must not survive on
        # disk to be re-parsed (and re-counted) by every incarnation
        self._rewrite_locked()

    def _handle_locked(self):
        if self._fh is None:
            self._fh = open(self._path, "ab")
        return self._fh

    def _encode_rows(self, rows: Sequence[Dict[str, float]]) -> bytes:
        """One durable journal record batch in the configured layout."""
        if self._fmt == "binary":
            payload = codec.encode(codec.pack_rows(rows))
            return _JLEN.pack(len(payload)) + payload
        return b"".join(
            # lint: ignore[hot-path-json] jsonl — the sanctioned human-inspectable journal layout
            (json.dumps(row) + "\n").encode("utf-8") for row in rows)

    def _rewrite_locked(self) -> None:
        """Compact the journal file to exactly the pending rows (tmp +
        atomic replace: a crash mid-compact keeps the previous file)."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        tmp = f"{self._path}.tmp"
        with open(tmp, "wb") as fh:
            if self._pending:
                fh.write(self._encode_rows(self._pending))
        os.replace(tmp, self._path)

    def _spill_locked(self, rows: Sequence[Dict[str, float]],
                      reason: str) -> int:
        fh = self._handle_locked()
        fh.write(self._encode_rows(rows))
        fh.flush()
        self._pending.extend(dict(r) for r in rows)
        self._pending_ts.update(r.get("Timestamp") for r in rows)
        self._counters["spilled_rows"] += len(rows)
        overflow = len(self._pending) - self._bound
        if overflow > 0:
            shed = self._pending[:overflow]
            self._pending = self._pending[overflow:]
            self._pending_ts = {
                r.get("Timestamp") for r in self._pending}
            self._counters["shed_rows"] += len(shed)
            log.warning(
                "journal overflow: shed %d oldest row(s) (bound %d)",
                len(shed), self._bound)
            self._rewrite_locked()
        log.warning(
            "warehouse append failed (%s): %d row(s) journaled to %s "
            "(%d pending)", reason, len(rows), self._path,
            len(self._pending))
        return len(rows)

    # -- the landing path ----------------------------------------------------

    def insert_rows(self, rows: Sequence[Dict[str, float]]) -> int:
        """Land rows, spilling to the journal when the store refuses.

        Returns the row count either way — from the engine's point of
        view the rows are durably accepted; whether they are in the
        store or the journal is visible in :meth:`journal_stats`, the
        ``warehouse_journal`` health check, and the logs, never in an
        exception on the landing hot path."""
        rows = list(rows)
        if not rows:
            return 0
        self.drain_journal()
        with self._lock:
            if self._pending:
                # the store is still down (drain left rows behind):
                # journal the new rows too, preserving landing order
                return self._spill_locked(rows, "store still down")
        try:
            return self._inner.insert_rows(rows)
        except (KeyError, ValueError, TypeError, IndexError):
            # programming-shaped failures (unknown columns, bad row
            # dicts) must stay loud — journaling them would retry a bug
            # forever
            raise
        except Exception as e:  # noqa: BLE001 — transport/store-shaped
            # failure (ConnectionError incl. injected ChaosFault,
            # sqlite3/mysql errors, closed handles): the outage the
            # journal exists for
            with self._lock:
                return self._spill_locked(rows, repr(e))

    def drain_journal(self, max_rows: Optional[int] = None) -> int:
        """Re-land journaled rows; returns how many landed.

        Never raises: a store still down leaves the remaining rows in
        the journal (counted ``drain_failures``).  Each row is probed
        with the store's ``has_timestamp`` first, so replay after a
        crash between commit and compaction skips counted instead of
        double-landing.  A row the store rejects for a *data-shaped*
        reason (bad columns/values — rows spill before the store ever
        validated them) is dropped and counted (``poison_rows``) with
        an error log: retrying a poison row forever would wedge every
        future landing into the journal behind it.
        """
        with self._lock:
            if not self._pending:
                return 0
            batch = list(self._pending if max_rows is None
                         else self._pending[:max_rows])
        landed = 0
        skipped = 0
        poisoned = 0
        done = 0  # rows settled (landed/deduped/poisoned), in order
        failure = None
        for row in batch:
            ts = row.get("Timestamp")
            try:
                if ts is not None and self._inner.has_timestamp(ts):
                    skipped += 1
                elif self._inner.insert_rows([row]):
                    landed += 1
            except (KeyError, ValueError, TypeError, IndexError) as e:
                poisoned += 1
                log.error(
                    "journaled row %s is unlandable (%r): dropped "
                    "(poison_rows)", ts, e)
            except Exception as e:  # noqa: BLE001 — loss-free: still
                # down — this row and everything after it STAY in the
                # journal (pending, the gate's summed term); retried
                # next drain
                failure = e
                break
            done += 1
        with self._lock:
            self._pending = self._pending[done:]
            self._pending_ts = {
                r.get("Timestamp") for r in self._pending}
            self._counters["backfilled_rows"] += landed
            self._counters["dedupe_skipped"] += skipped
            self._counters["poison_rows"] += poisoned
            if failure is not None:
                self._counters["drain_failures"] += 1
            if done:
                self._rewrite_locked()
            remaining = len(self._pending)
        if failure is not None:
            log.warning(
                "journal drain stopped (%r): %d row(s) still pending",
                failure, remaining)
        if done:
            log.warning(
                "journal backfill: %d row(s) landed, %d deduped, %d "
                "poisoned, %d still pending", landed, skipped, poisoned,
                remaining)
        return landed

    # -- dedupe-exactness overrides ------------------------------------------

    def has_timestamp(self, ts: str) -> bool:
        """True when the row is in the store OR the journal — the
        engine's crash-replay dedupe must treat a journaled row as
        landed, or replay would spill a duplicate copy."""
        with self._lock:
            if ts in self._pending_ts:
                return True
        return bool(self._inner.has_timestamp(ts))

    def recent_timestamps(self, limit: int) -> List[str]:
        """Store tail plus the journal tail, so a restarted engine's
        landed-tick seed covers rows an outage left in the journal."""
        out = self._inner.recent_timestamps(limit)
        with self._lock:
            tail = [r.get("Timestamp") for r in self._pending[-limit:]]
        return out + [t for t in tail if t is not None]

    # -- observability -------------------------------------------------------

    def journal_stats(self) -> Dict[str, int]:
        """Counters + current backlog (the ``warehouse_journal`` health
        check and obs collector read this)."""
        with self._lock:
            return {**self._counters, "pending": len(self._pending)}

    @property
    def journal_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- delegation ----------------------------------------------------------

    def __getattr__(self, name: str):
        return getattr(self._inner, name)

    def __len__(self) -> int:  # dunder lookups bypass __getattr__
        return len(self._inner)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        self._inner.close()
