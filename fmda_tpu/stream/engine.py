"""Streaming feature engine: the framework-owned Spark replacement.

Consumes the five feed topics from the bus, aligns heterogeneous timestamps,
computes microstructure/candle features, interval-joins the feeds, lands
joined rows in the warehouse, and emits a ``predict_timestamp`` signal per
row — the whole role of the reference's ``spark_consumer.py`` (506 lines +
JVM + external Spark/Kafka processes) as one deterministic, testable,
host-side micro-batch engine.

Semantics preserved from the reference:

- timestamps floored to 5-minute buckets (spark_consumer.py:111/181/231/263/315);
- inner interval join: a side-stream row matches a book row iff their floors
  are equal AND the side timestamp lies within ``[deep_ts, deep_ts + 3min]``
  (spark_consumer.py:434-477);
- 5-minute watermark bounds state: a book row with no match is *dropped*
  once every enabled stream's watermark has passed its join horizon;
- missing values become 0 (fillna, spark_consumer.py:311/480);
- exactly one output row per book tick (the reference's ``dropDuplicates``
  intent, spark_consumer.py:477) — the earliest match per stream is used;
- the signal topic carries the joined row's timestamp and is checkpointed
  via consumer offsets (spark_consumer.py:490-502).

Deviation (deliberate): the race the reference papers over with
``sleep(15)`` in serving (predict.py:141-157) cannot happen here — the
signal is emitted strictly *after* the warehouse insert commits.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from fmda_tpu.config import (
    COT_GROUPS,
    COT_VALUES,
    EVENT_VALUES,
    FeatureConfig,
    TOPIC_COT,
    TOPIC_DEEP,
    TOPIC_IND,
    TOPIC_PREDICT_TIMESTAMP,
    TOPIC_VIX,
    TOPIC_VOLUME,
)
from fmda_tpu.ops.microstructure import deep_features, wick_percentage
from fmda_tpu.stream.bus import MessageBus
from fmda_tpu.stream.warehouse import Warehouse
from fmda_tpu.utils.timeutils import floor_epoch, parse_ts, to_epoch
from fmda_tpu.utils.tracing import StageTimer

log = logging.getLogger("fmda_tpu.stream")


@dataclass
class _Event:
    ts: int  # epoch seconds
    ts_str: str
    payload: Dict[str, float]


@dataclass
class _StreamBuffer:
    """Per-feed buffer with watermark tracking."""

    name: str
    events: List[_Event] = field(default_factory=list)
    max_ts: int = -1

    def add(self, event: _Event) -> None:
        self.events.append(event)
        self.max_ts = max(self.max_ts, event.ts)

    def watermark(self, delay_s: int) -> int:
        return self.max_ts - delay_s if self.max_ts >= 0 else -1

    def evict_before(self, ts: int) -> None:
        self.events = [e for e in self.events if e.ts >= ts]

    def match(self, deep_ts: int, floor_s: int, tolerance_s: int) -> Optional[_Event]:
        """Earliest event with equal floor and ts in [deep_ts, deep_ts+tol]."""
        target_floor = floor_epoch(deep_ts, floor_s)
        best: Optional[_Event] = None
        for e in self.events:
            if floor_epoch(e.ts, floor_s) != target_floor:
                continue
            if not (deep_ts <= e.ts <= deep_ts + tolerance_s):
                continue
            if best is None or e.ts < best.ts:
                best = e
        return best


def _parse_deep(value: dict, bid_levels: int, ask_levels: int) -> _Event:
    """Flatten a DEEP book message (producer reshape, getMarketData.py:117-127;
    Spark schema spark_consumer.py:281-308).  Missing levels -> 0."""
    ts_str = value["Timestamp"]
    bids = np.zeros((1, bid_levels))
    bid_sizes = np.zeros((1, bid_levels))
    asks = np.zeros((1, ask_levels))
    ask_sizes = np.zeros((1, ask_levels))
    for i in range(bid_levels):
        lvl = value.get(f"bids_{i}") or {}
        bids[0, i] = lvl.get(f"bid_{i}") or 0.0
        bid_sizes[0, i] = lvl.get(f"bid_{i}_size") or 0.0
    for i in range(ask_levels):
        lvl = value.get(f"asks_{i}") or {}
        asks[0, i] = lvl.get(f"ask_{i}") or 0.0
        ask_sizes[0, i] = lvl.get(f"ask_{i}_size") or 0.0
    feats = deep_features(
        bids, bid_sizes, asks, ask_sizes, [parse_ts(ts_str)]
    )
    payload = {k: float(v[0]) for k, v in feats.items()}
    return _Event(to_epoch(ts_str), ts_str, payload)


def _parse_vix(value: dict) -> _Event:
    ts_str = value["Timestamp"]
    return _Event(to_epoch(ts_str), ts_str, {"VIX": float(value.get("VIX") or 0.0)})


def _parse_volume(value: dict) -> _Event:
    """OHLCV bar + wick percentage (spark_consumer.py:186-193)."""
    ts_str = value["Timestamp"]
    payload = {
        k: float(value.get(k) or 0.0)
        for k in ("1_open", "2_high", "3_low", "4_close", "5_volume")
    }
    payload["wick_prct"] = float(
        wick_percentage(
            [payload["1_open"]],
            [payload["2_high"]],
            [payload["3_low"]],
            [payload["4_close"]],
        )[0]
    )
    return _Event(to_epoch(ts_str), ts_str, payload)


def _parse_cot(value: dict) -> _Event:
    """Flatten nested COT groups (spark_consumer.py:200-225)."""
    ts_str = value["Timestamp"]
    payload: Dict[str, float] = {}
    for group in COT_GROUPS:
        nested = value.get(group) or {}
        for v in COT_VALUES:
            key = f"{group}_{v}"
            payload[key] = float(nested.get(key) or 0.0)
    return _Event(to_epoch(ts_str), ts_str, payload)


def _parse_ind(value: dict, events: Tuple[str, ...]) -> _Event:
    """Flatten the indicator template message (spark_consumer.py:239-259)."""
    ts_str = value["Timestamp"]
    payload: Dict[str, float] = {}
    for event in events:
        nested = value.get(event) or {}
        for ev_val in EVENT_VALUES:
            payload[f"{event}_{ev_val}"] = float(nested.get(ev_val) or 0.0)
    return _Event(to_epoch(ts_str), ts_str, payload)


class StreamEngine:
    """Micro-batch join engine over the bus feeds."""

    def __init__(
        self,
        bus: MessageBus,
        warehouse: Warehouse,
        features: FeatureConfig,
        *,
        signal_topic: str = TOPIC_PREDICT_TIMESTAMP,
        checkpoint_path: Optional[str] = None,
        from_end: bool = False,
    ) -> None:
        self.bus = bus
        self.warehouse = warehouse
        self.features = features
        self.signal_topic = signal_topic
        self.checkpoint_path = checkpoint_path

        self._side_streams: Dict[str, _StreamBuffer] = {}
        self._consumers = {}
        self._consumers[TOPIC_DEEP] = bus.consumer(TOPIC_DEEP, from_end=from_end)
        if features.get_vix:
            self._side_streams[TOPIC_VIX] = _StreamBuffer(TOPIC_VIX)
            self._consumers[TOPIC_VIX] = bus.consumer(TOPIC_VIX, from_end=from_end)
        if features.get_stock_volume:
            self._side_streams[TOPIC_VOLUME] = _StreamBuffer(TOPIC_VOLUME)
            self._consumers[TOPIC_VOLUME] = bus.consumer(TOPIC_VOLUME, from_end=from_end)
        if features.get_cot:
            self._side_streams[TOPIC_COT] = _StreamBuffer(TOPIC_COT)
            self._consumers[TOPIC_COT] = bus.consumer(TOPIC_COT, from_end=from_end)
        self._side_streams[TOPIC_IND] = _StreamBuffer(TOPIC_IND)
        self._consumers[TOPIC_IND] = bus.consumer(TOPIC_IND, from_end=from_end)

        self._pending_deep: List[_Event] = []
        self._emitted = 0
        self._dropped = 0
        #: per-stage wall-clock accounting (SURVEY.md §5: the reference has
        #: no tracing; here every step exposes ingest/join/land/signal time)
        self.timer = StageTimer()
        if checkpoint_path and os.path.exists(checkpoint_path):
            self.restore()

    # -- parsing -------------------------------------------------------------

    def _ingest(self) -> None:
        fc = self.features
        for rec in self._consumers[TOPIC_DEEP].poll():
            try:
                self._pending_deep.append(
                    _parse_deep(rec.value, fc.bid_levels, fc.ask_levels)
                )
            except (KeyError, ValueError, TypeError) as e:
                log.warning("bad deep message at offset %d: %s", rec.offset, e)
        parsers = {
            TOPIC_VIX: _parse_vix,
            TOPIC_VOLUME: _parse_volume,
            TOPIC_COT: _parse_cot,
            TOPIC_IND: lambda v: _parse_ind(v, fc.event_list_repl),
        }
        for topic, buf in self._side_streams.items():
            for rec in self._consumers[topic].poll():
                try:
                    buf.add(parsers[topic](rec.value))
                except (KeyError, ValueError, TypeError) as e:
                    log.warning(
                        "bad %s message at offset %d: %s", topic, rec.offset, e
                    )

    # -- join ----------------------------------------------------------------

    def step(self) -> int:
        """One micro-batch: poll, join what's ready, land + signal.

        Returns the number of rows emitted this step.
        """
        fc = self.features
        with self.timer.stage("ingest"):
            self._ingest()
        emitted_rows: List[Dict[str, float]] = []
        still_pending: List[_Event] = []

        with self.timer.stage("join"):
            for deep_ev in sorted(self._pending_deep, key=lambda e: e.ts):
                matches: Dict[str, _Event] = {}
                expired = False  # some stream can provably never match
                waiting = False  # some stream might still deliver a match
                for topic, buf in self._side_streams.items():
                    m = buf.match(deep_ev.ts, fc.floor_s, fc.join_tolerance_s)
                    if m is not None:
                        matches[topic] = m
                    elif (
                        buf.watermark(fc.watermark_s)
                        > deep_ev.ts + fc.join_tolerance_s
                    ):
                        expired = True
                    else:
                        waiting = True
                if expired:
                    # inner join: one unmatched stream past its horizon
                    # kills the row
                    self._dropped += 1
                    log.warning(
                        "dropping unjoinable book row at %s (no side match "
                        "within tolerance)", deep_ev.ts_str,
                    )
                elif waiting:
                    still_pending.append(deep_ev)
                else:  # all side streams matched
                    row: Dict[str, float] = {"Timestamp": deep_ev.ts_str}
                    row.update(deep_ev.payload)
                    for m in matches.values():
                        row.update(m.payload)
                    emitted_rows.append(row)

        self._pending_deep = still_pending

        if emitted_rows:
            with self.timer.stage("land"):
                self.warehouse.insert_rows(emitted_rows)
            # signal AFTER the write commits: no sleep-and-retry race
            with self.timer.stage("signal"):
                for row in emitted_rows:
                    self.bus.publish(
                        self.signal_topic, {"Timestamp": row["Timestamp"]}
                    )
            self._emitted += len(emitted_rows)

        # bound buffer state by the global watermark
        horizon = min(
            (b.watermark(fc.watermark_s) for b in self._side_streams.values()),
            default=-1,
        )
        if horizon > 0:
            for buf in self._side_streams.values():
                buf.evict_before(horizon - fc.join_tolerance_s)

        if self.checkpoint_path:
            self.checkpoint()
        return len(emitted_rows)

    # -- observability -------------------------------------------------------

    @property
    def stats(self) -> Dict[str, int]:
        return {
            "emitted": self._emitted,
            "dropped": self._dropped,
            "pending": len(self._pending_deep),
        }

    # -- checkpoint / resume -------------------------------------------------

    def checkpoint(self) -> None:
        """Persist the engine's durable state: consumer offsets *plus* all
        polled-but-unjoined events (pending book rows and side-stream
        buffers).  Offsets alone — the reference's Spark checkpoint story
        (spark_consumer.py:500) — would silently lose any row still waiting
        for a join match across a restart."""

        def dump_event(e: _Event) -> dict:
            return {"ts": e.ts, "ts_str": e.ts_str, "payload": e.payload}

        state = {
            "offsets": {t: c.offset for t, c in self._consumers.items()},
            "emitted": self._emitted,
            "dropped": self._dropped,
            "pending_deep": [dump_event(e) for e in self._pending_deep],
            "buffers": {
                t: {
                    "max_ts": b.max_ts,
                    "events": [dump_event(e) for e in b.events],
                }
                for t, b in self._side_streams.items()
            },
        }
        tmp = f"{self.checkpoint_path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(state, fh)
        os.replace(tmp, self.checkpoint_path)

    def restore(self) -> None:
        with open(self.checkpoint_path) as fh:
            state = json.load(fh)

        def load_event(d: dict) -> _Event:
            return _Event(d["ts"], d["ts_str"], d["payload"])

        for topic, offset in state["offsets"].items():
            if topic in self._consumers:
                self._consumers[topic].seek(offset)
        self._emitted = state.get("emitted", 0)
        self._dropped = state.get("dropped", 0)
        self._pending_deep = [
            load_event(d) for d in state.get("pending_deep", [])
        ]
        for topic, dump in state.get("buffers", {}).items():
            if topic in self._side_streams:
                buf = self._side_streams[topic]
                buf.events = [load_event(d) for d in dump["events"]]
                buf.max_ts = dump["max_ts"]
