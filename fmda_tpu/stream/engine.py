"""Streaming feature engine: the framework-owned Spark replacement.

Consumes the five feed topics from the bus, aligns heterogeneous timestamps,
computes microstructure/candle features, interval-joins the feeds, lands
joined rows in the warehouse, and emits a ``predict_timestamp`` signal per
row — the whole role of the reference's ``spark_consumer.py`` (506 lines +
JVM + external Spark/Kafka processes) as one deterministic, testable,
host-side micro-batch engine.

Semantics preserved from the reference:

- timestamps floored to 5-minute buckets (spark_consumer.py:111/181/231/263/315);
- inner interval join: a side-stream row matches a book row iff their floors
  are equal AND the side timestamp lies within ``[deep_ts, deep_ts + 3min]``
  (spark_consumer.py:434-477);
- 5-minute watermark bounds state: a book row with no match is *dropped*
  once every enabled stream's watermark has passed its join horizon;
- missing values become 0 (fillna, spark_consumer.py:311/480);
- exactly one output row per book tick (the reference's ``dropDuplicates``
  intent, spark_consumer.py:477) — the earliest match per stream is used;
- the signal topic carries the joined row's timestamp and is checkpointed
  via consumer offsets (spark_consumer.py:490-502).

Deviation (deliberate): the race the reference papers over with
``sleep(15)`` in serving (predict.py:141-157) cannot happen here — the
signal is emitted strictly *after* the warehouse insert commits.
"""

from __future__ import annotations

import json
import logging
import os
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from fmda_tpu.config import (
    COT_GROUPS,
    COT_VALUES,
    EVENT_VALUES,
    FeatureConfig,
    TOPIC_COT,
    TOPIC_DEEP,
    TOPIC_IND,
    TOPIC_PREDICT_TIMESTAMP,
    TOPIC_VIX,
    TOPIC_VOLUME,
)
from fmda_tpu.chaos.inject import default_chaos
from fmda_tpu.obs.trace import default_tracer, now_ns
from fmda_tpu.ops.microstructure import deep_features, wick_percentage
from fmda_tpu.stream.bus import MessageBus
from fmda_tpu.stream.warehouse import Warehouse
from fmda_tpu.utils.timeutils import floor_epoch, parse_ts, to_epoch
from fmda_tpu.utils.tracing import StageTimer

log = logging.getLogger("fmda_tpu.stream")

#: chaos injection singleton, captured once at import (the tracer's
#: discipline): ``engine.step`` is a compiled-in injection point so a
#: fault plan can kill/stall the join engine mid-stream (docs/chaos.md)
_CHAOS = default_chaos()


@dataclass
class _Event:
    ts: int  # epoch seconds
    ts_str: str
    payload: Dict[str, float]
    #: in-band trace context of the message that produced this event
    #: (deep/book events only — the book tick IS the traced entity);
    #: None when the producer wasn't tracing
    trace: Optional[str] = None
    #: True for a ghost event the engine synthesised for a stale side
    #: stream (degraded-mode join): the payload is that stream's
    #: last-known values (or empty — fillna 0 lands zeros).  A join
    #: consuming a ghost counts in ``degraded_rows``; real events are
    #: preferred over ghosts when both fall in a match window.
    degraded: bool = False


@dataclass
class _StreamBuffer:
    """Per-feed buffer with watermark tracking.

    Events are bucketed by floored timestamp so the join probe is an O(1)
    dict lookup plus a scan of one bucket (a handful of events), instead of
    a linear pass over everything buffered — the difference between O(rows)
    and O(rows^2) total work when replaying months of history through the
    engine (backtests, recovery)."""

    name: str
    floor_s: int
    buckets: Dict[int, List[_Event]] = field(default_factory=dict)
    max_ts: int = -1
    #: payload of the newest *real* event ever ingested — the
    #: "last-known values" a degraded-mode join falls back to while the
    #: feed is down; None until the stream first delivers
    last_payload: Optional[Dict[str, float]] = None

    def add(self, event: _Event) -> None:
        self.buckets.setdefault(
            floor_epoch(event.ts, self.floor_s), []).append(event)
        self.max_ts = max(self.max_ts, event.ts)
        if event.ts == self.max_ts:
            self.last_payload = event.payload

    def add_ghost(self, event: _Event) -> None:
        """Insert a degraded-mode ghost WITHOUT advancing ``max_ts`` (or
        ``last_payload``): the watermark tracks only what the feed really
        delivered, so recovery detection and eviction stay honest."""
        self.buckets.setdefault(
            floor_epoch(event.ts, self.floor_s), []).append(event)

    def watermark(self, delay_s: int) -> int:
        return self.max_ts - delay_s if self.max_ts >= 0 else -1

    def evict_before(self, ts: int) -> None:
        for fl in [f for f in self.buckets if f + self.floor_s <= ts]:
            del self.buckets[fl]
        boundary = floor_epoch(ts, self.floor_s)
        if boundary in self.buckets:  # partial bucket: filter exactly
            kept = [e for e in self.buckets[boundary] if e.ts >= ts]
            if kept:
                self.buckets[boundary] = kept
            else:
                del self.buckets[boundary]

    def match(self, deep_ts: int, tolerance_s: int) -> Optional[_Event]:
        """Earliest event with equal floor and ts in [deep_ts, deep_ts+tol].

        Real events beat ghosts regardless of timestamp: a feed that
        recovers inside a tick's match window should serve real values
        even though the ghost (minted at ``deep_ts``) sorts earliest."""
        best: Optional[_Event] = None
        for e in self.buckets.get(floor_epoch(deep_ts, self.floor_s), ()):
            if not (deep_ts <= e.ts <= deep_ts + tolerance_s):
                continue
            if (best is None or (best.degraded and not e.degraded)
                    or (best.degraded == e.degraded and e.ts < best.ts)):
                best = e
        return best

    @property
    def events(self) -> List[_Event]:
        """Flattened view (checkpointing and tests)."""
        return [e for fl in sorted(self.buckets) for e in self.buckets[fl]]


def _deep_key_table(bid_levels: int, ask_levels: int):
    """Precomputed per-level message keys — built once per engine, not
    per message (the f-strings were measurable in the replay profile)."""
    return (
        tuple((f"bids_{i}", f"bid_{i}", f"bid_{i}_size")
              for i in range(bid_levels)),
        tuple((f"asks_{i}", f"ask_{i}", f"ask_{i}_size")
              for i in range(ask_levels)),
    )


def _extract_deep_raw(value: dict, key_table) -> tuple:
    """Pull the raw book ladder out of one DEEP message (producer reshape,
    getMarketData.py:117-127; Spark schema spark_consumer.py:281-308).
    Missing levels -> 0.  Returns (ts_str, bids, bid_sizes, asks, ask_sizes)
    as python lists — feature math happens batched in
    :func:`_parse_deep_batch`."""
    ts_str = value["Timestamp"]
    to_epoch(ts_str)  # validate the timestamp before accepting the message
    bid_keys, ask_keys = key_table
    bids, bid_sizes = [], []
    asks, ask_sizes = [], []
    get = value.get
    for level_key, px_key, size_key in bid_keys:
        lvl = get(level_key) or {}
        bids.append(float(lvl.get(px_key) or 0.0))
        bid_sizes.append(float(lvl.get(size_key) or 0.0))
    for level_key, px_key, size_key in ask_keys:
        lvl = get(level_key) or {}
        asks.append(float(lvl.get(px_key) or 0.0))
        ask_sizes.append(float(lvl.get(size_key) or 0.0))
    return ts_str, bids, bid_sizes, asks, ask_sizes


def _parse_deep_batch(raws) -> List[_Event]:
    """Feature-compute a whole poll's DEEP messages in one vectorized pass
    (one ``deep_features`` call for N rows, not N calls of batch 1 — the
    replay-throughput difference is ~5x)."""
    if not raws:
        return []
    ts_strs = [r[0] for r in raws]
    feats = deep_features(
        np.asarray([r[1] for r in raws]),
        np.asarray([r[2] for r in raws]),
        np.asarray([r[3] for r in raws]),
        np.asarray([r[4] for r in raws]),
        [parse_ts(t) for t in ts_strs],
    )
    # .tolist() already yields python floats — no per-value float() needed
    cols = {k: v.tolist() for k, v in feats.items()}
    items = list(cols.items())
    return [
        _Event(to_epoch(ts), ts, {k: v[i] for k, v in items})
        for i, ts in enumerate(ts_strs)
    ]


def _parse_vix(value: dict) -> _Event:
    ts_str = value["Timestamp"]
    return _Event(to_epoch(ts_str), ts_str, {"VIX": float(value.get("VIX") or 0.0)})


def _parse_volume(value: dict) -> _Event:
    """OHLCV bar + wick percentage (spark_consumer.py:186-193)."""
    ts_str = value["Timestamp"]
    payload = {
        k: float(value.get(k) or 0.0)
        for k in ("1_open", "2_high", "3_low", "4_close", "5_volume")
    }
    payload["wick_prct"] = float(
        wick_percentage(
            [payload["1_open"]],
            [payload["2_high"]],
            [payload["3_low"]],
            [payload["4_close"]],
        )[0]
    )
    return _Event(to_epoch(ts_str), ts_str, payload)


#: COT flattening keys, built once at import (same f-string-hoisting as
#: :func:`_deep_key_table`; the combined name is both the nested lookup
#: key and the payload key, spark_consumer.py:200-225)
_COT_KEY_TABLE = tuple(
    (group, tuple(f"{group}_{v}" for v in COT_VALUES))
    for group in COT_GROUPS
)


def _parse_cot(value: dict) -> _Event:
    """Flatten nested COT groups (spark_consumer.py:200-225)."""
    ts_str = value["Timestamp"]
    payload: Dict[str, float] = {}
    vget = value.get
    for group, keys in _COT_KEY_TABLE:
        nget = (vget(group) or {}).get
        for key in keys:
            payload[key] = float(nget(key) or 0.0)
    return _Event(to_epoch(ts_str), ts_str, payload)


def _ind_key_table(events: Tuple[str, ...]):
    """(event, ((payload_key, nested_key), ...)) — built once per engine
    (39 f-strings per message otherwise, spark_consumer.py:239-259)."""
    return tuple(
        (event, tuple((f"{event}_{v}", v) for v in EVENT_VALUES))
        for event in events
    )


def _parse_ind(value: dict, key_table) -> _Event:
    """Flatten the indicator template message (spark_consumer.py:239-259)."""
    ts_str = value["Timestamp"]
    payload: Dict[str, float] = {}
    vget = value.get
    for event, pairs in key_table:
        nget = (vget(event) or {}).get
        for out_key, ev_val in pairs:
            payload[out_key] = float(nget(ev_val) or 0.0)
    return _Event(to_epoch(ts_str), ts_str, payload)


class StreamEngine:
    """Micro-batch join engine over the bus feeds."""

    #: in-memory landed-tick dedupe entries kept/seeded before falling
    #: back to indexed warehouse lookups for older ticks
    _LANDED_SEED_LIMIT = 5000

    def __init__(
        self,
        bus: MessageBus,
        warehouse: Warehouse,
        features: FeatureConfig,
        *,
        signal_topic: str = TOPIC_PREDICT_TIMESTAMP,
        checkpoint_path: Optional[str] = None,
        from_end: bool = False,
        checkpoint_every: int = 1,
        join_backend: str = "python",
        staleness_deadline_s: Optional[int] = None,
        metrics=None,
    ) -> None:
        self.bus = bus
        self.warehouse = warehouse
        self.features = features
        self.signal_topic = signal_topic
        self.checkpoint_path = checkpoint_path
        #: Degraded-mode join deadline (stream-time seconds): once a side
        #: stream's watermark trails the newest book tick by more than
        #: this, the engine stops stalling on it and joins with the
        #: stream's last-known (or absent) values instead — each such
        #: row counted per topic in ``degraded_rows``.  None (default)
        #: keeps the strict inner-join stall semantics.
        self.staleness_deadline_s = staleness_deadline_s
        #: Checkpoint cadence in steps.  1 = after every step (strongest
        #: durability, the default); N > 1 amortises the state write over
        #: replay/backtest churn — a crash then replays at most the last N
        #: steps' messages from the bus (offsets move back with the
        #: checkpoint), re-landing those rows in the warehouse.
        self.checkpoint_every = max(1, checkpoint_every)
        self._steps_since_ckpt = 0
        self._dirty = False

        floor_s = features.floor_s
        self._side_streams: Dict[str, _StreamBuffer] = {}
        self._consumers = {}
        self._consumers[TOPIC_DEEP] = bus.consumer(TOPIC_DEEP, from_end=from_end)
        if features.get_vix:
            self._side_streams[TOPIC_VIX] = _StreamBuffer(TOPIC_VIX, floor_s)
            self._consumers[TOPIC_VIX] = bus.consumer(TOPIC_VIX, from_end=from_end)
        if features.get_stock_volume:
            self._side_streams[TOPIC_VOLUME] = _StreamBuffer(TOPIC_VOLUME, floor_s)
            self._consumers[TOPIC_VOLUME] = bus.consumer(TOPIC_VOLUME, from_end=from_end)
        if features.get_cot:
            self._side_streams[TOPIC_COT] = _StreamBuffer(TOPIC_COT, floor_s)
            self._consumers[TOPIC_COT] = bus.consumer(TOPIC_COT, from_end=from_end)
        self._side_streams[TOPIC_IND] = _StreamBuffer(TOPIC_IND, floor_s)
        self._consumers[TOPIC_IND] = bus.consumer(TOPIC_IND, from_end=from_end)

        #: kept sorted by ts (insertion-sorted on ingest; feeds are nearly
        #: in order, so the bisect degenerates to an append)
        self._pending_deep: List[_Event] = []
        #: optional C++ scheduler for the matching loop (join decisions
        #: only — payloads stay in the Python buffers/pending list); the
        #: "native" backend is bit-identical to "python", test-locked
        self._core = None
        if join_backend == "native" and staleness_deadline_s is not None:
            # degraded-mode preference (a real event beats a ghost
            # inside a match window) lives in the python scheduler's
            # match(); the C++ core's earliest-ts rule would pick the
            # ghost after a feed recovers mid-window, silently diverging
            # from the python path.  Loud fallback, same discipline as
            # an absent toolchain: the python path is bit-identical.
            log.warning(
                "degraded-mode joins (staleness_deadline_s=%s) run on "
                "the python join scheduler; ignoring join_backend="
                "'native'", staleness_deadline_s)
            join_backend = "python"
        if join_backend == "native":
            from fmda_tpu.stream.native_join import (
                NativeJoinCore, NativeJoinUnavailable,
            )

            try:
                self._stream_topics = list(self._side_streams)
                self._core = NativeJoinCore(
                    features.floor_s, features.join_tolerance_s,
                    features.watermark_s, len(self._stream_topics),
                )
            # loss-free: loud fallback, like default_bus for the ring
            # bus — the python join path is bit-identical, just not C++
            except NativeJoinUnavailable as e:
                # loud fallback, like default_bus for the ring bus: the
                # python path is bit-identical, just not C++
                log.warning(
                    "native join scheduler unavailable (%s); using the "
                    "python join path", e,
                )
                self._core = None
        elif join_backend != "python":
            raise ValueError(
                f"join_backend {join_backend!r}; use 'python' or 'native'")
        self._deep_keys = _deep_key_table(
            features.bid_levels, features.ask_levels)
        self._side_parsers = {
            TOPIC_VIX: _parse_vix,
            TOPIC_VOLUME: _parse_volume,
            TOPIC_COT: _parse_cot,
            TOPIC_IND: (
                lambda v, _kt=_ind_key_table(features.event_list_repl):
                _parse_ind(v, _kt)
            ),
        }
        #: timestamps of landed ticks — the "exactly one output row per
        #: book tick" dropDuplicates semantics (spark_consumer.py:477),
        #: which also makes crash-replay idempotent.  Seeded bounded from
        #: the warehouse tail at construction and pruned below the join
        #: watermark as the session runs; ticks older than the seed window
        #: fall back to an indexed warehouse lookup (deep replays stay
        #: exact without holding all history in memory).
        seed = warehouse.recent_timestamps(self._LANDED_SEED_LIMIT)
        self._landed_ts: set = set(seed)
        self._landed_seed_floor: Optional[str] = (
            min(seed) if len(seed) >= self._LANDED_SEED_LIMIT else None
        )
        self._emitted = 0
        self._dropped = 0
        #: malformed feed messages discarded at parse time — the
        #: never-abort contract counts every discard (a book tick that
        #: dies here was published but will never land, and the
        #: counted-loss lint rule holds parse drops to the same
        #: discipline as join drops)
        self._bad_messages = 0
        #: degraded-mode accounting: rows emitted with ghost features,
        #: per side topic, plus the timestamps of those rows (pruned with
        #: the landed-dedupe set) so a chaos harness can exclude them
        #: from bit-identity comparisons
        self._degraded_rows: Dict[str, int] = {
            t: 0 for t in self._side_streams}
        self._degraded_ts: set = set()
        #: corrupt/truncated checkpoint files survived (counted fresh
        #: starts — see :meth:`restore`)
        self._checkpoint_corrupt = 0
        #: newest book-tick timestamp ingested (epoch s) — the stream-time
        #: "now" that watermark ages in :attr:`stats` are measured against
        self._max_deep_ts = -1
        #: first book-tick timestamp ever ingested: the degraded-mode
        #: reference for a side stream that has NEVER delivered (its
        #: watermark is undefined, so staleness is measured as how far
        #: book time has advanced since the session started)
        self._first_deep_ts = -1
        #: warehouse backfill hook (fmda_tpu.stream.journal): drained
        #: once per step so a spilled journal recovers even on idle
        #: ticks; None for plain warehouses (one attribute read per step)
        self._wh_drain = getattr(warehouse, "drain_journal", None)
        #: per-stage wall-clock accounting (SURVEY.md §5: the reference has
        #: no tracing; here every step exposes ingest/join/land/signal time)
        self.timer = StageTimer()
        #: optional fmda_tpu.obs registry: one end-to-end latency
        #: histogram per step (the lag/watermark/StageTimer detail is
        #: sampled scrape-time by obs.engine_families — zero cost here)
        self._obs_step_hist = (
            metrics.histogram("engine_step_seconds")
            if metrics is not None else None
        )
        #: span recorder (fmda_tpu.obs.trace) — the process-default
        #: tracer, captured once; disabled = one branch per step
        self._tracer = default_tracer()
        if checkpoint_path:
            tmp = f"{checkpoint_path}.tmp"
            if os.path.exists(tmp):
                # a kill mid-checkpoint() leaves the tmp behind
                # (os.replace never committed it); the durable file is
                # authoritative — a stale tmp must never be mistaken for
                # state or block the next atomic replace
                log.warning("removing leftover checkpoint tmp %s", tmp)
                os.remove(tmp)
            if os.path.exists(checkpoint_path):
                self.restore()

    # -- parsing -------------------------------------------------------------

    def _ingest(self) -> bool:
        """Poll every feed; returns True if anything new arrived."""
        import bisect

        fc = self.features
        polled_any = False
        raws = []
        wires = []  # in-band trace contexts, aligned with raws
        for rec in self._consumers[TOPIC_DEEP].poll():
            polled_any = True
            try:
                raw = _extract_deep_raw(rec.value, self._deep_keys)
            except (KeyError, ValueError, TypeError, AttributeError) as e:
                # AttributeError: a nested level that should be a dict is a
                # scalar — malformed producer output, not a crash
                self._bad_messages += 1
                log.warning("bad deep message at offset %d: %s", rec.offset, e)
                continue
            raws.append(raw)
            wires.append(rec.value.get("trace"))
        try:
            deep_events = _parse_deep_batch(raws)
            for event, wire in zip(deep_events, wires):
                event.trace = wire
        except (KeyError, ValueError, TypeError, AttributeError) as e:
            # one pathological message that survived extraction must not
            # abort the whole poll's batch — fall back to per-message
            # parsing and drop only the offender(s); the per-message
            # retry below counts each actual discard (loss-free here)
            log.warning(
                "batched deep parse failed (%s); retrying per-message", e)
            deep_events = []
            for raw, wire in zip(raws, wires):
                try:
                    parsed = _parse_deep_batch([raw])
                except (KeyError, ValueError, TypeError, AttributeError) as e2:
                    self._bad_messages += 1
                    log.warning("bad deep message %s dropped: %s", raw[0], e2)
                    continue
                for event in parsed:
                    event.trace = wire
                deep_events.extend(parsed)
        for event in deep_events:
            bisect.insort(self._pending_deep, event, key=lambda e: e.ts)
            self._max_deep_ts = max(self._max_deep_ts, event.ts)
            if self._first_deep_ts < 0:
                self._first_deep_ts = event.ts
            if self._core is not None:
                self._core.add_deep(event.ts)
        parsers = self._side_parsers
        for idx, (topic, buf) in enumerate(self._side_streams.items()):
            for rec in self._consumers[topic].poll():
                polled_any = True
                try:
                    event = parsers[topic](rec.value)
                except (KeyError, ValueError, TypeError, AttributeError) as e:
                    self._bad_messages += 1
                    log.warning(
                        "bad %s message at offset %d: %s", topic, rec.offset, e
                    )
                    continue
                buf.add(event)
                if self._core is not None:
                    self._core.add_side(idx, event.ts)
        return polled_any

    # -- degraded-mode joins (docs/chaos.md "Data-plane faults") -------------

    def degraded_streams(self) -> Tuple[str, ...]:
        """Side streams currently past the staleness deadline: their
        watermark trails the newest book tick by more than
        ``staleness_deadline_s`` (a stream that has never delivered is
        measured from the first book tick instead).  Empty when the
        feature is disabled or every feed is fresh — recovery is
        automatic the moment real events advance the watermark."""
        dl = self.staleness_deadline_s
        if dl is None or self._max_deep_ts < 0:
            return ()
        wm_s = self.features.watermark_s
        out = []
        for topic, buf in self._side_streams.items():
            wm = buf.watermark(wm_s)
            ref = wm if wm >= 0 else self._first_deep_ts - wm_s
            if self._max_deep_ts - ref > dl:
                out.append(topic)
        return tuple(out)

    def _apply_degraded_mode(self) -> None:
        """Mint ghost events so stale streams stop blocking the join:
        for every pending book tick with no real match in a degraded
        stream, a ghost carrying the stream's last-known payload (empty
        if it never delivered — fillna lands zeros) is inserted at the
        tick's own timestamp.  The normal join path (python or native)
        then emits the row; the consumed ghost is what increments
        ``degraded_rows``.  Ghosts never advance watermarks, so the
        stream re-joins cleanly the moment it recovers."""
        degraded = self.degraded_streams()
        if not degraded:
            return
        # _core is always None here: the constructor forces the python
        # scheduler when a staleness deadline is configured (the C++
        # core has no real-beats-ghost match rule)
        tol = self.features.join_tolerance_s
        for topic in degraded:
            buf = self._side_streams[topic]
            for deep_ev in self._pending_deep:
                if buf.match(deep_ev.ts, tol) is not None:
                    continue
                ghost = _Event(
                    deep_ev.ts, deep_ev.ts_str,
                    dict(buf.last_payload or {}), degraded=True)
                buf.add_ghost(ghost)

    def _count_degraded(self, ts_str: str, topics) -> None:
        for topic in topics:
            self._degraded_rows[topic] += 1
        if topics:
            self._degraded_ts.add(ts_str)

    # -- join ----------------------------------------------------------------

    def step(self) -> int:
        """One micro-batch: poll, join what's ready, land + signal.

        Returns the number of rows emitted this step.
        """
        if _CHAOS.enabled:
            # a kill window on this point is the "engine process died
            # mid-stream" fault: the step raises before touching any
            # state, exactly like a SIGKILL between steps — the driver
            # rebuilds from the checkpoint via restore()
            _CHAOS.check("engine.step")
        if self._obs_step_hist is None:
            return self._step()
        t0 = _time.perf_counter()
        try:
            return self._step()
        finally:
            self._obs_step_hist.observe(_time.perf_counter() - t0)

    def _step(self) -> int:
        fc = self.features
        tr = self._tracer
        tracing = tr.enabled  # one branch; ns stamps only when tracing
        t_step0_ns = now_ns() if tracing else 0
        if self._wh_drain is not None:
            # backfill a spilled write-ahead journal before this step's
            # rows land (ordering: journaled rows are older); a no-op
            # when the journal is empty, swallowed-failure when the
            # store is still down (the journal keeps the rows)
            self._wh_drain()
        with self.timer.stage("ingest"):
            polled_any = self._ingest()
        if self.staleness_deadline_s is not None and self._pending_deep:
            self._apply_degraded_mode()
        emitted_rows: List[Dict[str, float]] = []
        still_pending: List[_Event] = []
        #: Timestamp -> in-band trace context for rows emitted this step
        row_traces: Dict[str, str] = {}
        #: Timestamp -> side topics joined via ghost (counted only for
        #: rows that actually land — a crash-replayed duplicate row must
        #: not double-count degradation)
        row_degraded: Dict[str, List[str]] = {}

        with self.timer.stage("join"):
            if self._core is not None:
                emitted_rows, still_pending = self._join_native(
                    row_traces, row_degraded)
            else:
                for deep_ev in self._pending_deep:  # insertion-sorted by ts
                    matches: Dict[str, _Event] = {}
                    expired = False  # some stream can provably never match
                    waiting = False  # some stream might still deliver one
                    for topic, buf in self._side_streams.items():
                        m = buf.match(deep_ev.ts, fc.join_tolerance_s)
                        if m is not None:
                            matches[topic] = m
                        elif (
                            buf.watermark(fc.watermark_s)
                            > deep_ev.ts + fc.join_tolerance_s
                        ):
                            expired = True
                        else:
                            waiting = True
                    if expired:
                        # inner join: one unmatched stream past its horizon
                        # kills the row
                        self._dropped += 1
                        log.warning(
                            "dropping unjoinable book row at %s (no side "
                            "match within tolerance)", deep_ev.ts_str,
                        )
                    elif waiting:
                        still_pending.append(deep_ev)
                    else:  # all side streams matched
                        row: Dict[str, float] = {"Timestamp": deep_ev.ts_str}
                        row.update(deep_ev.payload)
                        for m in matches.values():
                            row.update(m.payload)
                        emitted_rows.append(row)
                        ghosted = [t for t, m in matches.items()
                                   if m.degraded]
                        if ghosted:
                            row_degraded[deep_ev.ts_str] = ghosted
                        if deep_ev.trace is not None:
                            row_traces[deep_ev.ts_str] = deep_ev.trace

        self._pending_deep = still_pending
        t_join_ns = now_ns() if tracing else 0

        # one output row per book tick (dropDuplicates intent,
        # spark_consumer.py:477): a tick whose timestamp already landed —
        # duplicate feed message, or crash-replay after offsets rewound —
        # is skipped, warehouse untouched
        if emitted_rows:
            fresh, seen_now = [], set()
            for r in emitted_rows:
                ts = r["Timestamp"]
                if ts in self._landed_ts or ts in seen_now:
                    continue
                # older than the bounded in-memory seed (deep replay):
                # the warehouse itself is the source of truth
                if (
                    self._landed_seed_floor is not None
                    and ts < self._landed_seed_floor
                    and self._warehouse_has(ts)
                ):
                    continue
                seen_now.add(ts)
                fresh.append(r)
            if len(fresh) < len(emitted_rows):
                log.info(
                    "skipping %d row(s) for already-landed tick(s) "
                    "(duplicate feed message or resume replay)",
                    len(emitted_rows) - len(fresh),
                )
            emitted_rows = fresh
        if emitted_rows:
            t_land0_ns = now_ns() if tracing else 0
            with self.timer.stage("land"):
                self.warehouse.insert_rows(emitted_rows)
            t_land1_ns = now_ns() if tracing else 0
            # mark landed / signal AFTER the write commits: no
            # sleep-and-retry race, no phantom dedupe entry on a failed
            # insert
            with self.timer.stage("signal"):
                for row in emitted_rows:
                    self._landed_ts.add(row["Timestamp"])
                    self._count_degraded(
                        row["Timestamp"],
                        row_degraded.get(row["Timestamp"], ()))
                    msg: Dict[str, object] = {"Timestamp": row["Timestamp"]}
                    if row_traces:
                        # propagate the book tick's trace context onto
                        # the signal, so serving stitches into its trace
                        wire = row_traces.get(row["Timestamp"])
                        if wire is not None:
                            msg["trace"] = wire
                    self.bus.publish(self.signal_topic, msg)
            self._emitted += len(emitted_rows)
            if tracing and row_traces:
                # per-landed-row stage attribution on the producer's
                # trace: the step's measured boundaries, one span triple
                # per traced row (join covers poll+match for the step
                # that emitted the row)
                t_sig1_ns = now_ns()
                for row in emitted_rows:
                    wire = row_traces.get(row["Timestamp"])
                    if wire is None:
                        continue
                    tr.add_span_wire(
                        wire, "join", "engine", t_step0_ns, t_join_ns)
                    tr.add_span_wire(
                        wire, "land", "warehouse", t_land0_ns, t_land1_ns)
                    tr.add_span_wire(
                        wire, "signal", "bus", t_land1_ns, t_sig1_ns)

        # bound buffer state by the global watermark; a degraded stream's
        # stalled watermark is excluded from the min (its book ticks flow
        # through on ghosts, so a long feed outage must not pin every
        # OTHER buffer's memory at the outage start)
        degraded = set(self.degraded_streams())
        horizon = min(
            (b.watermark(fc.watermark_s)
             for t, b in self._side_streams.items() if t not in degraded),
            default=(
                self._max_deep_ts - fc.watermark_s
                if degraded else -1
            ),
        )
        if horizon > 0:
            for buf in self._side_streams.values():
                buf.evict_before(horizon - fc.join_tolerance_s)
            # ticks more than one tolerance below the eviction boundary
            # can never be emitted again (no surviving side event can fall
            # in their [ts, ts+tol] match window), so their dedupe entries
            # are dead weight — prune occasionally to bound the set
            if len(self._landed_ts) > 8192:
                cutoff = horizon - 2 * fc.join_tolerance_s
                self._landed_ts = {
                    t for t in self._landed_ts if to_epoch(t) >= cutoff
                }
                self._degraded_ts = {
                    t for t in self._degraded_ts if to_epoch(t) >= cutoff
                }

        if self.checkpoint_path:
            if polled_any or emitted_rows:
                self._dirty = True
            self._steps_since_ckpt += 1
            # write every N steps while busy, or once when the stream
            # quiesces (nothing polled, nothing emitted) with state still
            # unpersisted — a fully idle poll loop writes nothing
            quiesced = not polled_any and not emitted_rows
            if self._dirty and (
                self._steps_since_ckpt >= self.checkpoint_every or quiesced
            ):
                self.checkpoint()
        return len(emitted_rows)

    def _find_side_event(self, topic: str, ts: int) -> _Event:
        """Payload of the side event the native scheduler matched (the
        first-added event at that timestamp, the C++ tie rule)."""
        buf = self._side_streams[topic]
        for e in buf.buckets.get(floor_epoch(ts, buf.floor_s), ()):
            if e.ts == ts:
                return e
        raise RuntimeError(
            f"native join matched {topic}@{ts} but the payload buffer has "
            "no such event (state divergence)"
        )

    def _join_native(
        self,
        row_traces: Optional[Dict[str, str]] = None,
        row_degraded: Optional[Dict[str, List[str]]] = None,
    ) -> Tuple[List[Dict[str, float]], List[_Event]]:
        """Join decisions from the C++ scheduler; payload assembly here."""
        from collections import defaultdict

        by_ts: Dict[int, List[_Event]] = defaultdict(list)
        for e in self._pending_deep:
            by_ts[e.ts].append(e)
        emitted, dropped = self._core.step()
        for ts in dropped:
            deep_ev = by_ts[ts].pop(0)
            self._dropped += 1
            log.warning(
                "dropping unjoinable book row at %s (no side match within "
                "tolerance)", deep_ev.ts_str,
            )
        rows: List[Dict[str, float]] = []
        for tup in emitted:
            deep_ev = by_ts[tup[0]].pop(0)
            row: Dict[str, float] = {"Timestamp": deep_ev.ts_str}
            row.update(deep_ev.payload)
            ghost_topics = []
            for i, topic in enumerate(self._stream_topics):
                m = self._find_side_event(topic, tup[1 + i])
                row.update(m.payload)
                if m.degraded:
                    ghost_topics.append(topic)
            rows.append(row)
            if ghost_topics and row_degraded is not None:
                row_degraded[deep_ev.ts_str] = ghost_topics
            if row_traces is not None and deep_ev.trace is not None:
                row_traces[deep_ev.ts_str] = deep_ev.trace
        still_pending = [
            e
            for e in self._pending_deep
            if any(kept is e for kept in by_ts[e.ts])
        ]
        return rows, still_pending

    # -- observability -------------------------------------------------------

    @property
    def stats(self) -> Dict[str, object]:
        """Counters plus the lag/watermark observability the reference
        sketched but never wired (spark_consumer.py:48-66's unused
        ``count_kafka_mssg`` offset counter):

        - ``consumer_lag``: per-topic published-but-unpolled message
          count (``bus.end_offset - consumer.offset``) — a growing lag
          means the engine step loop is falling behind its producers;
        - ``watermark_age_s``: per side stream, how far that stream's
          join watermark trails the newest ingested book tick (stream
          time, not wall time — replay-safe).  A large age means the
          feed has gone quiet while book ticks keep arriving, so joins
          are waiting on it; None until both sides have seen data.
        """
        lag = {
            topic: self.bus.end_offset(topic) - c.offset
            for topic, c in self._consumers.items()
        }
        ages: Dict[str, Optional[int]] = {}
        for topic, buf in self._side_streams.items():
            wm = buf.watermark(self.features.watermark_s)
            ages[topic] = (
                self._max_deep_ts - wm
                if wm >= 0 and self._max_deep_ts >= 0 else None
            )
        return {
            "emitted": self._emitted,
            "dropped": self._dropped,
            "bad_messages": self._bad_messages,
            "pending": len(self._pending_deep),
            "consumer_lag": lag,
            "watermark_age_s": ages,
            "degraded_rows": dict(self._degraded_rows),
            "degraded_streams": list(self.degraded_streams()),
            "checkpoint_corrupt": self._checkpoint_corrupt,
        }

    @property
    def degraded_row_timestamps(self) -> Tuple[str, ...]:
        """Timestamps of rows that landed with ghost features (bounded:
        pruned with the landed-dedupe set).  Chaos harnesses use this to
        exclude degraded rows from bit-identity comparisons; operators
        use it to audit what a feed outage actually touched."""
        return tuple(sorted(self._degraded_ts))

    # -- checkpoint / resume -------------------------------------------------

    def _warehouse_has(self, ts: str) -> bool:
        """Indexed membership probe for the deep-replay dedupe: prefer the
        warehouse's point ``has_timestamp`` (O(log n)); fall back to the
        positional lookup for sources that only expose that."""
        has = getattr(self.warehouse, "has_timestamp", None)
        if has is not None:
            return bool(has(ts))
        return self.warehouse.id_for_timestamp(ts) is not None

    def checkpoint(self) -> None:
        """Persist the engine's durable state: consumer offsets *plus* all
        polled-but-unjoined events (pending book rows and side-stream
        buffers).  Offsets alone — the reference's Spark checkpoint story
        (spark_consumer.py:500) — would silently lose any row still waiting
        for a join match across a restart."""

        def dump_event(e: _Event) -> dict:
            d = {"ts": e.ts, "ts_str": e.ts_str, "payload": e.payload}
            if e.trace is not None:  # keep checkpoints small when untraced
                d["trace"] = e.trace
            if e.degraded:
                d["degraded"] = True
            return d

        state = {
            "offsets": {t: c.offset for t, c in self._consumers.items()},
            "emitted": self._emitted,
            "dropped": self._dropped,
            "bad_messages": self._bad_messages,
            "max_deep_ts": self._max_deep_ts,
            "first_deep_ts": self._first_deep_ts,
            "degraded_rows": self._degraded_rows,
            "degraded_ts": sorted(self._degraded_ts),
            "pending_deep": [dump_event(e) for e in self._pending_deep],
            "buffers": {
                t: {
                    "max_ts": b.max_ts,
                    "last_payload": b.last_payload,
                    "events": [dump_event(e) for e in b.events],
                }
                for t, b in self._side_streams.items()
            },
        }
        tmp = f"{self.checkpoint_path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(state, fh)
        os.replace(tmp, self.checkpoint_path)
        self._steps_since_ckpt = 0
        self._dirty = False

    def restore(self) -> None:
        """Rebuild engine state from the checkpoint file.

        A corrupt or truncated checkpoint (a kill mid-write on a
        filesystem without atomic replace, disk trouble, a foreign
        writer) is survived as a *counted fresh start*: the bad file is
        moved aside to ``<path>.corrupt`` (forensics), the
        ``checkpoint_corrupt`` counter increments, and the engine keeps
        its fresh construction-time state — consumers replay from offset
        0 and the landed-tick dedupe makes the re-landing idempotent, so
        the cost is replay work, never duplicated rows.  The state dict
        is parsed *fully* before any of it is applied: a checkpoint that
        fails halfway through validation cannot leave the engine
        half-restored (offsets moved, buffers not).
        """

        def load_event(d: dict) -> _Event:
            return _Event(int(d["ts"]), d["ts_str"], dict(d["payload"]),
                          trace=d.get("trace"),
                          degraded=bool(d.get("degraded", False)))

        try:
            with open(self.checkpoint_path) as fh:
                state = json.load(fh)
            offsets = {t: int(o) for t, o in state["offsets"].items()}
            pending = [load_event(d)
                       for d in state.get("pending_deep", [])]
            buffers = {
                topic: (int(dump["max_ts"]), dump.get("last_payload"),
                        [load_event(d) for d in dump["events"]])
                for topic, dump in state.get("buffers", {}).items()
            }
        except (OSError, json.JSONDecodeError, KeyError, TypeError,
                ValueError, AttributeError) as e:
            self._checkpoint_corrupt += 1
            log.warning(
                "corrupt/truncated checkpoint %s (%s): counted fresh "
                "start — bus replay + landed-tick dedupe make this "
                "exact, not lossy", self.checkpoint_path, e)
            try:
                os.replace(self.checkpoint_path,
                           f"{self.checkpoint_path}.corrupt")
            except OSError:  # loss-free: the .corrupt copy is forensics only; the counted fresh start already happened
                pass  # already gone / unwritable dir: nothing to keep
            return

        for topic, offset in offsets.items():
            if topic in self._consumers:
                self._consumers[topic].seek(offset)
        self._emitted = state.get("emitted", 0)
        self._dropped = state.get("dropped", 0)
        self._bad_messages = state.get("bad_messages", 0)
        for topic, n in state.get("degraded_rows", {}).items():
            if topic in self._degraded_rows:
                self._degraded_rows[topic] = int(n)
        self._degraded_ts = set(state.get("degraded_ts", ()))
        self._pending_deep = pending
        # the join loop trusts sorted order; make the invariant
        # self-establishing for checkpoints from any writer
        self._pending_deep.sort(key=lambda e: e.ts)
        # stream-time "now" for watermark ages: persisted exactly since
        # round 5 (a checkpoint taken after all ticks joined would
        # otherwise restore with no age signal until the next tick);
        # older checkpoints fall back to the newest still-pending tick
        self._max_deep_ts = state.get("max_deep_ts", self._max_deep_ts)
        self._first_deep_ts = state.get(
            "first_deep_ts", self._first_deep_ts)
        if self._pending_deep:
            self._max_deep_ts = max(
                self._max_deep_ts, self._pending_deep[-1].ts)
        for topic, (max_ts, last_payload, events) in buffers.items():
            if topic in self._side_streams:
                buf = self._side_streams[topic]
                buf.buckets = {}
                for e in events:
                    if e.degraded:  # ghosts must not touch the watermark
                        buf.add_ghost(e)
                    else:
                        buf.add(e)
                # the watermark can be ahead of any buffered event (post-
                # eviction); restore it exactly.  Same for last_payload —
                # the newest real event may long be evicted (older
                # checkpoints lack the field: keep what add() derived).
                buf.max_ts = max_ts
                if last_payload is not None:
                    buf.last_payload = last_payload
        if self._core is not None:
            # mirror the restored state into a FRESH C++ scheduler (the
            # Python side fully reset above; appending to a used core
            # would duplicate its state)
            from fmda_tpu.stream.native_join import NativeJoinCore

            fc = self.features
            self._core = NativeJoinCore(
                fc.floor_s, fc.join_tolerance_s, fc.watermark_s,
                len(self._stream_topics),
            )
            for idx, (topic, buf) in enumerate(self._side_streams.items()):
                for e in buf.events:
                    self._core.add_side(idx, e.ts)
                if buf.max_ts >= 0:
                    self._core.force_max_ts(idx, buf.max_ts)
            for e in self._pending_deep:
                self._core.add_deep(e.ts)
