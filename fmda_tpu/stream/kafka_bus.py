"""Kafka adapter: run the framework against real brokers (deployment parity).

The framework's default transports are the in-process Python bus and the
native C++ ring bus; this adapter lets the same engine/serving code run
against external Kafka brokers like the reference's deployment
(config.py:15, README.md:186-292).  Gated on ``kafka-python`` being
installed — the constructor raises a clear error otherwise, so air-gapped
environments never pay for the import.
"""

from __future__ import annotations

import logging
from typing import Iterable, List, Optional, Sequence

from fmda_tpu.obs.trace import default_tracer, stamp_message, stamp_messages
from fmda_tpu.stream import codec
from fmda_tpu.stream.bus import Consumer, Record

log = logging.getLogger("fmda_tpu.stream")

_TRACER = default_tracer()


class KafkaBus:
    """MessageBus over kafka-python producers/consumers.

    Offsets are Kafka's native partition-0 offsets, matching the
    reference's single-partition topic usage (predict.py:26-27).
    """

    def __init__(
        self,
        topics: Iterable[str],
        servers: Sequence[str] = ("localhost:9092",),
    ) -> None:
        try:
            from kafka import KafkaConsumer, KafkaProducer, TopicPartition  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "KafkaBus needs the 'kafka-python' package; use "
                "InProcessBus or NativeBus otherwise"
            ) from e
        self._TopicPartition = TopicPartition
        self._KafkaConsumer = KafkaConsumer
        self._topics = tuple(topics)
        self._servers = list(servers)
        # Kafka stays JSON on the wire (the broker ecosystem's tooling
        # expects text); raw arrays in bus values lower to the codec's
        # tagged-base64 form and decode back to arrays on read, so the
        # value model matches the other backends
        self._producer = KafkaProducer(
            bootstrap_servers=self._servers,
            value_serializer=codec.dumps,
        )
        # one metadata consumer reused for offset queries
        self._meta = KafkaConsumer(
            bootstrap_servers=self._servers, group_id=None,
            enable_auto_commit=False,
        )

    def _check(self, topic: str) -> None:
        if topic not in self._topics:
            raise KeyError(
                f"unknown topic {topic!r}; configured: {sorted(self._topics)}"
            )

    def add_topic(self, topic: str) -> None:
        """Admit a topic after construction (idempotent).  Kafka brokers
        auto-create topics on first produce (the reference deployment
        relies on it), so this only widens the adapter's configured set
        — the same dynamic-membership contract NativeBus/InProcessBus
        implement by actually allocating a log."""
        if topic not in self._topics:
            self._topics = self._topics + (topic,)

    def publish(self, topic: str, value: dict) -> int:
        self._check(topic)
        if _TRACER.enabled:  # in-band trace context (fmda_tpu.obs.trace)
            value = stamp_message(value)
        future = self._producer.send(topic, value=value)
        meta = future.get(timeout=30)
        return meta.offset

    def publish_many(self, topic: str, values) -> List[int]:
        """Batched publish: all sends enter the producer's buffer before
        any ack is awaited, so the batch rides the broker round-trip
        once instead of once per record.  Messages without their own
        ``trace`` field inherit the active trace context."""
        self._check(topic)
        if _TRACER.enabled:
            values = stamp_messages(values)
        futures = [self._producer.send(topic, value=v) for v in values]
        return [f.get(timeout=30).offset for f in futures]

    def read(
        self, topic: str, offset: int, max_records: Optional[int] = None
    ) -> List[Record]:
        self._check(topic)
        tp = self._TopicPartition(topic, 0)
        consumer = self._KafkaConsumer(
            bootstrap_servers=self._servers, group_id=None,
            enable_auto_commit=False,
            value_deserializer=codec.loads,
        )
        try:
            consumer.assign([tp])
            consumer.seek(tp, max(offset, 0))
            out: List[Record] = []
            while max_records is None or len(out) < max_records:
                polled = consumer.poll(timeout_ms=500)
                records = polled.get(tp, [])
                if not records:
                    break
                for r in records:
                    out.append(Record(topic, r.offset, r.value))
                    if max_records is not None and len(out) >= max_records:
                        break
            return out
        finally:
            consumer.close()

    def end_offset(self, topic: str) -> int:
        self._check(topic)
        tp = self._TopicPartition(topic, 0)
        return self._meta.end_offsets([tp])[tp]

    def topics(self) -> Sequence[str]:
        return self._topics

    def consumer(self, topic: str, *, from_end: bool = False) -> Consumer:
        c = Consumer(self, topic)
        if from_end:
            c.seek_to_end()
        return c
