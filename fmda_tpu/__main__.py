from fmda_tpu.cli import main

raise SystemExit(main())
