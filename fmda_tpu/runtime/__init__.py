"""fmda_tpu.runtime — dynamic micro-batching serving runtime.

Multiplexes thousands of independent ticker sessions onto the batched
carried-state streaming kernels: a slot-pool session manager packs N
carried states into one state tree (:mod:`~fmda_tpu.runtime.session_pool`),
a deadline-aware micro-batcher coalesces tick requests into a few
compiled-once padded shapes (:mod:`~fmda_tpu.runtime.batcher`), and an
admission-controlled gateway with bounded queueing and counted load
shedding serves results back per-session over the framework's MessageBus
(:mod:`~fmda_tpu.runtime.gateway`).  ``python -m fmda_tpu serve-fleet``
runs the whole stack against a synthetic multi-ticker load
(:mod:`~fmda_tpu.runtime.loadgen`).  Architecture: docs/runtime.md.
"""

from fmda_tpu.runtime.batcher import BatcherConfig, MicroBatcher, Tick
from fmda_tpu.runtime.gateway import FleetGateway, FleetResult
from fmda_tpu.runtime.loadgen import (
    FleetLoadConfig,
    PredictorLoadConfig,
    run_fleet_load,
    run_predictor_load,
)
from fmda_tpu.runtime.metrics import LatencyHistogram, RuntimeMetrics
from fmda_tpu.runtime.predictor_pool import PredictorGateway, PredictorPool
from fmda_tpu.runtime.session_pool import (
    PoolExhausted,
    SessionHandle,
    SessionPool,
    StaleSessionError,
)

__all__ = [
    "BatcherConfig",
    "MicroBatcher",
    "Tick",
    "FleetGateway",
    "FleetResult",
    "FleetLoadConfig",
    "PredictorLoadConfig",
    "run_fleet_load",
    "run_predictor_load",
    "LatencyHistogram",
    "RuntimeMetrics",
    "PredictorGateway",
    "PredictorPool",
    "PoolExhausted",
    "SessionHandle",
    "SessionPool",
    "StaleSessionError",
]
