"""fmda_tpu.runtime — dynamic micro-batching serving runtime.

Multiplexes thousands of independent ticker sessions onto the batched
carried-state streaming kernels: a slot-pool session manager packs N
carried states into one state tree (:mod:`~fmda_tpu.runtime.session_pool`),
a deadline-aware micro-batcher coalesces tick requests into a few
compiled-once padded shapes (:mod:`~fmda_tpu.runtime.batcher`), and an
admission-controlled gateway with bounded queueing and counted load
shedding serves results back per-session over the framework's MessageBus
(:mod:`~fmda_tpu.runtime.gateway`).  ``python -m fmda_tpu serve-fleet``
runs the whole stack against a synthetic multi-ticker load
(:mod:`~fmda_tpu.runtime.loadgen`).  Architecture: docs/runtime.md.

Exports resolve lazily (PEP 562): the session pool pulls in jax at
import, and the multi-host router (:mod:`fmda_tpu.fleet`) must be able
to import the jax-free submodules (``runtime.metrics``) on a bus-only
host without dragging the whole accelerator stack in.
"""

#: public name -> defining submodule; resolved on first attribute access
_EXPORTS = {
    "BatcherConfig": "fmda_tpu.runtime.batcher",
    "MicroBatcher": "fmda_tpu.runtime.batcher",
    "Tick": "fmda_tpu.runtime.batcher",
    "FleetGateway": "fmda_tpu.runtime.gateway",
    "FleetResult": "fmda_tpu.runtime.gateway",
    "FleetLoadConfig": "fmda_tpu.runtime.loadgen",
    "PredictorLoadConfig": "fmda_tpu.runtime.loadgen",
    "run_fleet_load": "fmda_tpu.runtime.loadgen",
    "run_predictor_load": "fmda_tpu.runtime.loadgen",
    "LatencyHistogram": "fmda_tpu.runtime.metrics",
    "RuntimeMetrics": "fmda_tpu.runtime.metrics",
    "PredictorGateway": "fmda_tpu.runtime.predictor_pool",
    "PredictorPool": "fmda_tpu.runtime.predictor_pool",
    "PoolExhausted": "fmda_tpu.runtime.session_pool",
    "SessionHandle": "fmda_tpu.runtime.session_pool",
    "SessionPool": "fmda_tpu.runtime.session_pool",
    "StaleSessionError": "fmda_tpu.runtime.session_pool",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    module_name = _EXPORTS.get(name)
    if module_name is None:
        raise AttributeError(
            f"module 'fmda_tpu.runtime' has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(module_name), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
