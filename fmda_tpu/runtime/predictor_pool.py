"""Batched Predictor serving: the window-re-scan path on the fleet runtime.

PR 1 multiplexed the unidirectional carried-state sessions; this module
closes the ROADMAP follow-up by multiplexing the flagship *bidirectional*
(and attn) serving path — the window-re-scan
:class:`~fmda_tpu.serve.predictor.Predictor` — onto the same
micro-batching machinery.  The Predictor is stateless per request, so
batcher reuse is direct: no slot pool, no carried state, just bucketed
``(B, window, F)`` forwards compiled once per bucket.

Two pieces:

- :class:`PredictorPool` — the compiled batched forward.  It jits the
  *same* :func:`~fmda_tpu.serve.predictor.make_batched_forward` program
  the solo Predictor runs (normalization folded in, norm stats as jit
  arguments), so a bucket-1 flush is **bit-identical** to the solo path
  — the contract ``tests/test_predictor_fleet.py`` asserts.  One compile
  per bucket (:attr:`PredictorPool.compile_count` is the proof hook).
  With ``use_ring=True`` it additionally keeps a **device-resident
  window ring** of the stream's newest ``window`` feature rows: when a
  flush's signals continue the stream (consecutive row positions), only
  the ``B`` *new* rows cross the host boundary and a jitted gather
  builds the ``(B, window, F)`` windows on device — O(B·F) host bytes
  per flush instead of O(B·window·F).  The windows feed the exact same
  compiled forward, so ring flushes stay bit-identical to fetch flushes;
  a gap (skipped/missing signal, out-of-order landing) falls back to the
  batched warehouse gather and re-seeds the ring, counted
  (``ring_hits``/``ring_misses``).

- :class:`PredictorGateway` — the serving loop: consume
  ``predict_timestamp`` signals (stale filter, exactly the solo
  Predictor's semantics), coalesce them through the existing
  :class:`~fmda_tpu.runtime.batcher.MicroBatcher`, replace B per-signal
  SQL lookups + window fetches with ONE
  :meth:`~fmda_tpu.stream.warehouse.Warehouse.ids_for_timestamps` +
  :meth:`~fmda_tpu.stream.warehouse.Warehouse.fetch_windows` per flush,
  dispatch the batched forward asynchronously through the one-deep
  in-flight pipeline (``pipeline_depth=0`` = the bit-identical serial
  A/B reference), and publish every flush with one ``publish_many``.
  Missing-row / short-history signals are skipped with the solo path's
  warnings, plus counters (``missing_rows`` / ``short_history``).
  Per-signal trace spans (queued/gather/dispatch/device/publish) tile
  the tick's journey; a signal arriving with in-band trace context gets
  them stitched under a ``serve`` span on *its* trace (the engine →
  serve journey), a bare signal gets its own sampled root.

:class:`~fmda_tpu.runtime.metrics.RuntimeMetrics` instruments the whole
path (the new ``gather`` stage prices the batched warehouse read);
``Observability.track_predictor_fleet`` exports it under the
``predictor_`` prefix.  Architecture: docs/runtime.md "Batched
Predictor path".
"""

from __future__ import annotations

import datetime as _dt
import logging
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fmda_tpu.config import (
    DEFAULT_QUEUE_BOUND,
    TARGET_COLUMNS,
    TOPIC_PREDICT_TIMESTAMP,
    TOPIC_PREDICTION,
    ModelConfig,
)
from fmda_tpu.data.normalize import NormParams
from fmda_tpu.obs.device import tracked_jit
from fmda_tpu.obs.trace import TraceRef, default_tracer, now_ns, parse_wire
from fmda_tpu.runtime.batcher import BatcherConfig, MicroBatcher, Tick
from fmda_tpu.runtime.metrics import RuntimeMetrics
from fmda_tpu.runtime.session_pool import SessionHandle
from fmda_tpu.serve.predictor import (
    Prediction,
    labels_over_threshold,
    make_batched_forward,
    prediction_message,
)
from fmda_tpu.utils.timeutils import get_timezone, parse_ts

log = logging.getLogger("fmda_tpu.runtime")

#: Queued predictor requests carry no feature row (the window is gathered
#: per flush, not per submit) — one shared placeholder, never read.
_NO_ROW = np.empty(0, np.float32)


class PredictorPool:
    """The compiled batched window-re-scan forward (+ optional device
    window ring).

    Stateless per request — "pool" here pools *compilations*, not
    sessions: one jitted ``(B, window, F) -> (B, n_classes)`` program
    per micro-batch bucket, replayed forever.  The program is the solo
    Predictor's own (:func:`make_batched_forward`), so bucket-1 flushes
    are bit-identical to solo serving.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        params,
        norm_params: NormParams,
        *,
        window: int,
        use_ring: bool = False,
    ) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.cfg = model_cfg
        self.window = window
        self.n_features = int(np.asarray(norm_params.x_min).shape[0])
        self._params = params
        self._x_min = jnp.asarray(norm_params.x_min)
        self._x_range = jnp.asarray(norm_params.x_max - norm_params.x_min)
        # the ONE shared forward (serve/predictor.py) — jitting it here
        # and in the solo Predictor yields the same program at B=1
        self._forward = tracked_jit(
            make_batched_forward(model_cfg),
            name="predictor_forward",
            signature_of=lambda *a, **k: ("B", int(a[3].shape[0])))
        # fallback compile accounting (batch size is the only varying
        # shape in the forward signature; see SessionPool.compile_count)
        self._batch_sizes_seen: set = set()

        #: Device-resident window ring (``use_ring``): the newest
        #: ``window`` feature rows of the served stream, living on device
        #: between flushes so consecutive signals re-send only new rows.
        self.use_ring = use_ring
        self._ring = None  # (window, F) device array once seeded
        #: warehouse position (1-based) of the ring's newest row; 0 =
        #: unseeded (the next flush takes the fetch path and seeds it)
        self.ring_pos = 0
        w = window

        def ring_gather(ring, rows, n_valid):
            """Windows for ``n_valid`` consecutive new rows, on device.

            ``ring`` (window, F) holds the stream's last rows; ``rows``
            (bucket, F) appends the new ones (lanes past ``n_valid`` are
            padding).  Lane i's window is rows ``i+1 .. i+window`` of the
            concatenation — garbage for padded lanes, sliced off by the
            caller.  The new ring is the concatenation's last ``window``
            *real* rows (dynamic slice at ``n_valid``, so padding never
            enters the carried state)."""
            buf = jnp.concatenate([ring, rows], axis=0)
            bucket = rows.shape[0]
            idx = (jnp.arange(1, w + 1)[None, :]
                   + jnp.arange(bucket)[:, None])
            x = buf[idx]  # (bucket, window, F)
            new_ring = jax.lax.dynamic_slice_in_dim(buf, n_valid, w, axis=0)
            return x, new_ring

        self._ring_gather = tracked_jit(
            ring_gather,
            name="predictor_ring_gather",
            signature_of=lambda *a, **k: ("B", int(a[1].shape[0])))

    @property
    def compile_count(self) -> int:
        """Distinct compiled forward programs — one per bucket size ever
        dispatched (the no-recompile-on-the-tick-path proof hook; the
        ring's gather programs are counted separately and never affect
        this).  Probes jax's jit cache when the hook exists."""
        size = self._forward.cache_size()
        if size is not None:
            return size
        return len(self._batch_sizes_seen)

    def mark_warm(self) -> None:
        """Declare precompile over: further forward/gather compiles are
        unexpected recompiles (counted, evented, SLO-alertable)."""
        self._forward.mark_warm()
        self._ring_gather.mark_warm()

    @property
    def recompiles_after_warmup(self) -> int:
        return (self._forward.unexpected_recompiles
                + self._ring_gather.unexpected_recompiles)

    def live_tree(self):
        """The pool's live device tree (params + norms + window ring)
        — the owner callback for the device memory monitor."""
        return (self._params, self._x_min, self._x_range, self._ring)

    # -- the hot path -------------------------------------------------------

    def forward_device(self, x):
        """One bucketed flush, asynchronously: ``x`` (B, window, F) →
        the (B, n_classes) sigmoid probabilities as a **device** array
        (no host transfer; the gateway forces it one flush late).
        Padded lanes compute garbage the caller slices off."""
        self._batch_sizes_seen.add(int(x.shape[0]))
        return self._forward(
            self._params, self._x_min, self._x_range, jnp.asarray(x))

    def forward(self, x) -> np.ndarray:
        """Blocking :meth:`forward_device` (direct callers and tests)."""
        return np.asarray(self.forward_device(x))

    # -- the device window ring ---------------------------------------------

    def seed_ring(self, last_window: np.ndarray, row_id: int) -> None:
        """(Re-)seed the ring from a host-fetched window ending at
        warehouse position ``row_id`` — the fetch path does this on every
        flush so the *next* consecutive flush can take the ring path."""
        self._ring = jnp.asarray(last_window, jnp.float32)
        self.ring_pos = int(row_id)

    def ring_forward_device(
        self, rows: np.ndarray, n_valid: int, last_row_id: int
    ):
        """Ring-path flush: append ``n_valid`` consecutive new rows
        (``rows`` is bucket-padded, padding zeroed), build the windows on
        device, and run the SAME compiled forward the fetch path runs —
        identical program, identical row values, bit-identical output."""
        if self._ring is None:
            raise RuntimeError("ring not seeded; take the fetch path first")
        x, self._ring = self._ring_gather(
            self._ring, jnp.asarray(rows, jnp.float32),
            np.int32(n_valid))
        self.ring_pos = int(last_row_id)
        return self.forward_device(x)


@dataclass
class _InFlight:
    """A dispatched-but-unconsumed flush: the device handle to its
    probabilities plus what ``_complete`` needs to publish them."""

    live: List[Tick]
    probs_dev: object  # (bucket, n_classes) device array
    bucket: int
    #: perf_counter_ns stamps of the dispatch window (0 when untraced)
    t_gather_ns: int = 0
    t_dispatch_ns: int = 0
    t_dispatched_ns: int = 0


class PredictorGateway:
    """Multiplexes predict-timestamp signals onto bucketed batched
    forwards — the window-re-scan Predictor as a fleet citizen."""

    #: Log every Nth shed (counter is the source of truth).
    SHED_LOG_EVERY = 1000

    def __init__(
        self,
        pool: PredictorPool,
        bus,
        warehouse,
        *,
        batcher_config: Optional[BatcherConfig] = None,
        queue_bound: int = DEFAULT_QUEUE_BOUND,
        metrics: Optional[RuntimeMetrics] = None,
        clock: Callable[[], float] = time.monotonic,
        signal_topic: str = TOPIC_PREDICT_TIMESTAMP,
        prediction_topic: str = TOPIC_PREDICTION,
        threshold: float = 0.5,
        y_fields: Tuple[str, ...] = TARGET_COLUMNS,
        from_end: bool = True,
        max_staleness_s: Optional[int] = 4 * 60,
        timezone: str = "US/Eastern",
        now_fn: Optional[Callable[[], _dt.datetime]] = None,
        pipeline_depth: int = 1,
    ) -> None:
        if queue_bound < 1:
            raise ValueError(f"queue_bound must be >= 1, got {queue_bound}")
        if pipeline_depth not in (0, 1):
            raise ValueError(
                f"pipeline_depth must be 0 (serial) or 1 (one-deep "
                f"overlap), got {pipeline_depth}")
        if bus is not None and prediction_topic not in bus.topics():
            # fail at construction, not mid-flush (same contract as the
            # fleet gateway: a publish KeyError after dispatch would
            # lose the whole flush's results)
            raise ValueError(
                f"bus has no topic {prediction_topic!r} (configured: "
                f"{sorted(bus.topics())})")
        self.pool = pool
        self.bus = bus
        self.warehouse = warehouse
        self.queue_bound = queue_bound
        self.metrics = metrics or RuntimeMetrics()
        self.clock = clock
        self.prediction_topic = prediction_topic
        self.threshold = threshold
        self.y_fields = tuple(y_fields)
        self.max_staleness_s = max_staleness_s
        #: 1 = one-deep overlap pipeline; 0 = strictly serial flushes
        #: (the bit-identical A/B reference, CLI ``--serial``).
        self.pipeline_depth = pipeline_depth
        # staleness clock: exchange-local, exactly the solo Predictor's
        # (signal timestamps are naive exchange-local strings)
        if now_fn is None:
            tz = get_timezone(timezone)

            def now_fn():
                return _dt.datetime.now(tz).replace(tzinfo=None)

        self.now_fn = now_fn
        self._consumer = (
            bus.consumer(signal_topic, from_end=from_end)
            if bus is not None else None)
        self.batcher = MicroBatcher(batcher_config, clock=clock)
        # signals are stateless one-shots: every request is its own
        # "session" for the batcher's per-session bookkeeping, keyed by
        # a monotonically increasing synthetic slot (no two requests
        # ever collide, so every flush takes the lockstep fast path)
        self._next_slot = 0
        # double-buffered per-bucket staging, one (bucket, window, F)
        # window buffer + one (bucket, F) ring-row buffer per parity
        # (jax may alias host numpy on CPU; a one-deep pipeline has at
        # most one prior dispatch still reading its staging)
        self._staging = {}
        self._staging_idx = {}
        self._publish_many = (
            getattr(bus, "publish_many", None) if bus is not None else None)
        #: the cross-pump in-flight flush (None when pipeline_depth == 0)
        self._inflight: Optional[_InFlight] = None
        self._tracer = default_tracer()
        self._ids_for = getattr(warehouse, "ids_for_timestamps", None)
        self._fetch_windows = getattr(warehouse, "fetch_windows", None)

    # -- the request path ---------------------------------------------------

    def _is_stale(self, ts_str: str) -> bool:
        if self.max_staleness_s is None:
            return False
        age = (self.now_fn() - parse_ts(ts_str)).total_seconds()
        return age > self.max_staleness_s

    def submit(self, ts_str: str, wire: Optional[str] = None) -> None:
        """Enqueue a predict-timestamp signal.  ``wire`` is the signal's
        in-band trace context, carried onto the prediction message and
        used as the span parent.  Overload sheds the oldest queued
        signal (counted + heartbeat-logged) — stale market signals are
        the cheapest thing to lose."""
        while len(self.batcher) >= self.queue_bound:
            shed = self.batcher.shed_oldest()
            self.metrics.count("shed_oldest")
            n = self.metrics.counters["shed_oldest"]
            if n == 1 or n % self.SHED_LOG_EVERY == 0:
                log.warning(
                    "signal queue full (bound=%d): shed oldest (%s); "
                    "%d shed so far",
                    self.queue_bound, shed.handle.session_id, n)
        ref = None
        if wire is None:
            # bare signal: this tick may become its own sampled root
            ref = self._tracer.maybe_trace()
        elif self._tracer.enabled:
            ctx = parse_wire(wire)
            if ctx is not None:
                # ride the signal's journey: serve spans parent on the
                # publisher's span, t0 stamps the serve stage start
                ref = TraceRef(ctx[0], ctx[1], now_ns())
        slot, self._next_slot = self._next_slot, self._next_slot + 1
        self.batcher.add(Tick(
            handle=SessionHandle(ts_str, slot, 0), row=_NO_ROW,
            t_enqueue=self.clock(), trace=ref, wire=wire))
        self.metrics.gauge("queue_depth", len(self.batcher))

    @property
    def saturated(self) -> bool:
        """Backpressure signal: the next submit will shed."""
        return len(self.batcher) >= self.queue_bound

    # -- the serving loop ---------------------------------------------------

    def poll(self) -> List[Prediction]:
        """Serve every new signal on the bus: stale-filter (solo
        semantics, plus a ``stale_signals`` counter), batch, flush.
        Returns the predictions made — the same contract as the solo
        :meth:`Predictor.poll`, so ``Application.run_tick`` drives
        either interchangeably."""
        for rec in self._consumer.poll():
            ts = rec.value.get("Timestamp")
            if not ts:
                log.warning(
                    "signal without Timestamp at offset %d", rec.offset)
                continue
            if self._is_stale(ts):
                log.warning("dropping stale signal %s", ts)
                self.metrics.count("stale_signals")
                continue
            self.submit(ts, wire=rec.value.get("trace"))
        return self.pump(force=True)

    def pump(self, *, force: bool = False) -> List[Prediction]:
        """Flush ready micro-batches (all pending when ``force``).
        Consecutive flushes run through the one-deep overlap pipeline —
        flush k+1's gather + dispatch run while flush k's probabilities
        cross the host boundary and publish — persisting across calls
        exactly like the fleet gateway's (``pump`` returns predictions
        *completed* this call; ``force`` completes everything)."""
        results: List[Prediction] = []
        dispatched_any = False
        try:
            while True:
                if force:
                    if not len(self.batcher):
                        break
                elif not self.batcher.ready(self.clock()):
                    break
                ticks = self.batcher.take_batch()
                if not ticks:
                    break
                nxt = self._dispatch(ticks)
                if nxt is not None:
                    dispatched_any = True
                # hand the previous flush off BEFORE completing it, so a
                # completion failure can never strand the new dispatch
                prev, self._inflight = self._inflight, nxt
                if prev is not None:
                    if nxt is not None:
                        self.metrics.count("overlapped_flushes")
                    results.extend(self._complete_counted(prev))
                if self.pipeline_depth == 0 and self._inflight is not None:
                    prev, self._inflight = self._inflight, None
                    results.extend(self._complete_counted(prev))
            if self._inflight is not None and (force or not dispatched_any):
                prev, self._inflight = self._inflight, None
                results.extend(self._complete_counted(prev))
        except BaseException:
            # an in-flight flush's results must still publish on unwind
            # (and a second failure is counted, never silent)
            if self._inflight is not None:
                prev, self._inflight = self._inflight, None
                try:
                    self._complete_counted(prev)
                except Exception:  # noqa: BLE001 — loss-free: double
                    # fault while unwinding; the flush's signals were
                    # counted lost by _complete_counted, and the outer
                    # handler re-raises the original failure
                    log.exception(
                        "in-flight flush lost while unwinding pump failure")
            raise
        finally:
            self.metrics.gauge("queue_depth", len(self.batcher))
        return results

    def drain(self) -> List[Prediction]:
        """Serve everything still queued (shutdown / end of load)."""
        return self.pump(force=True)

    def _complete_counted(self, inflight: _InFlight) -> List[Prediction]:
        try:
            return self._complete(inflight)
        except Exception:
            self.metrics.count("flush_results_lost", len(inflight.live))
            raise

    # -- flush stages -------------------------------------------------------

    def _staging_for(self, bucket: int):
        """The next (windows, rows) staging pair for ``bucket`` —
        pre-allocated once, alternating between two parities."""
        bufs = self._staging.get(bucket)
        if bufs is None:
            w, f = self.pool.window, self.pool.n_features
            bufs = [
                (np.zeros((bucket, w, f), np.float32),
                 np.zeros((bucket, f), np.float32))
                for _ in range(2)
            ]
            self._staging[bucket] = bufs
            self._staging_idx[bucket] = 0
        idx = self._staging_idx[bucket]
        self._staging_idx[bucket] = 1 - idx
        return bufs[idx]

    def _lookup_ids(self, ts_list: List[str]) -> List[Optional[int]]:
        if self._ids_for is not None:
            return self._ids_for(ts_list)  # ONE query for the flush
        # warehouse without the batched API (custom FeatureSource): the
        # per-signal path still works, just without the batching win
        return [self.warehouse.id_for_timestamp(ts) for ts in ts_list]

    def _gather_ids(
        self, ticks: List[Tick], window: int
    ) -> Tuple[List[Tick], List[int]]:
        """Batched id lookup + the solo path's skip semantics: unknown
        timestamps and short-history rows are warned and counted, never
        fatal to the flush's other signals."""
        ts_list = [t.handle.session_id for t in ticks]
        row_ids = self._lookup_ids(ts_list)
        live: List[Tick] = []
        live_ids: List[int] = []
        for tick, rid in zip(ticks, row_ids):
            if rid is None:
                log.warning("no warehouse row for signal %s",
                            tick.handle.session_id)
                self.metrics.count("missing_rows")
            elif rid < window:
                log.warning(
                    "row %d at %s has <%d rows of history; skipping",
                    rid, tick.handle.session_id, window)
                self.metrics.count("short_history")
            else:
                live.append(tick)
                live_ids.append(rid)
        return live, live_ids

    def _gather_rows(
        self, live_ids: List[int], windows_staging, rows_staging,
        window: int,
    ) -> bool:
        """Fill the flush's staging: the ring path (the flush continues
        the stream — consecutive positions picking up right after the
        ring's newest row; fetch only the B new rows) or the batched
        full-window gather (which (re-)seeds the ring).  Returns whether
        the ring path was taken."""
        n = len(live_ids)
        ring_hit = (
            self.pool.use_ring
            and self.pool.ring_pos == live_ids[0] - 1
            and live_ids == list(range(live_ids[0], live_ids[0] + n))
        )
        if ring_hit:
            rows_staging[:n] = self.warehouse.fetch(
                range(live_ids[0], live_ids[-1] + 1))
            rows_staging[n:] = 0.0
            self.metrics.count("ring_hits")
        else:
            windows = (
                self._fetch_windows(live_ids, window)
                if self._fetch_windows is not None
                else np.stack([
                    self.warehouse.fetch(range(rid - window + 1, rid + 1))
                    for rid in live_ids
                ]))
            windows_staging[:n] = windows
            if self.pool.use_ring:
                self.pool.seed_ring(windows[-1], live_ids[-1])
                self.metrics.count("ring_misses")
        return ring_hit

    def _dispatch(self, ticks: List[Tick]) -> Optional[_InFlight]:
        """Stage 1 of a flush: batched id lookup + window gather (or the
        device-ring append), then the async bucketed forward.  Returns
        None when every signal was skipped (missing row/short history —
        the solo path's warnings, plus counters) or when the warehouse
        read failed (the batched analogue of the solo poll()'s
        per-signal error isolation: a transient backend error drops the
        flush's signals — counted, never silent — and the serving loop
        keeps running)."""
        tracing = self._tracer.enabled
        t_gather = self.clock()
        t_gather_ns = now_ns() if tracing else 0
        window = self.pool.window
        with self.metrics.timer.stage("gather"):
            try:
                live, live_ids = self._gather_ids(ticks, window)
                if not live:
                    return None
                bucket = self.batcher.bucket_for(len(live))
                windows_staging, rows_staging = self._staging_for(bucket)
                n = len(live)
                ring_hit = self._gather_rows(
                    live_ids, windows_staging, rows_staging, window)
            except Exception:  # noqa: BLE001 — a warehouse failure
                # mid-flush must not abort the poll/pump loop (the solo
                # Predictor's per-signal isolation, per flush here: a
                # batched read cannot name the failing signal)
                self.metrics.count("gather_errors")
                self.metrics.count("signals_dropped_on_error", len(ticks))
                log.exception(
                    "batched warehouse gather failed; dropping %d "
                    "queued signal(s) and continuing", len(ticks))
                return None
        t_dispatch = self.clock()
        t_dispatch_ns = now_ns() if tracing else 0
        with self.metrics.timer.stage("dispatch"):
            if ring_hit:
                probs_dev = self.pool.ring_forward_device(
                    rows_staging, n, live_ids[-1])
            else:
                probs_dev = self.pool.forward_device(windows_staging)
        t_dispatched = self.clock()
        t_dispatched_ns = now_ns() if tracing else 0

        m = self.metrics
        m.count("flushes")
        m.count(f"flushes_bucket_{bucket}")
        m.count("padded_lanes", bucket - n)
        m.observe("gather", t_dispatch - t_gather)
        m.observe("dispatch", t_dispatched - t_dispatch)
        for tick in live:
            m.observe("enqueue_to_dispatch", t_gather - tick.t_enqueue)
        return _InFlight(
            live=live, probs_dev=probs_dev, bucket=bucket,
            t_gather_ns=t_gather_ns, t_dispatch_ns=t_dispatch_ns,
            t_dispatched_ns=t_dispatched_ns)

    def _complete(self, inflight: _InFlight) -> List[Prediction]:
        """Stage 2: force the host transfer, threshold labels, publish
        the whole flush in one batched bus call."""
        tracing = self._tracer.enabled
        t_synced = self.clock()
        with self.metrics.timer.stage("device"):
            probs = np.asarray(inflight.probs_dev)  # blocks: host array
        t_device = self.clock()
        t_device_ns = now_ns() if tracing else 0

        results: List[Prediction] = []
        messages = [] if self.bus is not None else None
        t_pub0_ns = 0
        with self.metrics.timer.stage("publish"):
            for i, tick in enumerate(inflight.live):
                p = probs[i]
                idx, labels = labels_over_threshold(
                    p, self.threshold, self.y_fields)
                pred = Prediction(
                    timestamp=tick.handle.session_id,
                    probabilities=tuple(float(v) for v in p),
                    threshold=self.threshold,
                    labels=labels,
                    label_indices=idx,
                )
                results.append(pred)
                if messages is not None:
                    # in-band context propagates onward: the signal's own
                    # wire when it arrived with one, this tick's sampled
                    # root otherwise
                    wire = tick.wire if tick.wire is not None else (
                        tick.trace.wire if tick.trace is not None else None)
                    messages.append(prediction_message(pred, wire))
            if messages:
                t_pub0_ns = now_ns() if tracing else 0
                if self._publish_many is not None:
                    self._publish_many(self.prediction_topic, messages)
                else:
                    for msg in messages:
                        self.bus.publish(self.prediction_topic, msg)
        t_publish = self.clock()

        m = self.metrics
        m.count("signals_served", len(results))
        m.observe("device", t_device - t_synced)
        m.observe("publish", t_publish - t_device)
        for tick in inflight.live:
            m.observe("total", t_publish - tick.t_enqueue)
        if tracing:
            self._record_flush_spans(inflight, t_device_ns, t_pub0_ns)
        return results

    def _record_flush_spans(
        self, inflight: _InFlight, t_device_ns: int, t_pub0_ns: int
    ) -> None:
        """Close every traced signal in a completed flush: queued /
        gather / dispatch / device / publish children tiling the serve
        journey.  Signals with in-band context get the children under a
        ``serve`` span on their OWN trace (stitching into the engine →
        serve journey, like the solo Predictor's serve span — with the
        breakdown the solo span never had); bare sampled signals get
        their own root, closed via ``finish_root`` so they feed
        ``e2e_tick_seconds``."""
        if not inflight.t_gather_ns:
            return  # dispatched before tracing was enabled
        tr = self._tracer
        t_publish_ns = now_ns()
        for tick in inflight.live:
            ref = tick.trace
            if ref is None:
                continue
            tid = ref.trace_id
            if tick.wire is not None:
                parent = tr.add_span(tid, ref.span_id, "serve", "serve",
                                     ref.t0_ns, t_publish_ns)
            else:
                parent = ref.span_id
            tr.add_span(tid, parent, "queued", "gateway",
                        ref.t0_ns, inflight.t_gather_ns)
            tr.add_span(tid, parent, "gather", "warehouse",
                        inflight.t_gather_ns, inflight.t_dispatch_ns)
            tr.add_span(tid, parent, "dispatch", "gateway",
                        inflight.t_dispatch_ns, inflight.t_dispatched_ns)
            tr.add_span(tid, parent, "device", "pool",
                        inflight.t_dispatched_ns, t_device_ns)
            pub = tr.add_span(tid, parent, "publish", "publish",
                              t_device_ns, t_publish_ns)
            if t_pub0_ns:
                tr.add_span(tid, pub, "bus_publish", "bus",
                            t_pub0_ns, t_publish_ns)
            if tick.wire is None:
                tr.finish_root(ref, "predict", "serve", t_publish_ns)
