"""Serving-runtime observability: per-stage latency histograms + counters.

The runtime's contract is "overload degrades visibly" — queue depth,
shed/reject counters, and enqueue→dispatch→device→publish latency
histograms are first-class state, not log lines.  Host-side stage wall
clock rides the same :class:`~fmda_tpu.utils.tracing.StageTimer` the
stream engine uses, so ``serve-fleet`` and ``engine.step`` report through
one vocabulary.

:class:`LatencyHistogram` itself lives in the process-wide observability
plane (:mod:`fmda_tpu.obs.registry` — thread-safe, with
``snapshot()``/``merge()`` for cross-thread aggregation) and is
re-exported here; :func:`fmda_tpu.obs.runtime_families` translates a
whole :class:`RuntimeMetrics` into registry samples, which is how the
fleet shows up on a ``/metrics`` scrape.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Tuple

from fmda_tpu.obs.registry import LatencyHistogram
from fmda_tpu.utils.tracing import StageTimer

__all__ = ["LatencyHistogram", "RuntimeMetrics", "STAGES"]

#: The pipeline stages every tick moves through (gateway.submit →
#: batcher flush → device step → bus publish).  Keys of
#: :attr:`RuntimeMetrics.histograms`.
STAGES: Tuple[str, ...] = (
    "enqueue_to_dispatch",  # time spent queued/lingering before a flush
    "gather",               # batched warehouse window gather (the
                            # predictor gateway's id lookup + fetch;
                            # unused — and therefore unreported — by the
                            # carried-state fleet gateway)
    "route",                # multi-host router: submit -> tick batch
                            # published on the owner's inbox topic
                            # (fmda_tpu.fleet; unused in-process)
    "dispatch",             # stale filter + staging assembly + async
                            # enqueue of the batched jit step
    "device",               # host transfer block in _complete; under the
                            # overlap pipeline the device computes during
                            # the previous flush's dispatch/publish, so
                            # this is the *unhidden* remainder
    "publish",              # per-flush batched bus publish
    "total",                # submit -> result published
)


class RuntimeMetrics:
    """All the runtime's instruments in one place.

    - :attr:`histograms` — per-stage :class:`LatencyHistogram` (STAGES);
    - :attr:`counters` — monotonic counts (ticks_served, flushes,
      shed_oldest, rejected_sessions, stale_dropped, ...);
    - :attr:`gauges` — last-observed values (queue_depth, active_sessions);
      ``queue_depth_peak`` is tracked as a counter-style high-water mark;
    - :attr:`timer` — host wall clock per runtime stage (StageTimer).
    """

    def __init__(self) -> None:
        self.histograms: Dict[str, LatencyHistogram] = {
            s: LatencyHistogram(s) for s in STAGES
        }
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.timer = StageTimer()

    def observe(self, stage: str, seconds: float) -> None:
        self.histograms[stage].observe(seconds)

    def count(self, name: str, delta: int = 1) -> None:
        self.counters[name] += delta

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value
        peak = f"{name}_peak"
        if value > self.gauges.get(peak, float("-inf")):
            self.gauges[peak] = value

    def summary(self) -> Dict[str, object]:
        return {
            "latency": {
                s: h.summary() for s, h in self.histograms.items() if h.n
            },
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "host_stages": self.timer.summary(),
        }
