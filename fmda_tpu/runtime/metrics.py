"""Serving-runtime observability: per-stage latency histograms + counters.

The runtime's contract is "overload degrades visibly" — queue depth,
shed/reject counters, and enqueue→dispatch→device→publish latency
histograms are first-class state, not log lines.  Host-side stage wall
clock rides the same :class:`~fmda_tpu.utils.tracing.StageTimer` the
stream engine uses, so ``serve-fleet`` and ``engine.step`` report through
one vocabulary.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, Tuple

from fmda_tpu.utils.tracing import StageTimer

#: The pipeline stages every tick moves through (gateway.submit →
#: batcher flush → device step → bus publish).  Keys of
#: :attr:`RuntimeMetrics.histograms`.
STAGES: Tuple[str, ...] = (
    "enqueue_to_dispatch",  # time spent queued/lingering before a flush
    "device",               # batched jit step incl. host transfer
    "publish",              # per-flush bus publish fan-out
    "total",                # submit -> result published
)


class LatencyHistogram:
    """Fixed log-spaced latency histogram (1 µs .. ~100 s).

    O(1) observe, percentile estimates from bin edges — accurate to one
    bin width (10 bins/decade), which is plenty for p50/p99 serving
    dashboards and costs no per-observation allocation.
    """

    #: 10 bins per decade over 8 decades starting at 1 µs.
    BINS_PER_DECADE = 10
    N_BINS = 8 * BINS_PER_DECADE
    _LO_EXP = -6  # 1e-6 s

    def __init__(self) -> None:
        self.counts = [0] * self.N_BINS
        self.n = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def _bin(self, seconds: float) -> int:
        if seconds <= 1e-6:
            return 0
        b = int((math.log10(seconds) - self._LO_EXP) * self.BINS_PER_DECADE)
        return min(max(b, 0), self.N_BINS - 1)

    def observe(self, seconds: float) -> None:
        self.counts[self._bin(seconds)] += 1
        self.n += 1
        self.total_s += seconds
        if seconds > self.max_s:
            self.max_s = seconds

    def percentile(self, p: float) -> float:
        """Upper edge of the bin holding the p-th percentile (seconds),
        clamped to the true observed max (the top bin's edge can
        otherwise overshoot it)."""
        if self.n == 0:
            return 0.0
        target = p / 100.0 * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                edge = 10.0 ** (
                    self._LO_EXP + (i + 1) / self.BINS_PER_DECADE)
                return min(edge, self.max_s)
        return self.max_s

    def summary(self) -> Dict[str, float]:
        return {
            "count": self.n,
            "mean_ms": round(self.total_s / self.n * 1e3, 4) if self.n else 0.0,
            "p50_ms": round(self.percentile(50) * 1e3, 4),
            "p99_ms": round(self.percentile(99) * 1e3, 4),
            "max_ms": round(self.max_s * 1e3, 4),
        }


class RuntimeMetrics:
    """All the runtime's instruments in one place.

    - :attr:`histograms` — per-stage :class:`LatencyHistogram` (STAGES);
    - :attr:`counters` — monotonic counts (ticks_served, flushes,
      shed_oldest, rejected_sessions, stale_dropped, ...);
    - :attr:`gauges` — last-observed values (queue_depth, active_sessions);
      ``queue_depth_peak`` is tracked as a counter-style high-water mark;
    - :attr:`timer` — host wall clock per runtime stage (StageTimer).
    """

    def __init__(self) -> None:
        self.histograms: Dict[str, LatencyHistogram] = {
            s: LatencyHistogram() for s in STAGES
        }
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.timer = StageTimer()

    def observe(self, stage: str, seconds: float) -> None:
        self.histograms[stage].observe(seconds)

    def count(self, name: str, delta: int = 1) -> None:
        self.counters[name] += delta

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value
        peak = f"{name}_peak"
        if value > self.gauges.get(peak, float("-inf")):
            self.gauges[peak] = value

    def summary(self) -> Dict[str, object]:
        return {
            "latency": {
                s: h.summary() for s, h in self.histograms.items() if h.n
            },
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "host_stages": self.timer.summary(),
        }
