"""Synthetic multi-ticker load generator for the serving runtime.

Drives a :class:`~fmda_tpu.runtime.gateway.FleetGateway` with N
independent ticker sessions — each with its own price scale (per-session
normalization stats) and its own random-walk feature stream — submitting
rows round by round and pumping the gateway, exactly the traffic shape
the fleet runtime exists for.  Used by ``python -m fmda_tpu serve-fleet``
and by the ``runtime_fleet_smoke`` bench phase (the serving-trajectory
baseline later PRs regress against).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from fmda_tpu.data.normalize import NormParams


@dataclass(frozen=True)
class FleetLoadConfig:
    """Shape of the synthetic fleet."""

    n_sessions: int = 64
    #: Submission rounds; every session ticks each round with prob ``duty``.
    n_ticks: int = 100
    #: Fraction of sessions ticking per round (1.0 = lockstep fleet;
    #: lower values exercise ragged arrival + padded buckets).
    duty: float = 1.0
    seed: int = 0
    #: Adversarial reconnect storm: every ``storm_every`` rounds, a
    #: burst of sessions closes and immediately reopens (the traffic
    #: shape a fleet membership change produces — clients stampeding
    #: back).  0 disables.  Reopened sessions restart their stream:
    #: fresh carried state, seq back to 0.
    storm_every: int = 0
    #: Fraction of sessions hit per storm burst.
    storm_fraction: float = 0.25
    #: Synchronized burst (the market-open spike): every ``burst_every``
    #: rounds, EVERY session ticks — duty and the slow-drip set are
    #: overridden — for ``burst_rounds`` consecutive rounds, so the
    #: largest bucket, the queue bound, and the shedder all get hit at
    #: once.  0 disables.
    burst_every: int = 0
    burst_rounds: int = 1
    #: Slow-drip stragglers: this fraction of sessions tick at
    #: ``slow_duty`` instead of ``duty`` — long-lived sessions that
    #: barely tick keep slots pinned, drag the linger deadline, and
    #: ragged-fill the small buckets (the anti-batching shape).
    slow_fraction: float = 0.0
    slow_duty: float = 0.05
    #: Tenant-labeled traffic mix (fmda_tpu.control QoS): parallel
    #: tuples of class names and per-class session weights.  Each
    #: session is assigned one class (deterministic from ``seed``,
    #: proportional to weight) and opened with ``tenant=<class>`` —
    #: composable with bursts, storms, and stragglers, so a spiky gold
    #: tenant can storm a best-effort background fleet.  Empty =
    #: unlabeled sessions (the pre-QoS shape, byte-for-byte).
    tenant_classes: tuple = ()
    tenant_weights: tuple = ()

    def __post_init__(self) -> None:
        if len(self.tenant_classes) != len(self.tenant_weights):
            raise ValueError(
                "tenant_classes and tenant_weights must be parallel: "
                f"{self.tenant_classes} vs {self.tenant_weights}")


def assign_tenants(load: "FleetLoadConfig", rng) -> Optional[list]:
    """Per-session tenant labels for the configured mix (None when no
    mix): weight-proportional draw, deterministic in the load's rng
    stream so a reference replay assigns identically."""
    if not load.tenant_classes:
        return None
    weights = np.asarray(load.tenant_weights, float)
    probs = weights / weights.sum()
    idx = rng.choice(len(load.tenant_classes), size=load.n_sessions, p=probs)
    return [load.tenant_classes[i] for i in idx]


def run_fleet_load(
    gateway,
    load: Optional[FleetLoadConfig] = None,
    *,
    on_round=None,
) -> Dict:
    """Run the synthetic fleet to completion; returns a result dict with
    throughput, per-stage latency summaries, and the loss counters.

    ``gateway`` is anything speaking the gateway serving API —
    :class:`~fmda_tpu.runtime.gateway.FleetGateway` in-process, or a
    :class:`~fmda_tpu.fleet.router.FleetRouter` fronting a multi-host
    topology (same open/submit/pump/drain surface; results then arrive
    asynchronously and ``drain`` blocks until the fleet answers).

    ``on_round`` (optional) is called with the round index after each
    round's pump — the fleet-telemetry fold rides here (cadence-gated
    inside, so the cost when not due is one clock read).
    """
    load = load or FleetLoadConfig()
    pool = getattr(gateway, "pool", None)
    feats = pool.cfg.n_features if pool is not None else gateway.n_features
    rng = np.random.default_rng(load.seed)

    session_ids = [f"T{i:04d}" for i in range(load.n_sessions)]
    tenants = assign_tenants(load, rng)
    # per-session price scale: normalization stats differ per ticker, so
    # the pool's per-slot norm gather is actually exercised
    mins = rng.normal(0.0, 1.0, size=(load.n_sessions, feats)).astype(
        np.float32)
    maxs = mins + rng.uniform(1.0, 5.0, size=(load.n_sessions, feats)).astype(
        np.float32)
    for i, sid in enumerate(session_ids):
        if tenants is None:
            gateway.open_session(sid, NormParams(mins[i], maxs[i]))
        else:
            gateway.open_session(
                sid, NormParams(mins[i], maxs[i]), tenant=tenants[i])

    # independent random walks (B, F), advanced only for sessions that tick
    walk = rng.normal(size=(load.n_sessions, feats)).astype(np.float32)
    # the slow-drip straggler set is fixed for the whole load (the same
    # long-lived barely-ticking clients every round, not a rotating one)
    per_session_duty = np.full(load.n_sessions, load.duty)
    n_slow = int(load.n_sessions * load.slow_fraction)
    if n_slow:
        slow_idx = rng.choice(load.n_sessions, size=n_slow, replace=False)
        per_session_duty[slow_idx] = load.slow_duty
    submitted = 0
    submitted_by_class: Dict[str, int] = {}
    served = 0
    reopened = 0
    burst_ticks = 0
    t0 = time.perf_counter()
    for r in range(load.n_ticks):
        if load.storm_every and r and r % load.storm_every == 0:
            # reconnect storm: close + instantly reopen a burst of
            # sessions (keeps their norm stats — same client, new
            # connection), the shape that drives the migration/reopen
            # machinery hardest
            n_hit = max(1, int(load.n_sessions * load.storm_fraction))
            for i in rng.choice(load.n_sessions, size=n_hit,
                                replace=False):
                sid = session_ids[i]
                gateway.close_session(sid)
                if tenants is None:
                    gateway.open_session(sid, NormParams(mins[i], maxs[i]))
                else:
                    # same client reconnecting: the class sticks
                    gateway.open_session(
                        sid, NormParams(mins[i], maxs[i]),
                        tenant=tenants[i])
                reopened += 1
        in_burst = (load.burst_every and r >= load.burst_every
                    and r % load.burst_every < load.burst_rounds)
        if in_burst:
            # market-open spike: everyone ticks, stragglers included
            ticking = np.ones(load.n_sessions, bool)
            burst_ticks += load.n_sessions
        else:
            ticking = rng.random(load.n_sessions) < per_session_duty
        steps = rng.normal(
            scale=0.1, size=(load.n_sessions, feats)).astype(np.float32)
        walk[ticking] += steps[ticking]
        for i in np.flatnonzero(ticking):
            while gateway.saturated:
                # well-behaved producer: drain instead of racing the
                # shedder (fleets larger than queue_bound would otherwise
                # lose ticks before pump() ever ran).  A multi-host
                # router stays saturated until its workers catch up —
                # yield the GIL so the bus-server threads can serve them
                drained = gateway.pump(force=True)
                served += len(drained)
                if not drained and gateway.saturated:
                    time.sleep(0.002)
            gateway.submit(session_ids[i], walk[i])
            submitted += 1
            if tenants is not None:
                cls = tenants[i]
                submitted_by_class[cls] = \
                    submitted_by_class.get(cls, 0) + 1
        served += len(gateway.pump())
        if on_round is not None:
            on_round(r)
    served += len(gateway.drain())
    wall_s = time.perf_counter() - t0

    summary = gateway.metrics.summary()
    out = {
        "sessions": load.n_sessions,
        "rounds": load.n_ticks,
        "ticks_submitted": submitted,
        "ticks_served": served,
        "wall_s": round(wall_s, 3),
        "ticks_per_s": round(served / wall_s, 1) if wall_s > 0 else None,
        "compile_count": pool.compile_count if pool is not None else None,
        **summary,
    }
    if load.storm_every:
        out["sessions_reopened"] = reopened
    if load.burst_every:
        out["burst_ticks"] = burst_ticks
    if n_slow:
        out["slow_sessions"] = n_slow
    if tenants is not None:
        out["submitted_by_class"] = submitted_by_class
    return out


@dataclass(frozen=True)
class PredictorLoadConfig:
    """Shape of a batched-Predictor load: serve ``n_signals`` warehouse
    timestamps (0 = every servable one) in bursts of ``burst`` signals
    per poll — the traffic the engine's signal-after-commit cadence
    produces."""

    n_signals: int = 0
    burst: int = 32


def run_predictor_load(
    gateway, timestamps, load: Optional[PredictorLoadConfig] = None
) -> Dict:
    """Publish predict-timestamp signals in bursts on the gateway's bus
    and poll the :class:`~fmda_tpu.runtime.predictor_pool
    .PredictorGateway` after each burst; returns throughput + per-stage
    latency + loss counters (``serve-fleet --predictor`` and the
    ``predictor_fleet_smoke`` bench phase)."""
    from fmda_tpu.config import TOPIC_PREDICT_TIMESTAMP

    load = load or PredictorLoadConfig()
    timestamps = list(timestamps)
    if load.n_signals:
        timestamps = timestamps[: load.n_signals]
    served = 0
    t0 = time.perf_counter()
    for i in range(0, len(timestamps), load.burst):
        for ts in timestamps[i:i + load.burst]:
            gateway.bus.publish(TOPIC_PREDICT_TIMESTAMP, {"Timestamp": ts})
        served += len(gateway.poll())
    served += len(gateway.drain())
    wall_s = time.perf_counter() - t0

    summary = gateway.metrics.summary()
    return {
        "signals_submitted": len(timestamps),
        "signals_served": served,
        "burst": load.burst,
        "wall_s": round(wall_s, 3),
        "signals_per_s": round(served / wall_s, 1) if wall_s > 0 else None,
        "compile_count": gateway.pool.compile_count,
        **summary,
    }
