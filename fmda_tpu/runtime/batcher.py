"""Deadline-aware micro-batcher: coalesce tick requests into bucketed flushes.

The MXU wants one big batched step, the client wants its answer *now* —
the micro-batcher sits between them (the same trade every batching
inference server makes).  Requests accumulate until either

- **batch-full**: as many distinct sessions are pending as the largest
  bucket holds (waiting longer cannot grow the flush), or
- **deadline**: the oldest pending request has lingered ``max_linger_s``
  (waiting longer only buys latency).

Flush sizes are then padded *up* to a small fixed set of ``bucket_sizes``
so XLA compiles one program per bucket and replays it forever — the
compiled-once/dispatch-many discipline (PAPERS.md, pjit at scale): a
fleet serving thousands of tickers must never pay a compile on the tick
path.  :attr:`SessionPool.compile_count` asserts this holds.

Per-session ordering: a session's ticks advance a recurrence, so two rows
from one session can never share a flush (the scatter would race).  The
batcher takes the *first* pending row per session per flush; the rest
keep their arrival order for the next one.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, List, Optional, Tuple

import numpy as np

from fmda_tpu.config import DEFAULT_BUCKET_SIZES, DEFAULT_MAX_LINGER_S
from fmda_tpu.runtime.session_pool import SessionHandle


@dataclass(frozen=True)
class BatcherConfig:
    """Tuning knobs (docs/runtime.md discusses the trade-offs)."""

    #: Ascending padded batch sizes; each flush compiles/replays the
    #: smallest bucket that fits.  Keep this set SMALL — every entry is
    #: one XLA compilation held in cache.  The default is
    #: config.DEFAULT_BUCKET_SIZES, the same constant RuntimeConfig uses
    #: (64 included so the default fleet size doesn't pad 2x).
    bucket_sizes: Tuple[int, ...] = DEFAULT_BUCKET_SIZES
    #: Max time the oldest request may wait before a flush is forced.
    max_linger_s: float = DEFAULT_MAX_LINGER_S

    def __post_init__(self) -> None:
        if not self.bucket_sizes:
            raise ValueError("bucket_sizes must be non-empty")
        if tuple(sorted(self.bucket_sizes)) != tuple(self.bucket_sizes):
            raise ValueError(
                f"bucket_sizes must be ascending: {self.bucket_sizes}")
        if self.max_linger_s < 0:
            raise ValueError("max_linger_s must be >= 0")


@dataclass
class Tick:
    """One queued tick request: a session's newest feature row."""

    handle: SessionHandle
    row: np.ndarray
    t_enqueue: float
    seq: int = 0
    #: sampled trace root (:class:`fmda_tpu.obs.trace.TraceRef`) begun at
    #: submit; None when tracing is disabled or the tick was unsampled
    trace: Optional[object] = None
    #: in-band trace context (``"trace_id:span_id"``) the request arrived
    #: with — the predictor gateway stitches its serve spans into the
    #: *signal's* journey instead of opening a fresh root
    wire: Optional[str] = None


class MicroBatcher:
    """FIFO of pending ticks with deadline/batch-full flush decisions."""

    def __init__(
        self,
        config: Optional[BatcherConfig] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.config = config or BatcherConfig()
        self.clock = clock
        self._pending: Deque[Tick] = deque()
        #: distinct sessions currently pending (slot, generation) -> count
        self._per_session: dict = {}
        #: Upper bound on distinct sessions that can possibly be pending
        #: (the gateway keeps this at the pool's active-session count).
        #: When every possible session is already pending, a flush cannot
        #: grow — waiting out the linger would buy pure latency, so
        #: ``ready`` fires early.  None = only the largest bucket counts
        #: as batch-full.
        self.full_target: Optional[int] = None
        #: Soft cap on the flush size the batching controller can lower
        #: at runtime (fmda_tpu.control): flushes stop growing past the
        #: largest *configured* bucket at or under the cap — only
        #: already-compiled buckets are ever selected, so a retune can
        #: never cost a compile on the tick path.  None = uncapped.
        self.bucket_cap: Optional[int] = None

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def distinct_sessions(self) -> int:
        return len(self._per_session)

    def add(self, tick: Tick) -> None:
        self._pending.append(tick)
        key = (tick.handle.slot, tick.handle.generation)
        self._per_session[key] = self._per_session.get(key, 0) + 1

    def shed_oldest(self) -> Optional[Tick]:
        """Drop (and return) the oldest pending tick — the gateway's
        load-shedding primitive.  Never silent: the caller counts it."""
        if not self._pending:
            return None
        tick = self._pending.popleft()
        self._dec(tick)
        return tick

    def shed_matching(self, pred: Callable[[Tick], bool]) -> Optional[Tick]:
        """Drop (and return) the *oldest* pending tick satisfying
        ``pred`` — the per-tenant QoS shed (fmda_tpu.control.qos picks
        the victim class; this removes its oldest tick).  None when
        nothing matches; the caller counts every drop, never silent.
        O(queue) scan, but only ever on the contended-shed path."""
        for i, tick in enumerate(self._pending):
            if pred(tick):
                del self._pending[i]
                self._dec(tick)
                return tick
        return None

    def effective_cap(self) -> int:
        """The flush-size ceiling: the largest configured bucket at or
        under ``bucket_cap`` (smallest bucket when the cap undercuts
        them all; the largest when uncapped)."""
        sizes = self.config.bucket_sizes
        if self.bucket_cap is None:
            return sizes[-1]
        for b in reversed(sizes):
            if b <= self.bucket_cap:
                return b
        return sizes[0]

    def _dec(self, tick: Tick) -> None:
        key = (tick.handle.slot, tick.handle.generation)
        n = self._per_session.get(key, 0) - 1
        if n <= 0:
            self._per_session.pop(key, None)
        else:
            self._per_session[key] = n

    def oldest_age(self, now: Optional[float] = None) -> float:
        if not self._pending:
            return 0.0
        return (now if now is not None else self.clock()) \
            - self._pending[0].t_enqueue

    def ready(self, now: Optional[float] = None) -> bool:
        """Flush now?  Batch-full (distinct sessions fill the largest
        bucket, or every session that COULD tick is already pending —
        ``full_target``) or deadline (oldest tick lingered past the
        budget)."""
        if not self._pending:
            return False
        target = self.effective_cap()
        if self.full_target is not None:
            target = min(target, max(self.full_target, 1))
        if self.distinct_sessions >= target:
            return True
        return self.oldest_age(now) >= self.config.max_linger_s

    def take_batch(self) -> List[Tick]:
        """Pop the next flush: first pending row per session, FIFO, up to
        the largest bucket.  Later rows of the same session stay queued
        (their recurrence needs this flush's result first)."""
        cap = self.effective_cap()
        # fast path for the common lockstep flush: when no session has a
        # second row queued and everything fits one flush, the whole
        # queue is the batch — no per-tick set hashing or re-queueing
        if (len(self._pending) <= cap
                and len(self._per_session) == len(self._pending)):
            taken = list(self._pending)
            self._pending.clear()
            self._per_session.clear()
            return taken
        taken: List[Tick] = []
        seen = set()
        leftover: List[Tick] = []
        while self._pending and len(taken) < cap:
            tick = self._pending.popleft()
            key = (tick.handle.slot, tick.handle.generation)
            if key in seen:
                leftover.append(tick)
                continue
            seen.add(key)
            self._dec(tick)
            taken.append(tick)
        # deferred same-session rows go back to the FRONT (still the
        # oldest work; per-session order is preserved exactly)
        self._pending.extendleft(reversed(leftover))
        return taken

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket holding ``n`` requests."""
        for b in self.config.bucket_sizes:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket "
            f"{self.config.bucket_sizes[-1]} (take_batch caps at it)")
