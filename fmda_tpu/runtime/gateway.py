"""Admission-control front door: bounded queue → micro-batcher → pool → bus.

One :class:`FleetGateway` owns the serving loop for a fleet of sessions:

- ``open_session``/``close_session`` — admission control against the
  slot pool (a full pool **rejects loudly**, it never queues forever);
- ``submit`` — enqueue a session's newest row behind a **bounded** queue;
  overload sheds the *oldest* queued tick with a counted metric
  (``shed_oldest``) — stale market data is the cheapest thing to lose,
  and an unbounded queue is how serving systems die;
- ``pump`` — flush micro-batches whenever the batcher says so
  (batch-full or deadline), run the one fused pool step, and publish each
  session's result on the :class:`~fmda_tpu.stream.bus.MessageBus`
  (``fleet_prediction`` topic, ``session`` field keying per-session
  consumption) — the same transport every other stage of the framework
  already speaks.

**The overlap pipeline** (ISSUE 3, persistence ISSUE 4): dispatching a
flush and consuming its results are split into
:meth:`FleetGateway._dispatch` (stale filter, staging-buffer assembly,
async ``SessionPool.step_device``) and :meth:`FleetGateway._complete`
(host transfer, label thresholding, one batched bus publish).  ``pump``
runs them one flush apart — while flush k's probabilities cross the
host boundary and fan out to the bus, flush k+1 is already assembled
and enqueued on the device.  The one-deep pipeline **persists across
``pump`` calls**: a flush dispatched by this call stays in flight so
the *next* call's dispatch overlaps it — single-flush-per-pump traffic
(the steady-state serving loop) overlaps too, not just multi-flush
drains.  Consequently ``pump`` returns every result *completed* this
call; the trailing flush's results arrive on the next ``pump`` (an idle
pump — nothing new to dispatch — flushes the pipeline) or on
:meth:`drain`.  ``pipeline_depth=0`` forces strictly serial same-call
results, the bit-identical A/B reference.  Batch assembly writes into
pre-allocated per-bucket staging buffers (double-buffered, because a
one-deep pipeline has at most one prior flush whose dispatch may still
read its staging), killing the two per-flush array allocations.

Every tick's journey is measured (enqueue→dispatch→device→publish
histograms in :class:`~fmda_tpu.runtime.metrics.RuntimeMetrics`); every
loss path is a counter, never a silent drop.  Under overlap, ``device``
measures the time ``_complete`` spends *blocked* on the transfer —
overlapped device work hides inside the preceding ``dispatch``/
``publish`` wall clock, which is the point.

When the process tracer (:mod:`fmda_tpu.obs.trace`) is enabled, sampled
ticks get a full trace: a root span begun at :meth:`submit` plus
queued/dispatch/device/publish child spans that tile it exactly, and
the result message carries the tick's ``trace`` context in-band.
Disabled tracing costs one branch per submit and per flush.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from fmda_tpu.config import (
    DEFAULT_QUEUE_BOUND,
    TARGET_COLUMNS,
    TOPIC_FLEET_PREDICTION,
)
from fmda_tpu.data.normalize import NormParams
from fmda_tpu.obs.trace import TraceRef, default_tracer, now_ns, parse_wire
from fmda_tpu.runtime.batcher import BatcherConfig, MicroBatcher, Tick
from fmda_tpu.runtime.metrics import RuntimeMetrics
from fmda_tpu.runtime.session_pool import (
    PoolExhausted,
    SessionHandle,
    SessionPool,
)
from fmda_tpu.serve.predictor import labels_over_threshold
from fmda_tpu.stream import codec

log = logging.getLogger("fmda_tpu.runtime")


@dataclass(frozen=True)
class FleetResult:
    """One served tick: the probabilities for one session's newest row."""

    session_id: str
    seq: int
    probabilities: np.ndarray
    labels: Tuple[str, ...]
    #: checkpoint generation that served this tick — None before the
    #: first hot swap (the pre-swap result shape, unchanged); the
    #: quality plane keys its per-version metrics on this stamp
    weights_version: Optional[int] = None


@dataclass
class _InFlight:
    """A dispatched-but-unconsumed flush: the device handle to its
    probabilities plus everything ``_complete`` needs to publish them."""

    live: List[Tick]
    probs_dev: object  # (bucket, n_classes) device array
    bucket: int
    #: perf_counter_ns stamps of the dispatch window (0 when untraced) —
    #: the queued/dispatch span boundaries for this flush's traced ticks
    t_dispatch_ns: int = 0
    t_dispatched_ns: int = 0


class FleetGateway:
    """Multiplexes many ticker sessions onto one batched serving step."""

    #: Log every Nth shed (the counter is the source of truth; the log is
    #: a human-visible heartbeat that shedding is happening).
    SHED_LOG_EVERY = 1000

    def __init__(
        self,
        pool: SessionPool,
        bus=None,
        *,
        batcher_config: Optional[BatcherConfig] = None,
        queue_bound: int = DEFAULT_QUEUE_BOUND,
        metrics: Optional[RuntimeMetrics] = None,
        clock: Callable[[], float] = time.monotonic,
        prediction_topic: str = TOPIC_FLEET_PREDICTION,
        threshold: float = 0.5,
        y_fields: Tuple[str, ...] = TARGET_COLUMNS,
        pipeline_depth: int = 1,
    ) -> None:
        if queue_bound < 1:
            raise ValueError(f"queue_bound must be >= 1, got {queue_bound}")
        if pipeline_depth not in (0, 1):
            raise ValueError(
                f"pipeline_depth must be 0 (serial) or 1 (one-deep "
                f"overlap), got {pipeline_depth}")
        if bus is not None and prediction_topic not in bus.topics():
            # fail at construction, not mid-flush: a publish KeyError
            # after pool.step would lose results whose state advance is
            # irreversible (pre-PR-1 configs with an explicit bus.topics
            # list lack the fleet topic)
            raise ValueError(
                f"bus has no topic {prediction_topic!r} (configured: "
                f"{sorted(bus.topics())}); add it to bus.topics — the "
                "default layout includes it as TOPIC_FLEET_PREDICTION")
        self.pool = pool
        self.bus = bus
        self.queue_bound = queue_bound
        self.metrics = metrics or RuntimeMetrics()
        self.clock = clock
        self.prediction_topic = prediction_topic
        self.threshold = threshold
        self.y_fields = tuple(y_fields)
        #: 1 = one-deep overlap pipeline (default); 0 = serial flushes
        #: (the A/B reference the bit-identity tests compare against).
        self.pipeline_depth = pipeline_depth
        self.batcher = MicroBatcher(batcher_config, clock=clock)
        self._seq: Dict[str, int] = {}
        #: per-session tenant labels (None entries never stored); rides
        #: export/import so a migrated session keeps its class
        self._tenant: Dict[str, str] = {}
        #: per-tenant QoS policy (fmda_tpu.control.qos.QosPolicy); None
        #: = global oldest-drop shedding, exactly the pre-control path
        self.qos = None
        #: queued ticks per priority class, maintained O(1) per tick
        #: (only while a policy is attached — the victim pick must not
        #: scan the queue per submit)
        self._queued_by_class: Dict[str, int] = {}
        # pre-allocated per-bucket staging for batch assembly, two
        # (slots, rows) pairs per bucket: with a one-deep pipeline at
        # most one earlier flush's dispatch can still be reading its
        # staging (jax may alias host numpy on CPU), and its completion
        # — which always precedes reusing the same parity — forces that
        # read to have finished
        self._staging: Dict[int, list] = {}
        self._staging_idx: Dict[int, int] = {}
        self._publish_many = (
            getattr(bus, "publish_many", None) if bus is not None else None)
        #: the cross-pump in-flight flush (the persistent one-deep
        #: pipeline; always None when pipeline_depth == 0)
        self._inflight: Optional[_InFlight] = None
        #: span recorder (fmda_tpu.obs.trace) — process-default tracer,
        #: captured once; disabled = one branch per submit/flush
        self._tracer = default_tracer()
        #: opt-in jax.profiler.StepTraceAnnotation around each pool step
        #: dispatch, so device-side work lands in a --jax-profile capture
        #: as numbered pool_flush steps (serve-fleet --jax-profile DIR)
        self.annotate_device_steps = False
        #: publish whole flushes as columnar ``result_block`` messages
        #: (fmda_tpu.stream.codec.pack_results) instead of per-tick
        #: dicts.  Off by default: only a consumer that understands
        #: blocks may turn this on — the fleet worker does, once the
        #: router has proven itself v2 (ISSUE 13; in-process consumers
        #: of the prediction topic keep the per-tick shape).
        self.result_blocks = False
        #: checkpoint generation serving the pool — ``None`` until the
        #: first :meth:`hot_swap` (results and reports stay byte-shaped
        #: exactly as before any swap); stamped into every published
        #: result and session report afterwards so mixed-version windows
        #: are observable (docs/replay.md "Hot swap")
        self.weights_version: Optional[int] = None
        #: results completed by a hot-swap barrier outside pump — handed
        #: to the caller on the next pump/drain so in-process consumers
        #: (no bus) never lose the old-weights flush
        self._barrier_results: List[FleetResult] = []
        #: served-tick counts keyed by the weights_version that served
        #: them (0 = pre-swap) — heartbeats carry this so the router's
        #: quality plane attributes traffic share per checkpoint
        self._version_ticks: Dict[int, int] = {}
        self._flush_idx = 0

    # -- admission ----------------------------------------------------------

    def open_session(
        self, session_id: str, norm: Optional[NormParams] = None,
        *, seq: int = 0, tenant: Optional[str] = None,
    ) -> SessionHandle:
        """Admit a session (raises :class:`PoolExhausted` when the fleet
        is full — counted, so rejected admissions show up on dashboards,
        and the caller decides whether to retry, evict, or scale).

        ``seq`` starts the session's result sequence above 0 — the
        multi-host router reopens a lost-state session mid-stream and
        must not emit colliding (session, seq) pairs.  ``tenant`` is
        the session's priority-class label (fmda_tpu.control QoS);
        unlabeled sessions ride the policy's default class."""
        try:
            handle = self.pool.alloc(session_id, norm)
        except PoolExhausted:
            # only capacity rejections count here — a duplicate-id
            # ValueError is a client bug, not a fleet-is-full signal
            self.metrics.count("rejected_sessions")
            raise
        if seq:
            self._seq[session_id] = int(seq)
        if tenant is not None:
            self._tenant[session_id] = str(tenant)
        self._sessions_changed()
        return handle

    def close_session(self, session_id: str) -> None:
        handle = self.pool.handle_for(session_id)
        if handle is None:
            raise KeyError(f"no open session {session_id!r}")
        self.pool.free(handle)
        self._seq.pop(session_id, None)
        self._tenant.pop(session_id, None)
        self._sessions_changed()

    def session_tenant(self, session_id: str) -> Optional[str]:
        """The session's tenant label (None when opened unlabeled) —
        what the worker's session report carries so failover and
        migration preserve the class."""
        return self._tenant.get(session_id)

    # -- control-plane hooks (fmda_tpu.control; docs/control.md) ------------

    def attach_qos(self, policy) -> None:
        """Install a per-tenant QoS policy: admission bookkeeping turns
        on and overload shedding becomes WFQ fair-share + quota based
        (see :meth:`submit`).  Detach with ``None`` to restore global
        oldest-drop."""
        self.qos = policy
        self._queued_by_class = {}

    def retune(
        self, *, max_linger_ms: Optional[float] = None,
        bucket_cap: Optional[int] = None,
    ) -> None:
        """Swap the batching knobs at runtime (the batching controller's
        actuation): the frozen config is replaced atomically, and the
        bucket cap only ever selects an already-compiled bucket — a
        retune can never cost a compile on the tick path."""
        import dataclasses as _dc

        if max_linger_ms is not None:
            self.batcher.config = _dc.replace(
                self.batcher.config, max_linger_s=max_linger_ms / 1e3)
        self.batcher.bucket_cap = bucket_cap
        self.metrics.count("retunes_applied")

    @property
    def version_ticks(self) -> Dict[int, int]:
        """Served ticks per weights_version (0 = pre-swap) — heartbeat
        stats carry a copy for router-side per-checkpoint attribution."""
        return dict(self._version_ticks)

    def hot_swap(self, params, *, version: Optional[int] = None) -> int:
        """Land a new checkpoint into the live pool — zero dropped
        sessions, zero recompiles (docs/replay.md "Hot swap").

        The one ordering obligation is the **swap barrier**: a flush
        dispatched under the old weights must publish before the version
        flips, or an old-weights result would carry the new stamp.  So
        the in-flight pipeline stage (if any) is completed here, its
        results published under the old version; everything still queued
        in the batcher dispatches after the rebind and is served by the
        new weights.  Returns the new ``weights_version`` (caller-pinned
        via ``version``, else monotonically bumped from 1).
        """
        if self._inflight is not None:
            prev, self._inflight = self._inflight, None
            self._barrier_results.extend(self._complete_counted(prev))
        self.pool.swap_weights(params)
        self.weights_version = (
            int(version) if version is not None
            else (self.weights_version or 0) + 1)
        self.metrics.count("hot_swaps_applied")
        self.metrics.gauge("weights_version", float(self.weights_version))
        return self.weights_version

    def _sessions_changed(self) -> None:
        self.metrics.gauge("active_sessions", self.pool.n_active)
        # when every active session is already pending a flush cannot
        # grow — tell the batcher so small fleets don't wait out the
        # linger on every steady-state flush
        self.batcher.full_target = self.pool.n_active

    # -- session migration (fmda_tpu.fleet; docs/multihost.md) --------------

    def export_session(self, session_id: str) -> dict:
        """Snapshot a session for migration: its pooled carried state
        (:meth:`SessionPool.export_slot`) plus the gateway's per-session
        sequence counter, so the new owner's results continue the same
        ``seq`` stream with no gap or collision.  Caller contract: the
        session's queued ticks are already flushed (``drain``) — ticks
        still queued here would be lost to the snapshot."""
        handle = self.pool.handle_for(session_id)
        if handle is None:
            raise KeyError(f"no open session {session_id!r}")
        state = self.pool.export_slot(handle)
        state["seq"] = self._seq.get(session_id, 0)
        tenant = self._tenant.get(session_id)
        if tenant is not None:
            # the QoS class migrates with the session — a gold session
            # must not land on the new owner as best-effort
            state["tenant"] = tenant
        return state

    def session_seq(self, session_id: str) -> int:
        """The next result sequence number of an open session — what a
        worker's session report carries so a restarted router resumes
        the stream with no gap or collision (fmda_tpu.fleet failover)."""
        if self.pool.handle_for(session_id) is None:
            raise KeyError(f"no open session {session_id!r}")
        return self._seq.get(session_id, 0)

    def resync_seq(self, session_id: str, seq: int) -> None:
        """Jump a session's sequence counter to the router's (fleet
        worker use): after ticks were lost in transit — a partitioned
        link's frame — the streams diverge by the loss count, and
        without a resync every later result would match the WRONG
        in-flight tick forever.  The caller counts the divergence."""
        if self.pool.handle_for(session_id) is None:
            raise KeyError(f"no open session {session_id!r}")
        self._seq[session_id] = int(seq)

    def import_session(self, session_id: str, state: dict) -> SessionHandle:
        """Open a session from an :meth:`export_session` snapshot (the
        receiving end of a migration): allocates a slot, loads the
        carried state bit-exact, and resumes the sequence counter."""
        handle = self.open_session(session_id, tenant=state.get("tenant"))
        try:
            self.pool.import_slot(handle, state)
        except Exception:
            # a malformed snapshot must not leak the slot it claimed
            self.pool.free(handle)
            self._tenant.pop(session_id, None)
            self._sessions_changed()
            raise
        self._seq[session_id] = int(state.get("seq", 0))
        return handle

    # -- the request path ---------------------------------------------------

    def submit(
        self, session_id: str, row: np.ndarray,
        wire: Optional[str] = None,
    ) -> int:
        """Enqueue a session's newest feature row; returns the tick's
        per-session sequence number.  Overload sheds the oldest queued
        tick (counted + heartbeat-logged), never blocks, never grows the
        queue past ``queue_bound``.

        ``wire`` is in-band trace context the tick arrived with (a
        multi-host router's ``trace`` field — fmda_tpu.fleet): the
        flush spans then stitch under a ``serve`` span on *that* trace
        instead of opening a fresh root, so a cross-process journey
        groups as one trace after ``trace --merge``."""
        handle = self.pool.handle_for(session_id)
        if handle is None:
            raise KeyError(f"no open session {session_id!r}")
        row = np.array(row, np.float32)  # copy: the queue must OWN rows
        if row.shape != (self.pool.cfg.n_features,):
            # reject at the submitter — a malformed row reaching _flush
            # would throw there and lose the whole batch's other ticks
            raise ValueError(
                f"row shape {row.shape} != ({self.pool.cfg.n_features},) "
                f"for session {session_id!r}")
        cls = None
        if self.qos is not None:
            # per-tenant quota: a class at its queue-share budget sheds
            # its OWN oldest tick to admit the new one — a storming
            # tenant can never crowd other classes out of the queue
            cls = self.qos.classify(self._tenant.get(session_id))
            quota = self.qos.quota(cls, self.queue_bound)
            while self._queued_by_class.get(cls, 0) >= quota:
                shed = self.batcher.shed_matching(
                    lambda t: self._class_of(t) == cls)
                if shed is None:
                    break
                self.metrics.count("quota_shed")
                self.metrics.count(f"shed_class_{cls}")
                self._class_dec(cls)
        while len(self.batcher) >= self.queue_bound:
            shed = None
            if self.qos is not None:
                # WFQ fair-share shedding: the class furthest over its
                # weighted share loses its oldest tick (global
                # oldest-drop when no policy is attached)
                vcls = self.qos.pick_victim(self._queued_by_class)
                if vcls is not None:
                    shed = self.batcher.shed_matching(
                        lambda t: self._class_of(t) == vcls)
            if shed is None:
                shed = self.batcher.shed_oldest()
            self.metrics.count("shed_oldest")
            if self.qos is not None and shed is not None:
                scls = self._class_of(shed)
                self.metrics.count(f"shed_class_{scls}")
                self._class_dec(scls)
            n = self.metrics.counters["shed_oldest"]
            if n == 1 or n % self.SHED_LOG_EVERY == 0:
                log.warning(
                    "queue full (bound=%d): shed oldest tick (session %s, "
                    "seq %d); %d shed so far",
                    self.queue_bound, shed.handle.session_id, shed.seq, n)
        seq = self._seq.get(session_id, 0)
        self._seq[session_id] = seq + 1
        ref = None
        if wire is None:
            # one branch when tracing is off; when sampled, the returned
            # ref is this tick's trace root, closed at publish in
            # _complete
            ref = self._tracer.maybe_trace()
        elif self._tracer.enabled:
            ctx = parse_wire(wire)
            if ctx is not None:
                # ride the router's journey: flush spans parent on the
                # publisher's span, t0 stamps the serve stage start
                ref = TraceRef(ctx[0], ctx[1], now_ns())
        self.batcher.add(Tick(
            handle=handle, row=row, t_enqueue=self.clock(), seq=seq,
            trace=ref, wire=wire))
        if self.qos is not None:
            self._queued_by_class[cls] = \
                self._queued_by_class.get(cls, 0) + 1
            self.metrics.count(f"admitted_class_{cls}")
        self.metrics.gauge("queue_depth", len(self.batcher))
        return seq

    def _class_of(self, tick: Tick) -> str:
        """A queued tick's priority class under the attached policy."""
        return self.qos.classify(self._tenant.get(tick.handle.session_id))

    def _class_dec(self, cls: str) -> None:
        n = self._queued_by_class.get(cls, 0) - 1
        if n <= 0:
            self._queued_by_class.pop(cls, None)
        else:
            self._queued_by_class[cls] = n

    @property
    def saturated(self) -> bool:
        """Backpressure signal: the next submit will shed.  Well-behaved
        producers check this and slow down instead of racing the shedder."""
        return len(self.batcher) >= self.queue_bound

    # -- the serving loop ---------------------------------------------------

    def pump(self, *, force: bool = False) -> List[FleetResult]:
        """Flush ready micro-batches (all pending ones when ``force`` —
        the drain path).  Returns every result *completed* this call;
        each is also published on the bus when one is attached.

        Consecutive flushes run through the one-deep overlap pipeline:
        flush k+1 is assembled and dispatched *before* flush k's
        probabilities are pulled to the host and published, so the
        device computes k+1 while the host finishes k.  The pipeline
        **persists across calls** (ROADMAP runtime follow-up): the last
        flush this call dispatches stays in flight, to be completed
        right after the *next* call's first dispatch — so steady-state
        single-flush-per-pump traffic overlaps too.  A pump that
        dispatches nothing completes the pending flush (result latency
        stays bounded by the pump cadence), ``force`` completes
        everything, and ``pipeline_depth=0`` keeps the strictly serial
        same-call contract (the bit-identical A/B reference).
        """
        results: List[FleetResult] = []
        if self._barrier_results:
            # old-weights results completed by a hot-swap barrier since
            # the last pump — already published; hand them to the caller
            results, self._barrier_results = self._barrier_results, []
        dispatched_any = False
        try:
            while True:
                if force:
                    if not len(self.batcher):
                        break
                elif not self.batcher.ready(self.clock()):
                    break
                ticks = self.batcher.take_batch()
                if not ticks:
                    break
                if self.qos is not None:
                    # ticks leave the queue only here or via shed —
                    # both decrement, so class counts stay exact
                    for t in ticks:
                        self._class_dec(self._class_of(t))
                nxt = self._dispatch(ticks)
                if nxt is not None:
                    dispatched_any = True
                # hand the previous flush off BEFORE completing it, so a
                # completion failure can never strand the just-dispatched
                # one (its state advance is already irreversible)
                prev, self._inflight = self._inflight, nxt
                if prev is not None:
                    if nxt is not None:
                        self.metrics.count("overlapped_flushes")
                    results.extend(self._complete_counted(prev))
                if self.pipeline_depth == 0 and self._inflight is not None:
                    prev, self._inflight = self._inflight, None
                    results.extend(self._complete_counted(prev))
            if self._inflight is not None and (force or not dispatched_any):
                # force-drain, or an idle pump with a leftover in-flight
                # flush from a previous call: flush the pipeline now
                prev, self._inflight = self._inflight, None
                results.extend(self._complete_counted(prev))
        except BaseException:
            # unwinding an exception with a live in-flight flush: its
            # pool-state advance already happened, so its results must
            # still be published (consumers stay consistent with the
            # recurrence) — and if even that fails, _complete_counted
            # made the loss a counter, never silence
            if self._inflight is not None:
                prev, self._inflight = self._inflight, None
                try:
                    self._complete_counted(prev)
                except Exception:  # noqa: BLE001 — loss-free: double
                    # fault while unwinding; _complete_counted already
                    # counted the flush's ticks lost, and the outer
                    # handler re-raises the original failure
                    log.exception(
                        "in-flight flush lost while unwinding pump failure")
            raise
        finally:
            self.metrics.gauge("queue_depth", len(self.batcher))
        return results

    def _complete_counted(self, inflight: _InFlight) -> List[FleetResult]:
        """:meth:`_complete` with the loss path counted: a completion
        failure (bus publish error, transfer failure) marks its ticks
        ``flush_results_lost`` before propagating — the state advance
        behind them is irreversible, so the loss must be visible."""
        try:
            return self._complete(inflight)
        except Exception:
            self.metrics.count("flush_results_lost", len(inflight.live))
            raise

    def drain(self) -> List[FleetResult]:
        """Serve everything still queued, deadline or not (shutdown/end
        of load)."""
        return self.pump(force=True)

    def _staging_for(self, bucket: int):
        """The next (slots, rows) staging pair for ``bucket`` —
        pre-allocated once per bucket, alternating between two parities
        (see the constructor comment for why two suffice)."""
        bufs = self._staging.get(bucket)
        if bufs is None:
            bufs = [
                (np.full(bucket, self.pool.padding_slot, np.int32),
                 np.zeros((bucket, self.pool.cfg.n_features), np.float32))
                for _ in range(2)
            ]
            self._staging[bucket] = bufs
            self._staging_idx[bucket] = 0
        idx = self._staging_idx[bucket]
        self._staging_idx[bucket] = 1 - idx
        return bufs[idx]

    def _dispatch(self, ticks: List[Tick]) -> Optional[_InFlight]:
        """Stage 1 of a flush: stale-filter, assemble into the bucket's
        staging buffers, enqueue the pool step on the device.  Returns
        the in-flight record (None if every tick went stale in queue)."""
        t_dispatch = self.clock()
        tracing = self._tracer.enabled
        t_dispatch_ns = now_ns() if tracing else 0
        live = []
        for tick in ticks:
            # a session freed while its tick was queued: drop, visibly
            if self.pool.is_live(tick.handle):
                live.append(tick)
            else:
                self.metrics.count("stale_dropped")
        if not live:
            return None
        bucket = self.batcher.bucket_for(len(live))
        slots, rows = self._staging_for(bucket)
        for i, tick in enumerate(live):
            slots[i] = tick.handle.slot
            rows[i] = tick.row
        # lanes past len(live) keep stale rows from the buffer's last
        # use — harmless by construction (they compute into the padding
        # slot, state nothing reads) — but their slot entries MUST be
        # re-pointed at the padding lane
        slots[len(live):] = self.pool.padding_slot
        self._flush_idx += 1
        with self.metrics.timer.stage("dispatch"):
            if self.annotate_device_steps:
                from fmda_tpu.utils.tracing import step_annotation

                with step_annotation("pool_flush", self._flush_idx):
                    probs_dev = self.pool.step_device(slots, rows)
            else:
                probs_dev = self.pool.step_device(slots, rows)  # async
        t_dispatched = self.clock()
        t_dispatched_ns = now_ns() if tracing else 0

        m = self.metrics
        m.count("flushes")
        m.count(f"flushes_bucket_{bucket}")
        m.count("padded_lanes", bucket - len(live))
        m.observe("dispatch", t_dispatched - t_dispatch)
        for tick in live:
            m.observe("enqueue_to_dispatch", t_dispatch - tick.t_enqueue)
        return _InFlight(
            live=live, probs_dev=probs_dev, bucket=bucket,
            t_dispatch_ns=t_dispatch_ns, t_dispatched_ns=t_dispatched_ns)

    def _complete(self, inflight: _InFlight) -> List[FleetResult]:
        """Stage 2 of a flush: force the host transfer, threshold labels,
        publish the whole flush in one batched bus call."""
        tracing = self._tracer.enabled
        t_synced = self.clock()
        with self.metrics.timer.stage("device"):
            probs = np.asarray(inflight.probs_dev)  # blocks: host array
        t_device = self.clock()
        t_device_ns = now_ns() if tracing else 0

        results = []
        messages = [] if self.bus is not None else None
        t_pub0_ns = 0
        with self.metrics.timer.stage("publish"):
            for i, tick in enumerate(inflight.live):
                # the persistent pipeline lets close_session (and a
                # same-id reopen, which restarts seq at 0) run between
                # dispatch and completion — publishing the dead
                # incarnation's result would interleave a colliding
                # (session, seq) into the new stream.  Same "freed
                # session's ticks drop, visibly" invariant as dispatch,
                # at the completion boundary.
                if not self.pool.is_live(tick.handle):
                    self.metrics.count("stale_results_dropped")
                    continue
                p = probs[i]
                _, labels = labels_over_threshold(
                    p, self.threshold, self.y_fields)
                results.append(FleetResult(
                    tick.handle.session_id, tick.seq, p, labels,
                    self.weights_version))
                if messages is not None:
                    msg = {
                        "session": tick.handle.session_id,
                        "seq": tick.seq,
                        "probabilities": [float(v) for v in p],
                        "pred_labels": list(labels),
                        "prob_threshold": self.threshold,
                    }
                    if self.weights_version is not None:
                        msg["weights_version"] = self.weights_version
                    # the tick's context in-band, so downstream
                    # consumers stitch into the same trace; an incoming
                    # wire (multi-host router) is forwarded even when
                    # this process's tracer is off — the router still
                    # closes its root off the result
                    wire = tick.wire if tick.wire is not None else (
                        tick.trace.wire if tick.trace is not None
                        else None)
                    if wire is not None:
                        msg["trace"] = wire
                    messages.append(msg)
            if messages:
                # one batched publish per flush: one lock acquisition /
                # native call sequence instead of per-tick bus overhead
                wire_msgs = messages
                if self.result_blocks and len(messages) > 1:
                    # the whole flush as ONE columnar block: a (B, C)
                    # f32 probability array + dictionary-encoded ids
                    # instead of B dicts boxing a few floats each —
                    # bit-identical on decode (wire tests assert it).
                    # An unpackable flush (a >63-label vocabulary, a
                    # mixed threshold) degrades to the always-correct
                    # per-tick dialect, counted — the state advance
                    # behind these results is irreversible, so packing
                    # must never be the reason they are lost
                    try:
                        wire_msgs = [
                            codec.pack_results(messages, self.y_fields)]
                    except codec.CodecError as e:
                        self.metrics.count("result_pack_errors")
                        log.warning(
                            "result-block packing failed (%s) — "
                            "publishing the per-tick dialect", e)
                t_pub0_ns = now_ns() if tracing else 0
                try:
                    if self._publish_many is not None:
                        self._publish_many(self.prediction_topic, wire_msgs)
                    else:
                        for msg in wire_msgs:
                            self.bus.publish(self.prediction_topic, msg)
                except Exception:
                    # the transport failed AFTER the state advance —
                    # _complete_counted marks the ticks lost; this
                    # counter splits "bus down" from "transfer failed"
                    # on dashboards (the chaos soak keys on it)
                    self.metrics.count("publish_errors")
                    raise
        t_publish = self.clock()

        m = self.metrics
        m.count("ticks_served", len(results))
        if results:
            v = (self.weights_version
                 if self.weights_version is not None else 0)
            self._version_ticks[v] = (
                self._version_ticks.get(v, 0) + len(results))
        m.observe("device", t_device - t_synced)
        m.observe("publish", t_publish - t_device)
        for tick in inflight.live:
            m.observe("total", t_publish - tick.t_enqueue)
        if tracing:
            self._record_flush_spans(inflight, t_device_ns, t_pub0_ns)
        return results

    def _record_flush_spans(
        self, inflight: _InFlight, t_device_ns: int, t_pub0_ns: int
    ) -> None:
        """Close the trace of every sampled tick in a completed flush.

        The four children tile the root exactly — queued [submit →
        dispatch start], dispatch [assembly + async enqueue], device
        [enqueue return → results on host; under the persistent overlap
        pipeline this is where the hidden device/pipeline wait lives],
        publish [thresholding + batched bus publish] — so a trace's
        stage breakdown sums to its e2e duration by construction
        (`python -m fmda_tpu trace`, docs/OPERATIONS.md §4d).
        """
        if not inflight.t_dispatch_ns:
            return  # dispatched before tracing was enabled: no timeline
        tr = self._tracer
        t_publish_ns = now_ns()
        for tick in inflight.live:
            ref = tick.trace
            if ref is None:
                continue
            tid = ref.trace_id
            if tick.wire is not None:
                # the tick arrived with a router's context: group this
                # process's stage spans under one "serve" span on the
                # ROUTER's trace (no second root, no double e2e count —
                # the router's finish_root owns the journey)
                root = tr.add_span(tid, ref.span_id, "serve", "serve",
                                   ref.t0_ns, t_publish_ns)
            else:
                root = ref.span_id
            tr.add_span(tid, root, "queued", "gateway",
                        ref.t0_ns, inflight.t_dispatch_ns)
            tr.add_span(tid, root, "dispatch", "gateway",
                        inflight.t_dispatch_ns, inflight.t_dispatched_ns)
            tr.add_span(tid, root, "device", "engine",
                        inflight.t_dispatched_ns, t_device_ns)
            pub = tr.add_span(tid, root, "publish", "publish",
                              t_device_ns, t_publish_ns)
            if t_pub0_ns:
                tr.add_span(tid, pub, "bus_publish", "bus",
                            t_pub0_ns, t_publish_ns)
            if tick.wire is None:
                tr.finish_root(ref, "tick", "ingest", t_publish_ns)
