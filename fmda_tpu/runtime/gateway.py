"""Admission-control front door: bounded queue → micro-batcher → pool → bus.

One :class:`FleetGateway` owns the serving loop for a fleet of sessions:

- ``open_session``/``close_session`` — admission control against the
  slot pool (a full pool **rejects loudly**, it never queues forever);
- ``submit`` — enqueue a session's newest row behind a **bounded** queue;
  overload sheds the *oldest* queued tick with a counted metric
  (``shed_oldest``) — stale market data is the cheapest thing to lose,
  and an unbounded queue is how serving systems die;
- ``pump`` — flush micro-batches whenever the batcher says so
  (batch-full or deadline), run the one fused pool step, and publish each
  session's result on the :class:`~fmda_tpu.stream.bus.MessageBus`
  (``fleet_prediction`` topic, ``session`` field keying per-session
  consumption) — the same transport every other stage of the framework
  already speaks.

**The overlap pipeline** (ISSUE 3): dispatching a flush and consuming
its results are split into :meth:`FleetGateway._dispatch` (stale filter,
staging-buffer assembly, async ``SessionPool.step_device``) and
:meth:`FleetGateway._complete` (host transfer, label thresholding, one
batched bus publish).  ``pump`` runs them one flush apart — while flush
k's probabilities cross the host boundary and fan out to the bus, flush
k+1 is already assembled and enqueued on the device.  The pipeline is
one deep and strictly local to each ``pump`` call: every result a call
flushed is returned by that call, so the external contract (and the
numbers) are identical to the serial path — ``pipeline_depth=0`` forces
serial for A/B tests.  Batch assembly writes into pre-allocated
per-bucket staging buffers (double-buffered, because a one-deep pipeline
has at most one prior flush whose dispatch may still read its staging),
killing the two per-flush array allocations.

Every tick's journey is measured (enqueue→dispatch→device→publish
histograms in :class:`~fmda_tpu.runtime.metrics.RuntimeMetrics`); every
loss path is a counter, never a silent drop.  Under overlap, ``device``
measures the time ``_complete`` spends *blocked* on the transfer —
overlapped device work hides inside the preceding ``dispatch``/
``publish`` wall clock, which is the point.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from fmda_tpu.config import (
    DEFAULT_QUEUE_BOUND,
    TARGET_COLUMNS,
    TOPIC_FLEET_PREDICTION,
)
from fmda_tpu.data.normalize import NormParams
from fmda_tpu.runtime.batcher import BatcherConfig, MicroBatcher, Tick
from fmda_tpu.runtime.metrics import RuntimeMetrics
from fmda_tpu.runtime.session_pool import (
    PoolExhausted,
    SessionHandle,
    SessionPool,
)
from fmda_tpu.serve.predictor import labels_over_threshold

log = logging.getLogger("fmda_tpu.runtime")


@dataclass(frozen=True)
class FleetResult:
    """One served tick: the probabilities for one session's newest row."""

    session_id: str
    seq: int
    probabilities: np.ndarray
    labels: Tuple[str, ...]


@dataclass
class _InFlight:
    """A dispatched-but-unconsumed flush: the device handle to its
    probabilities plus everything ``_complete`` needs to publish them."""

    live: List[Tick]
    probs_dev: object  # (bucket, n_classes) device array
    bucket: int


class FleetGateway:
    """Multiplexes many ticker sessions onto one batched serving step."""

    #: Log every Nth shed (the counter is the source of truth; the log is
    #: a human-visible heartbeat that shedding is happening).
    SHED_LOG_EVERY = 1000

    def __init__(
        self,
        pool: SessionPool,
        bus=None,
        *,
        batcher_config: Optional[BatcherConfig] = None,
        queue_bound: int = DEFAULT_QUEUE_BOUND,
        metrics: Optional[RuntimeMetrics] = None,
        clock: Callable[[], float] = time.monotonic,
        prediction_topic: str = TOPIC_FLEET_PREDICTION,
        threshold: float = 0.5,
        y_fields: Tuple[str, ...] = TARGET_COLUMNS,
        pipeline_depth: int = 1,
    ) -> None:
        if queue_bound < 1:
            raise ValueError(f"queue_bound must be >= 1, got {queue_bound}")
        if pipeline_depth not in (0, 1):
            raise ValueError(
                f"pipeline_depth must be 0 (serial) or 1 (one-deep "
                f"overlap), got {pipeline_depth}")
        if bus is not None and prediction_topic not in bus.topics():
            # fail at construction, not mid-flush: a publish KeyError
            # after pool.step would lose results whose state advance is
            # irreversible (pre-PR-1 configs with an explicit bus.topics
            # list lack the fleet topic)
            raise ValueError(
                f"bus has no topic {prediction_topic!r} (configured: "
                f"{sorted(bus.topics())}); add it to bus.topics — the "
                "default layout includes it as TOPIC_FLEET_PREDICTION")
        self.pool = pool
        self.bus = bus
        self.queue_bound = queue_bound
        self.metrics = metrics or RuntimeMetrics()
        self.clock = clock
        self.prediction_topic = prediction_topic
        self.threshold = threshold
        self.y_fields = tuple(y_fields)
        #: 1 = one-deep overlap pipeline (default); 0 = serial flushes
        #: (the A/B reference the bit-identity tests compare against).
        self.pipeline_depth = pipeline_depth
        self.batcher = MicroBatcher(batcher_config, clock=clock)
        self._seq: Dict[str, int] = {}
        # pre-allocated per-bucket staging for batch assembly, two
        # (slots, rows) pairs per bucket: with a one-deep pipeline at
        # most one earlier flush's dispatch can still be reading its
        # staging (jax may alias host numpy on CPU), and its completion
        # — which always precedes reusing the same parity — forces that
        # read to have finished
        self._staging: Dict[int, list] = {}
        self._staging_idx: Dict[int, int] = {}
        self._publish_many = (
            getattr(bus, "publish_many", None) if bus is not None else None)

    # -- admission ----------------------------------------------------------

    def open_session(
        self, session_id: str, norm: Optional[NormParams] = None
    ) -> SessionHandle:
        """Admit a session (raises :class:`PoolExhausted` when the fleet
        is full — counted, so rejected admissions show up on dashboards,
        and the caller decides whether to retry, evict, or scale)."""
        try:
            handle = self.pool.alloc(session_id, norm)
        except PoolExhausted:
            # only capacity rejections count here — a duplicate-id
            # ValueError is a client bug, not a fleet-is-full signal
            self.metrics.count("rejected_sessions")
            raise
        self._sessions_changed()
        return handle

    def close_session(self, session_id: str) -> None:
        handle = self.pool.handle_for(session_id)
        if handle is None:
            raise KeyError(f"no open session {session_id!r}")
        self.pool.free(handle)
        self._seq.pop(session_id, None)
        self._sessions_changed()

    def _sessions_changed(self) -> None:
        self.metrics.gauge("active_sessions", self.pool.n_active)
        # when every active session is already pending a flush cannot
        # grow — tell the batcher so small fleets don't wait out the
        # linger on every steady-state flush
        self.batcher.full_target = self.pool.n_active

    # -- the request path ---------------------------------------------------

    def submit(self, session_id: str, row: np.ndarray) -> int:
        """Enqueue a session's newest feature row; returns the tick's
        per-session sequence number.  Overload sheds the oldest queued
        tick (counted + heartbeat-logged), never blocks, never grows the
        queue past ``queue_bound``."""
        handle = self.pool.handle_for(session_id)
        if handle is None:
            raise KeyError(f"no open session {session_id!r}")
        row = np.array(row, np.float32)  # copy: the queue must OWN rows
        if row.shape != (self.pool.cfg.n_features,):
            # reject at the submitter — a malformed row reaching _flush
            # would throw there and lose the whole batch's other ticks
            raise ValueError(
                f"row shape {row.shape} != ({self.pool.cfg.n_features},) "
                f"for session {session_id!r}")
        while len(self.batcher) >= self.queue_bound:
            shed = self.batcher.shed_oldest()
            self.metrics.count("shed_oldest")
            n = self.metrics.counters["shed_oldest"]
            if n == 1 or n % self.SHED_LOG_EVERY == 0:
                log.warning(
                    "queue full (bound=%d): shed oldest tick (session %s, "
                    "seq %d); %d shed so far",
                    self.queue_bound, shed.handle.session_id, shed.seq, n)
        seq = self._seq.get(session_id, 0)
        self._seq[session_id] = seq + 1
        self.batcher.add(Tick(
            handle=handle, row=row, t_enqueue=self.clock(), seq=seq))
        self.metrics.gauge("queue_depth", len(self.batcher))
        return seq

    @property
    def saturated(self) -> bool:
        """Backpressure signal: the next submit will shed.  Well-behaved
        producers check this and slow down instead of racing the shedder."""
        return len(self.batcher) >= self.queue_bound

    # -- the serving loop ---------------------------------------------------

    def pump(self, *, force: bool = False) -> List[FleetResult]:
        """Flush ready micro-batches (all pending ones when ``force`` —
        the drain path).  Returns every result served this call; each is
        also published on the bus when one is attached.

        Consecutive flushes run through the one-deep overlap pipeline:
        flush k+1 is assembled and dispatched *before* flush k's
        probabilities are pulled to the host and published, so the
        device computes k+1 while the host finishes k.  The pipeline
        never outlives the call — the final in-flight flush is completed
        before returning, so callers see exactly the serial contract.
        """
        results: List[FleetResult] = []
        inflight: Optional[_InFlight] = None
        try:
            while True:
                if force:
                    if not len(self.batcher):
                        break
                elif not self.batcher.ready(self.clock()):
                    break
                ticks = self.batcher.take_batch()
                if not ticks:
                    break
                nxt = self._dispatch(ticks)
                # hand the previous flush off BEFORE completing it, so a
                # completion failure can never strand the just-dispatched
                # one (its state advance is already irreversible)
                prev, inflight = inflight, nxt
                if prev is not None:
                    if nxt is not None:
                        self.metrics.count("overlapped_flushes")
                    results.extend(self._complete_counted(prev))
                if self.pipeline_depth == 0 and inflight is not None:
                    prev, inflight = inflight, None
                    results.extend(self._complete_counted(prev))
            if inflight is not None:  # drain the trailing in-flight flush
                prev, inflight = inflight, None
                results.extend(self._complete_counted(prev))
        finally:
            # reached with a live in-flight only when unwinding an
            # exception: the flush's pool-state advance already happened,
            # so its results must still be published (consumers stay
            # consistent with the recurrence) — and if even that fails,
            # _complete_counted made the loss a counter, never silence
            if inflight is not None:
                try:
                    results.extend(self._complete_counted(inflight))
                except Exception:  # noqa: BLE001 — don't mask the unwind
                    log.exception(
                        "in-flight flush lost while unwinding pump failure")
            self.metrics.gauge("queue_depth", len(self.batcher))
        return results

    def _complete_counted(self, inflight: _InFlight) -> List[FleetResult]:
        """:meth:`_complete` with the loss path counted: a completion
        failure (bus publish error, transfer failure) marks its ticks
        ``flush_results_lost`` before propagating — the state advance
        behind them is irreversible, so the loss must be visible."""
        try:
            return self._complete(inflight)
        except Exception:
            self.metrics.count("flush_results_lost", len(inflight.live))
            raise

    def drain(self) -> List[FleetResult]:
        """Serve everything still queued, deadline or not (shutdown/end
        of load)."""
        return self.pump(force=True)

    def _staging_for(self, bucket: int):
        """The next (slots, rows) staging pair for ``bucket`` —
        pre-allocated once per bucket, alternating between two parities
        (see the constructor comment for why two suffice)."""
        bufs = self._staging.get(bucket)
        if bufs is None:
            bufs = [
                (np.full(bucket, self.pool.padding_slot, np.int32),
                 np.zeros((bucket, self.pool.cfg.n_features), np.float32))
                for _ in range(2)
            ]
            self._staging[bucket] = bufs
            self._staging_idx[bucket] = 0
        idx = self._staging_idx[bucket]
        self._staging_idx[bucket] = 1 - idx
        return bufs[idx]

    def _dispatch(self, ticks: List[Tick]) -> Optional[_InFlight]:
        """Stage 1 of a flush: stale-filter, assemble into the bucket's
        staging buffers, enqueue the pool step on the device.  Returns
        the in-flight record (None if every tick went stale in queue)."""
        t_dispatch = self.clock()
        live = []
        for tick in ticks:
            # a session freed while its tick was queued: drop, visibly
            if self.pool.is_live(tick.handle):
                live.append(tick)
            else:
                self.metrics.count("stale_dropped")
        if not live:
            return None
        bucket = self.batcher.bucket_for(len(live))
        slots, rows = self._staging_for(bucket)
        for i, tick in enumerate(live):
            slots[i] = tick.handle.slot
            rows[i] = tick.row
        # lanes past len(live) keep stale rows from the buffer's last
        # use — harmless by construction (they compute into the padding
        # slot, state nothing reads) — but their slot entries MUST be
        # re-pointed at the padding lane
        slots[len(live):] = self.pool.padding_slot
        with self.metrics.timer.stage("dispatch"):
            probs_dev = self.pool.step_device(slots, rows)  # async enqueue
        t_dispatched = self.clock()

        m = self.metrics
        m.count("flushes")
        m.count(f"flushes_bucket_{bucket}")
        m.count("padded_lanes", bucket - len(live))
        m.observe("dispatch", t_dispatched - t_dispatch)
        for tick in live:
            m.observe("enqueue_to_dispatch", t_dispatch - tick.t_enqueue)
        return _InFlight(live=live, probs_dev=probs_dev, bucket=bucket)

    def _complete(self, inflight: _InFlight) -> List[FleetResult]:
        """Stage 2 of a flush: force the host transfer, threshold labels,
        publish the whole flush in one batched bus call."""
        t_synced = self.clock()
        with self.metrics.timer.stage("device"):
            probs = np.asarray(inflight.probs_dev)  # blocks: host array
        t_device = self.clock()

        results = []
        messages = [] if self.bus is not None else None
        with self.metrics.timer.stage("publish"):
            for i, tick in enumerate(inflight.live):
                p = probs[i]
                _, labels = labels_over_threshold(
                    p, self.threshold, self.y_fields)
                results.append(FleetResult(
                    tick.handle.session_id, tick.seq, p, labels))
                if messages is not None:
                    messages.append({
                        "session": tick.handle.session_id,
                        "seq": tick.seq,
                        "probabilities": [float(v) for v in p],
                        "pred_labels": list(labels),
                        "prob_threshold": self.threshold,
                    })
            if messages:
                # one batched publish per flush: one lock acquisition /
                # native call sequence instead of per-tick bus overhead
                if self._publish_many is not None:
                    self._publish_many(self.prediction_topic, messages)
                else:
                    for msg in messages:
                        self.bus.publish(self.prediction_topic, msg)
        t_publish = self.clock()

        m = self.metrics
        m.count("ticks_served", len(inflight.live))
        m.observe("device", t_device - t_synced)
        m.observe("publish", t_publish - t_device)
        for tick in inflight.live:
            m.observe("total", t_publish - tick.t_enqueue)
        return results
