"""Admission-control front door: bounded queue → micro-batcher → pool → bus.

One :class:`FleetGateway` owns the serving loop for a fleet of sessions:

- ``open_session``/``close_session`` — admission control against the
  slot pool (a full pool **rejects loudly**, it never queues forever);
- ``submit`` — enqueue a session's newest row behind a **bounded** queue;
  overload sheds the *oldest* queued tick with a counted metric
  (``shed_oldest``) — stale market data is the cheapest thing to lose,
  and an unbounded queue is how serving systems die;
- ``pump`` — flush micro-batches whenever the batcher says so
  (batch-full or deadline), run the one fused pool step, and publish each
  session's result on the :class:`~fmda_tpu.stream.bus.MessageBus`
  (``fleet_prediction`` topic, ``session`` field keying per-session
  consumption) — the same transport every other stage of the framework
  already speaks.

Every tick's journey is measured (enqueue→dispatch→device→publish
histograms in :class:`~fmda_tpu.runtime.metrics.RuntimeMetrics`); every
loss path is a counter, never a silent drop.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from fmda_tpu.config import (
    DEFAULT_QUEUE_BOUND,
    TARGET_COLUMNS,
    TOPIC_FLEET_PREDICTION,
)
from fmda_tpu.data.normalize import NormParams
from fmda_tpu.runtime.batcher import BatcherConfig, MicroBatcher, Tick
from fmda_tpu.runtime.metrics import RuntimeMetrics
from fmda_tpu.runtime.session_pool import (
    PoolExhausted,
    SessionHandle,
    SessionPool,
)
from fmda_tpu.serve.predictor import labels_over_threshold

log = logging.getLogger("fmda_tpu.runtime")


@dataclass(frozen=True)
class FleetResult:
    """One served tick: the probabilities for one session's newest row."""

    session_id: str
    seq: int
    probabilities: np.ndarray
    labels: Tuple[str, ...]


class FleetGateway:
    """Multiplexes many ticker sessions onto one batched serving step."""

    #: Log every Nth shed (the counter is the source of truth; the log is
    #: a human-visible heartbeat that shedding is happening).
    SHED_LOG_EVERY = 1000

    def __init__(
        self,
        pool: SessionPool,
        bus=None,
        *,
        batcher_config: Optional[BatcherConfig] = None,
        queue_bound: int = DEFAULT_QUEUE_BOUND,
        metrics: Optional[RuntimeMetrics] = None,
        clock: Callable[[], float] = time.monotonic,
        prediction_topic: str = TOPIC_FLEET_PREDICTION,
        threshold: float = 0.5,
        y_fields: Tuple[str, ...] = TARGET_COLUMNS,
    ) -> None:
        if queue_bound < 1:
            raise ValueError(f"queue_bound must be >= 1, got {queue_bound}")
        if bus is not None and prediction_topic not in bus.topics():
            # fail at construction, not mid-flush: a publish KeyError
            # after pool.step would lose results whose state advance is
            # irreversible (pre-PR-1 configs with an explicit bus.topics
            # list lack the fleet topic)
            raise ValueError(
                f"bus has no topic {prediction_topic!r} (configured: "
                f"{sorted(bus.topics())}); add it to bus.topics — the "
                "default layout includes it as TOPIC_FLEET_PREDICTION")
        self.pool = pool
        self.bus = bus
        self.queue_bound = queue_bound
        self.metrics = metrics or RuntimeMetrics()
        self.clock = clock
        self.prediction_topic = prediction_topic
        self.threshold = threshold
        self.y_fields = tuple(y_fields)
        self.batcher = MicroBatcher(batcher_config, clock=clock)
        self._seq: Dict[str, int] = {}

    # -- admission ----------------------------------------------------------

    def open_session(
        self, session_id: str, norm: Optional[NormParams] = None
    ) -> SessionHandle:
        """Admit a session (raises :class:`PoolExhausted` when the fleet
        is full — counted, so rejected admissions show up on dashboards,
        and the caller decides whether to retry, evict, or scale)."""
        try:
            handle = self.pool.alloc(session_id, norm)
        except PoolExhausted:
            # only capacity rejections count here — a duplicate-id
            # ValueError is a client bug, not a fleet-is-full signal
            self.metrics.count("rejected_sessions")
            raise
        self._sessions_changed()
        return handle

    def close_session(self, session_id: str) -> None:
        handle = self.pool.handle_for(session_id)
        if handle is None:
            raise KeyError(f"no open session {session_id!r}")
        self.pool.free(handle)
        self._seq.pop(session_id, None)
        self._sessions_changed()

    def _sessions_changed(self) -> None:
        self.metrics.gauge("active_sessions", self.pool.n_active)
        # when every active session is already pending a flush cannot
        # grow — tell the batcher so small fleets don't wait out the
        # linger on every steady-state flush
        self.batcher.full_target = self.pool.n_active

    # -- the request path ---------------------------------------------------

    def submit(self, session_id: str, row: np.ndarray) -> int:
        """Enqueue a session's newest feature row; returns the tick's
        per-session sequence number.  Overload sheds the oldest queued
        tick (counted + heartbeat-logged), never blocks, never grows the
        queue past ``queue_bound``."""
        handle = self.pool.handle_for(session_id)
        if handle is None:
            raise KeyError(f"no open session {session_id!r}")
        row = np.array(row, np.float32)  # copy: the queue must OWN rows
        if row.shape != (self.pool.cfg.n_features,):
            # reject at the submitter — a malformed row reaching _flush
            # would throw there and lose the whole batch's other ticks
            raise ValueError(
                f"row shape {row.shape} != ({self.pool.cfg.n_features},) "
                f"for session {session_id!r}")
        while len(self.batcher) >= self.queue_bound:
            shed = self.batcher.shed_oldest()
            self.metrics.count("shed_oldest")
            n = self.metrics.counters["shed_oldest"]
            if n == 1 or n % self.SHED_LOG_EVERY == 0:
                log.warning(
                    "queue full (bound=%d): shed oldest tick (session %s, "
                    "seq %d); %d shed so far",
                    self.queue_bound, shed.handle.session_id, shed.seq, n)
        seq = self._seq.get(session_id, 0)
        self._seq[session_id] = seq + 1
        self.batcher.add(Tick(
            handle=handle, row=row, t_enqueue=self.clock(), seq=seq))
        self.metrics.gauge("queue_depth", len(self.batcher))
        return seq

    @property
    def saturated(self) -> bool:
        """Backpressure signal: the next submit will shed.  Well-behaved
        producers check this and slow down instead of racing the shedder."""
        return len(self.batcher) >= self.queue_bound

    # -- the serving loop ---------------------------------------------------

    def pump(self, *, force: bool = False) -> List[FleetResult]:
        """Flush ready micro-batches (all pending ones when ``force`` —
        the drain path).  Returns every result served this call; each is
        also published on the bus when one is attached."""
        results: List[FleetResult] = []
        while True:
            if force:
                if not len(self.batcher):
                    break
            elif not self.batcher.ready(self.clock()):
                break
            ticks = self.batcher.take_batch()
            if not ticks:
                break
            results.extend(self._flush(ticks))
        self.metrics.gauge("queue_depth", len(self.batcher))
        return results

    def drain(self) -> List[FleetResult]:
        """Serve everything still queued, deadline or not (shutdown/end
        of load)."""
        return self.pump(force=True)

    def _flush(self, ticks: List[Tick]) -> List[FleetResult]:
        t_dispatch = self.clock()
        live = []
        for tick in ticks:
            # a session freed while its tick was queued: drop, visibly
            if self.pool.is_live(tick.handle):
                live.append(tick)
            else:
                self.metrics.count("stale_dropped")
        if not live:
            return []
        bucket = self.batcher.bucket_for(len(live))
        slots = np.full(bucket, self.pool.padding_slot, np.int32)
        rows = np.zeros((bucket, self.pool.cfg.n_features), np.float32)
        for i, tick in enumerate(live):
            slots[i] = tick.handle.slot
            rows[i] = tick.row
        # "device" measures ONLY the jit step (+ its host transfer), not
        # the stale filter or batch assembly above — those land between
        # enqueue_to_dispatch and device, and always inside "total"
        t_assembled = self.clock()
        with self.metrics.timer.stage("device"):
            probs = self.pool.step(slots, rows)  # blocks: host np array
        t_device = self.clock()

        results = []
        with self.metrics.timer.stage("publish"):
            for i, tick in enumerate(live):
                p = probs[i]
                _, labels = labels_over_threshold(
                    p, self.threshold, self.y_fields)
                results.append(FleetResult(
                    tick.handle.session_id, tick.seq, p, labels))
                if self.bus is not None:
                    self.bus.publish(self.prediction_topic, {
                        "session": tick.handle.session_id,
                        "seq": tick.seq,
                        "probabilities": [float(v) for v in p],
                        "pred_labels": list(labels),
                        "prob_threshold": self.threshold,
                    })
        t_publish = self.clock()

        m = self.metrics
        m.count("flushes")
        m.count("ticks_served", len(live))
        m.count(f"flushes_bucket_{bucket}")
        m.count("padded_lanes", bucket - len(live))
        m.observe("device", t_device - t_assembled)
        m.observe("publish", t_publish - t_device)
        for tick in live:
            m.observe("enqueue_to_dispatch", t_dispatch - tick.t_enqueue)
            m.observe("total", t_publish - tick.t_enqueue)
        return results
