"""Slot-pool session manager: N carried streaming states in one state tree.

The streaming carriers (:class:`fmda_tpu.serve.streaming.StreamingBiGRU`)
already accept a ``batch`` dimension, but a fixed batch serves tickers in
lockstep — every row advances every lane.  A serving fleet is the opposite
shape: thousands of independent sessions, each ticking on its own clock,
and any given micro-batch carries rows for an arbitrary *subset* of them.

:class:`SessionPool` packs up to ``capacity`` carried states into one
``(capacity+1, ...)`` state tree and exposes a single jitted step over a
*gather → batched cell → scatter* program:

- ``slots (B,)`` selects which sessions this flush advances; their carry,
  ring, and tick positions are gathered, advanced with exactly the solo
  carrier's ops (same normalize → input-proj → gate → ring-update →
  masked-pool → head sequence, so a multiplexed session is bit-identical
  to a solo run), and scattered back;
- the extra slot (index ``capacity``) is the **padding lane**: micro-batch
  lanes beyond the real request count point at it, so padded flushes need
  no active-lane mask inside the step — padding writes land in state no
  session reads ("dead slots don't pollute pooling" by construction);
- per-slot **generation counters** guard reuse: ``free`` bumps the slot's
  generation, so a :class:`SessionHandle` kept past ``free`` can never
  read or advance a recycled slot (the stale-session bug class of every
  slot-reuse cache; see the O(1)-cache serving papers in PAPERS.md).

The step is compiled once per distinct batch size ``B``; the micro-batcher
(:mod:`fmda_tpu.runtime.batcher`) quantises ``B`` to a few bucket sizes so
XLA compiles a handful of programs and replays them forever
(:attr:`SessionPool.compile_count` is the proof hook tests assert on).

Two serving-hot-path disciplines (ISSUE 3):

- **Donation** — the jitted step donates the carry/ring/pos buffers
  (``donate_argnums``), so XLA advances the pooled state *in place*
  instead of allocating and copying the whole (capacity+1, ...) tree on
  every flush.  The pool immediately rebinds its state attributes to the
  step's outputs, so no caller can observe the consumed buffers.
- **Async dispatch** — :meth:`step_device` returns the *device* array of
  probabilities without forcing the host transfer; the gateway overlaps
  flush k's transfer+publish with flush k+1's assembly+dispatch
  (:mod:`fmda_tpu.runtime.gateway`, the one-deep in-flight pipeline).
  :meth:`step` keeps the old blocking contract for direct callers.

**Sharding** — pass ``mesh`` to shard the *slot* axis of the state tree
across chips with :class:`~jax.sharding.NamedSharding` over the existing
(dp, sp) mesh (:mod:`fmda_tpu.parallel.mesh`): fleet capacity then scales
with device count (each chip holds ``n_slots / dp`` sessions' state; the
gather/scatter crosses chips only for the lanes that live elsewhere).
The slot count is padded up to a multiple of the dp axis so every shard
is equal-sized; the extra lanes are permanent padding nothing ever
allocates.  A ``mesh`` spanning **one** device (or ``mesh=None``) takes
the exact unsharded code path — bit-identical to the pre-sharding pool.

Scope: the unidirectional recurrent carriers (``cell="gru"``/``"lstm"``/
``"ssm"``, any ``n_layers`` — the pure O(1)-per-tick cores).
Bidirectional or attn serving re-encodes a window per tick; multiplex
those through the window-re-scan
:class:`~fmda_tpu.serve.predictor.Predictor` instead.

The ``cell="ssm"`` pool carries the family's **constant-size cache**:
three H-vectors per layer per session, a zero-width ring (the EMA head
needs no window state), and no per-tick matmul or gather beyond the
slot indexing — the smallest state tree of the families, which is what
donation, migration export (:meth:`export_slot`), and the columnar wire
blocks then move (docs/runtime.md "The SSM cell family").
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fmda_tpu.config import ModelConfig
from fmda_tpu.data.normalize import NormParams
from fmda_tpu.obs.device import tracked_jit
from fmda_tpu.serve.streaming import (
    _recurrent_cell_ops,
    advance_cells,
    ema_head_logits,
    pooled_head_logits,
)

log = logging.getLogger("fmda_tpu.runtime")


class PoolExhausted(Exception):
    """alloc() on a pool with no free slots (admission control reacts)."""


class StaleSessionError(Exception):
    """A SessionHandle used after its slot was freed (or re-allocated)."""


@dataclass(frozen=True)
class SessionHandle:
    """A claim on one pool slot, valid for exactly one generation."""

    session_id: str
    slot: int
    generation: int


class SessionPool:
    """Fixed-capacity pool of carried streaming states (one jitted step).

    ``alloc``/``free``/``reset`` manage slots host-side, off the hot
    path (each functional ``.at[slot].set`` update copies its
    (capacity+1, ...) array, so slot churn costs O(capacity) per call —
    fine at serving-session churn rates; a donate-based fused reset is
    the known optimisation if admission ever becomes hot).  ``step`` /
    ``step_device`` are the hot path — one fused jit call advancing every
    session named in ``slots`` by one tick, with the carry/ring/pos
    buffers donated so the state advances in place.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        capacity: int,
        window: int,
        mesh=None,
        shard_axis: str = "dp",
    ) -> None:
        cell_ops = _recurrent_cell_ops(cfg.cell, use_pallas=cfg.use_pallas)
        gate_step, self._n_carry = cell_ops.gate_step, cell_ops.n_carry
        self._head = cell_ops.head
        if cfg.bidirectional:
            raise ValueError(
                "SessionPool multiplexes the unidirectional carried-state "
                "cores (O(1)/tick); serve bidirectional models through the "
                "window-re-scan Predictor."
            )
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.cfg = cfg
        self.capacity = capacity
        self.window = window
        #: The padding lane every padded micro-batch points its unused
        #: lanes at — state no session is ever allocated.
        self.padding_slot = capacity
        self._dtype = jnp.dtype(cfg.dtype)
        dtype = self._dtype
        self._params = jax.tree.map(
            lambda a: jnp.asarray(a).astype(dtype), params)

        self.mesh = mesh
        self.n_shards = int(mesh.shape[shard_axis]) if mesh is not None else 1
        n_slots = capacity + 1
        if self.n_shards > 1:
            # pad the slot axis to a multiple of the shard count so every
            # chip holds an equal block; lanes past `capacity` are
            # permanent padding (never in the free list, never indexed)
            n_slots = -(-n_slots // self.n_shards) * self.n_shards
        #: Leading-axis length of every state leaf (>= capacity + 1).
        self.n_slots = n_slots
        if self.n_shards > 1:
            from fmda_tpu.parallel.mesh import (
                replicated_sharding,
                slot_sharding,
            )

            self._state_sharding = slot_sharding(mesh, shard_axis)
            self._repl_sharding = replicated_sharding(mesh)
            self._params = jax.tree.map(
                lambda a: jax.device_put(a, self._repl_sharding),
                self._params)

            def place(a):
                return jax.device_put(a, self._state_sharding)
        else:
            self._state_sharding = None
            self._repl_sharding = None

            def place(a):
                return a

        #: Re-pins a state leaf to the slot sharding after a host-side
        #: functional update (alloc/reset), so the jitted step's donation
        #: aliasing never sees a drifted layout.  Identity when unsharded.
        self._place_state = place

        hidden = cfg.hidden_size
        feats = cfg.n_features
        self._carry = tuple(
            tuple(place(jnp.zeros((n_slots, hidden), dtype))
                  for _ in range(self._n_carry))
            for _ in range(cfg.n_layers))
        # carry-head cells (ssm) keep a ZERO-WIDTH ring: the pooling
        # state lives inside the cell carry, so nothing in the pooled
        # tree is sized by `window` — donation, export_slot, and the
        # wire codec all carry the same (tiny) leaf unchanged
        ring_w = window if self._head == "ring" else 0
        self._ring = place(jnp.zeros((n_slots, ring_w, hidden), dtype))
        self._pos = place(jnp.zeros((n_slots,), jnp.int32))
        # per-slot normalization (sessions serve different tickers with
        # different price scales), gathered alongside the state
        self._x_min = place(jnp.zeros((n_slots, feats), jnp.float32))
        self._x_range = place(jnp.ones((n_slots, feats), jnp.float32))

        # host-side slot bookkeeping
        self._generations = [0] * capacity
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._by_id: Dict[str, SessionHandle] = {}
        # fallback compile accounting for compile_count (distinct batch
        # sizes dispatched == programs compiled, since everything else
        # in the step signature is shape-stable)
        self._batch_sizes_seen: set = set()

        w = window

        def step(params, carry, ring, pos, x_min, x_range, slots, rows):
            """Advance the sessions in ``slots`` by one row each.

            Gather → the solo carrier's per-tick math
            (:func:`~fmda_tpu.serve.streaming.advance_cells` +
            :func:`~fmda_tpu.serve.streaming.pooled_head_logits`, shared
            code, not a copy) on a (B, ...) slice → scatter.  ``slots``
            must be duplicate-free over *live* slots (the batcher
            guarantees one row per session per flush); the padding lane
            may repeat freely — its scattered writes collide only with
            each other, in state nothing reads.
            """
            x = ((rows - x_min[slots]) / x_range[slots]).astype(dtype)
            pos_b = pos[slots]
            carry_b = tuple(
                tuple(c[slots] for c in layer) for layer in carry)
            h_new, carry_new = advance_cells(params, cfg, gate_step, x,
                                             carry_b)
            if self._head == "carry":
                # ssm: pooling state rides the carry; the zero-width
                # ring passes through untouched (kept for a uniform
                # step signature/donation layout)
                logits = ema_head_logits(params, h_new, carry_new[-1])
            else:
                ring = ring.at[slots, pos_b % w].set(h_new)
                ring_b = ring[slots]
                # per-session valid trailing window: n_valid is (B, 1)
                # here, a scalar in the solo carrier — same head either
                # way
                n_valid = jnp.minimum(pos_b + 1, w)[:, None]
                logits = pooled_head_logits(params, h_new, ring_b, n_valid)
            carry_out = tuple(
                tuple(c.at[slots].set(cb)
                      for c, cb in zip(carry[layer], carry_new[layer]))
                for layer in range(cfg.n_layers))
            pos = pos.at[slots].set(pos_b + 1)
            return jax.nn.sigmoid(logits), carry_out, ring, pos

        # carry/ring/pos are DONATED: the step advances the pooled state
        # in place (XLA aliases each donated input to its same-shape
        # output) instead of copying the whole (n_slots, ...) tree per
        # flush.  The attributes are rebound to the outputs immediately
        # below in step_device, so the consumed buffers are unreachable.
        donate = (1, 2, 3)
        # batch size (slots, arg 6) is the only varying shape in the
        # step signature — the cheap per-call program signature for the
        # compile ledger (fmda_tpu.obs.device)
        step_name = f"session_pool_step_{cfg.cell}"

        def sig(*a, **k):
            return ("B", int(a[6].shape[0]))

        if self.n_shards > 1:
            st, rp = self._state_sharding, self._repl_sharding
            # explicit shardings (pytree prefixes): state tree sharded on
            # the slot axis, params/norms-batch replicated — and the SAME
            # specs on the outputs, so donation aliasing holds shard for
            # shard.  slots/rows arrive replicated; XLA inserts the
            # cross-chip gather/scatter for foreign lanes.
            self._step = tracked_jit(
                step,
                name=step_name,
                signature_of=sig,
                donate_argnums=donate,
                in_shardings=(rp, st, st, st, st, st, rp, rp),
                out_shardings=(rp, st, st, st),
            )
        else:
            self._step = tracked_jit(
                step, name=step_name, signature_of=sig,
                donate_argnums=donate)

    # -- slot lifecycle (host-side, off the hot path) -----------------------

    def alloc(
        self, session_id: str, norm: Optional[NormParams] = None
    ) -> SessionHandle:
        """Claim a free slot for ``session_id``: zeroed state, the
        session's own normalization stats, a fresh generation."""
        if session_id in self._by_id:
            raise ValueError(f"session {session_id!r} already allocated")
        if not self._free:
            raise PoolExhausted(
                f"all {self.capacity} slots in use ({len(self._by_id)} "
                "sessions); free one or raise RuntimeConfig.capacity")
        slot = self._free.pop()
        self._reset_slot(slot)
        if norm is not None:
            x_min = np.asarray(norm.x_min, np.float32)
            x_range = np.asarray(norm.x_max, np.float32) - x_min
            self._x_min = self._place_state(self._x_min.at[slot].set(x_min))
            self._x_range = self._place_state(
                self._x_range.at[slot].set(x_range))
        else:
            self._x_min = self._place_state(self._x_min.at[slot].set(0.0))
            self._x_range = self._place_state(
                self._x_range.at[slot].set(1.0))
        handle = SessionHandle(session_id, slot, self._generations[slot])
        self._by_id[session_id] = handle
        return handle

    def free(self, handle: SessionHandle) -> None:
        """Release the slot.  The generation bump invalidates every copy
        of ``handle`` — a later ``step``/``check`` with it raises instead
        of touching whichever session re-used the slot."""
        self.check(handle)
        self._generations[handle.slot] += 1
        del self._by_id[handle.session_id]
        self._free.append(handle.slot)

    def reset(self, handle: SessionHandle) -> None:
        """Zero the session's carried state in place (same slot, same
        generation — for a client restarting its stream)."""
        self.check(handle)
        self._reset_slot(handle.slot)

    def _reset_slot(self, slot: int) -> None:
        place = self._place_state
        self._carry = tuple(
            tuple(place(c.at[slot].set(0.0)) for c in layer)
            for layer in self._carry)
        self._ring = place(self._ring.at[slot].set(0.0))
        self._pos = place(self._pos.at[slot].set(0))

    def export_slot(self, handle: SessionHandle) -> dict:
        """Snapshot one session's carried state as host numpy arrays —
        the migration payload (fmda_tpu.fleet): carry per layer, ring,
        tick position, and the per-slot normalization stats.  Raw-dtype
        copies, so an :meth:`import_slot` on another pool (same model
        config) reproduces the slot bit for bit."""
        self.check(handle)
        s = handle.slot
        return {
            "carry": [
                [np.asarray(c[s]) for c in layer] for layer in self._carry
            ],
            "ring": np.asarray(self._ring[s]),
            "pos": int(self._pos[s]),
            "x_min": np.asarray(self._x_min[s]),
            "x_range": np.asarray(self._x_range[s]),
        }

    def import_slot(self, handle: SessionHandle, state: dict) -> None:
        """Load an :meth:`export_slot` snapshot into this slot (the
        receiving end of a migration).  Functional ``.at[slot].set``
        writes of same-dtype arrays — bit-exact, same cost class as
        ``alloc``/``reset`` (host-side, off the hot path)."""
        self.check(handle)
        s = handle.slot
        if len(state["carry"]) != self.cfg.n_layers:
            raise ValueError(
                f"state has {len(state['carry'])} carry layers, pool "
                f"expects {self.cfg.n_layers} (model config mismatch?)")
        place = self._place_state
        self._carry = tuple(
            tuple(
                place(c.at[s].set(jnp.asarray(arr, c.dtype)))
                for c, arr in zip(layer, state_layer)
            )
            for layer, state_layer in zip(self._carry, state["carry"])
        )
        self._ring = place(
            self._ring.at[s].set(jnp.asarray(state["ring"],
                                             self._ring.dtype)))
        self._pos = place(self._pos.at[s].set(int(state["pos"])))
        self._x_min = place(
            self._x_min.at[s].set(jnp.asarray(state["x_min"], jnp.float32)))
        self._x_range = place(
            self._x_range.at[s].set(
                jnp.asarray(state["x_range"], jnp.float32)))

    def is_live(self, handle: SessionHandle) -> bool:
        return (
            0 <= handle.slot < self.capacity
            and self._generations[handle.slot] == handle.generation
            and self._by_id.get(handle.session_id) == handle
        )

    def check(self, handle: SessionHandle) -> None:
        if not self.is_live(handle):
            reallocated = any(
                h.slot == handle.slot for h in self._by_id.values())
            raise StaleSessionError(
                f"handle for session {handle.session_id!r} (slot "
                f"{handle.slot}, generation {handle.generation}) is no "
                "longer live — the slot was freed"
                + (" and re-allocated to another session"
                   if reallocated else ""))

    def handle_for(self, session_id: str) -> Optional[SessionHandle]:
        return self._by_id.get(session_id)

    def session_ids(self) -> List[str]:
        """Ids of every live session (the worker's session report —
        router failover rebuilds its registry from these)."""
        return list(self._by_id)

    def slot_norm(self, handle: SessionHandle) -> tuple:
        """One session's normalization stats as host ``(x_min, x_range)``
        arrays — the cheap slice a session report carries (the full
        :meth:`export_slot` hauls the ring too)."""
        self.check(handle)
        s = handle.slot
        return np.asarray(self._x_min[s]), np.asarray(self._x_range[s])

    def ticks_seen(self, handle: SessionHandle) -> int:
        self.check(handle)
        return int(self._pos[handle.slot])

    @property
    def n_active(self) -> int:
        return len(self._by_id)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def active_mask(self) -> np.ndarray:
        """(capacity,) bool — which slots currently carry a live session."""
        mask = np.zeros(self.capacity, bool)
        for h in self._by_id.values():
            mask[h.slot] = True
        return mask

    @property
    def compile_count(self) -> int:
        """Distinct compiled programs behind the jitted step — one per
        micro-batch bucket size.  Tests assert this stays equal to the
        number of buckets actually dispatched (no per-request recompiles).

        Probes jax's jit cache directly when the (private) hook exists —
        the honest measurement; falls back to counting distinct dispatched
        batch sizes (equivalent here: batch size is the only varying
        shape in the step signature) if a jax upgrade removes it.
        """
        size = self._step.cache_size()
        if size is not None:
            return size
        return len(self._batch_sizes_seen)

    def mark_warm(self) -> None:
        """Declare precompile over: any further compile of the step is
        an *unexpected recompile* — counted by the compile ledger,
        evented, and SLO-alertable (fmda_tpu.obs.device)."""
        self._step.mark_warm()

    @property
    def recompiles_after_warmup(self) -> int:
        """Compiles observed after :meth:`mark_warm` (0 is the
        steady-state contract the chaos/elastic soaks hard-gate)."""
        return self._step.unexpected_recompiles

    def live_tree(self):
        """The pool's live device tree (params + pooled state + norms)
        — the owner callback for the device memory monitor."""
        return (self._params, self._carry, self._ring, self._pos,
                self._x_min, self._x_range)

    def swap_weights(self, params) -> None:
        """Land a new checkpoint into the live pool without touching a
        single session.

        ``params`` is the first argument of the jitted step and is *not*
        donated, so the swap is a pure host-side rebind: cast the new
        tree to the pool dtype, re-place it on the replicated sharding
        when the pool is sharded, and point ``self._params`` at it.  The
        next flush serves the new weights; carried state, rings, norms,
        and slot bookkeeping are untouched, and because the tree
        structure and every leaf shape are validated against the serving
        tree the jit cache hits — zero recompiles, zero dropped
        sessions.  Structure or shape drift raises ``ValueError`` (a
        silent recompile storm is worse than a refused swap).
        """
        dtype = self._dtype
        old_leaves, old_treedef = jax.tree.flatten(self._params)
        raw_leaves, new_treedef = jax.tree.flatten(params)
        # structure first, cast second: a malformed checkpoint must be
        # refused as ValueError before any leaf touches the dtype lattice
        if new_treedef != old_treedef:
            raise ValueError(
                "swap_weights: checkpoint tree structure differs from the "
                f"serving tree ({new_treedef} vs {old_treedef})")
        new_leaves = [jnp.asarray(a).astype(dtype) for a in raw_leaves]
        new = jax.tree.unflatten(new_treedef, new_leaves)
        for old, fresh in zip(old_leaves, new_leaves):
            if old.shape != fresh.shape:
                raise ValueError(
                    "swap_weights: leaf shape mismatch "
                    f"{fresh.shape} vs serving {old.shape} — a hot swap "
                    "must not change the compiled program")
        if self._repl_sharding is not None:
            new = jax.tree.map(
                lambda a: jax.device_put(a, self._repl_sharding), new)
        self._params = new

    # -- the hot path -------------------------------------------------------

    def step_device(self, slots: np.ndarray, rows: np.ndarray):
        """One fused flush, asynchronously: advance ``slots[i]`` by
        ``rows[i]`` and return the (B, n_classes) sigmoid probabilities
        as a **device array** — no host transfer, no block.  The pool's
        state advances in place (donated buffers) the moment the step is
        enqueued; the caller forces the result whenever it actually needs
        the numbers (the gateway does so one flush late, overlapping the
        transfer with the next flush's dispatch).

        ``slots`` (B,) int32 — pool slots, padded lanes = ``padding_slot``;
        ``rows`` (B, F) float32.  Padding lanes carry garbage; callers
        slice them off.  Caller contract: at most one lane per live slot,
        handles already validated (the gateway/batcher do both).
        """
        slots = np.asarray(slots, np.int32)
        rows = np.asarray(rows, np.float32)
        self._batch_sizes_seen.add(int(slots.shape[0]))
        probs, self._carry, self._ring, self._pos = self._step(
            self._params, self._carry, self._ring, self._pos,
            self._x_min, self._x_range, slots, rows,
        )
        return probs

    def step(self, slots: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """Blocking :meth:`step_device`: one fused flush, probabilities
        as a host numpy array (the pre-pipeline contract, kept for direct
        callers and tests)."""
        return np.asarray(self.step_device(slots, rows))
