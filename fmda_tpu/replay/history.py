"""History sources for the replay driver.

A history source is an iterable of :class:`ReplayBatch` *rounds* — the
unit the driver coalesces into one columnar tick block and one gateway
flush.  Iteration must be **deterministic and repeatable**: iterating
the same source twice yields bit-identical batches (the replay-vs-live
identity gate replays the same source into two gateways and compares
published probabilities byte for byte).

Two sources ship:

- :class:`SyntheticHistory` — the hermetic generator (seeded rng, no
  I/O): per-ticker random walks with per-ticker price scales, the same
  traffic shape as :func:`fmda_tpu.runtime.loadgen.run_fleet_load`, but
  re-iterable and virtual-clock stamped.
- :class:`WarehouseHistory` — warehoused rows via the bulk chunked
  reader (``Warehouse.iter_row_chunks``, one keyset range query per
  chunk), fanned round-robin over the ticker universe.

The virtual clock is **data**, not a reading: epoch seconds derived
from the rows' own timestamps (synthetic sources compute them from
``start_epoch + round * step_s``).  Nothing in this module may consult
the host clock — the ``virtual-clock`` lint rule enforces it.
"""

from __future__ import annotations

from dataclasses import dataclass
from datetime import datetime, timezone
from typing import Iterator, List, Optional, Tuple

import numpy as np

from fmda_tpu.data.normalize import NormParams


@dataclass(frozen=True)
class ReplayBatch:
    """One replay round: the rows the virtual clock advances past in a
    single gateway flush."""

    #: Virtual time (epoch seconds) after this batch — the watermark.
    virtual_ts: float
    #: (B,) int ticker indices into the source's ticker universe.
    tickers: np.ndarray
    #: (B, F) float32 feature rows, parallel to ``tickers``.
    rows: np.ndarray
    #: Warehouse timestamp strings parallel to ``rows`` (the label-join
    #: key the quality evaluator resolves through ids_for_timestamps);
    #: None for sources without warehouse identity (synthetic).
    timestamps: Optional[Tuple[str, ...]] = None


def parse_epoch(ts: str, fallback: float = 0.0) -> float:
    """Warehouse timestamp string → epoch seconds, timezone-pinned to
    UTC so the virtual clock is host-independent (naive
    ``datetime.timestamp()`` would read the host zone — a wall-clock
    dependency in disguise)."""
    try:
        dt = datetime.fromisoformat(ts)
    except ValueError:
        return fallback
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


class SyntheticHistory:
    """Seeded synthetic history: N ticker random walks, one row per
    ticking session per round, virtual time advancing ``step_s`` per
    round.  ``duty`` < 1 makes rounds ragged (a deterministic subset of
    tickers skips — per-ticker lag becomes visible); the identity gate
    runs lockstep ``duty=1.0``, where flush composition is forced and
    live-vs-replay is bit-identical."""

    def __init__(
        self,
        n_tickers: int,
        n_rounds: int,
        n_features: int,
        *,
        seed: int = 0,
        duty: float = 1.0,
        start_epoch: float = 1577973000.0,  # 2020-01-02 13:30:00 UTC
        step_s: float = 60.0,
    ) -> None:
        if n_tickers < 1:
            raise ValueError(f"n_tickers must be >= 1, got {n_tickers}")
        if not 0.0 < duty <= 1.0:
            raise ValueError(f"duty must be in (0, 1], got {duty}")
        self.n_tickers = n_tickers
        self.n_rounds = n_rounds
        self.n_features = n_features
        self.seed = seed
        self.duty = duty
        self.start_epoch = float(start_epoch)
        self.step_s = float(step_s)
        # per-ticker price scales, from their own seeded stream so the
        # walk stream below replays identically however norms are used
        rng = np.random.default_rng(seed)
        mins = rng.normal(0.0, 1.0, size=(n_tickers, n_features)).astype(
            np.float32)
        maxs = mins + rng.uniform(
            1.0, 5.0, size=(n_tickers, n_features)).astype(np.float32)
        self._mins, self._maxs = mins, maxs
        self._walk0 = rng.normal(
            size=(n_tickers, n_features)).astype(np.float32)

    @property
    def norms(self) -> List[NormParams]:
        return [NormParams(self._mins[i], self._maxs[i])
                for i in range(self.n_tickers)]

    def __iter__(self) -> Iterator[ReplayBatch]:
        # fresh stream per iteration: the source is re-iterable and
        # every pass is bit-identical (the A/B identity contract)
        rng = np.random.default_rng((self.seed, 1))
        walk = self._walk0.copy()
        for r in range(self.n_rounds):
            if self.duty >= 1.0:
                ticking = np.arange(self.n_tickers)
            else:
                mask = rng.random(self.n_tickers) < self.duty
                ticking = np.flatnonzero(mask)
                if ticking.size == 0:
                    # virtual time still advances on an empty round
                    continue
            steps = rng.normal(
                scale=0.1,
                size=(self.n_tickers, self.n_features)).astype(np.float32)
            walk[ticking] += steps[ticking]
            yield ReplayBatch(
                virtual_ts=self.start_epoch + (r + 1) * self.step_s,
                tickers=ticking.astype(np.int32),
                rows=walk[ticking].copy(),
            )


class WarehouseHistory:
    """Warehoused history fanned over N ticker sessions: rows stream in
    landed (ID) order through ``iter_row_chunks`` — one keyset range
    query per chunk — and row *j* drives ticker ``j % n_tickers``, so a
    single-symbol warehouse exercises a whole fleet and every ticker
    advances through the same market history interleaved.

    ``row_transform`` maps a ``(B, W)`` float64 chunk of raw landed
    columns to the ``(B, F)`` float32 feature rows the pool expects;
    when omitted the landed width must already equal ``n_features``
    (anything else raises — silently truncating features would serve
    garbage bit-deterministically, the worst kind of wrong)."""

    def __init__(
        self,
        warehouse,
        n_tickers: int,
        *,
        n_features: Optional[int] = None,
        start_ts: Optional[str] = None,
        end_ts: Optional[str] = None,
        chunk: int = 4096,
        row_transform=None,
    ) -> None:
        if n_tickers < 1:
            raise ValueError(f"n_tickers must be >= 1, got {n_tickers}")
        self.warehouse = warehouse
        self.n_tickers = n_tickers
        self.n_features = n_features
        self.start_ts = start_ts
        self.end_ts = end_ts
        self.chunk = chunk
        self.row_transform = row_transform

    @property
    def norms(self) -> Optional[List[NormParams]]:
        return None  # identity normalization: landed rows serve as-is

    def __iter__(self) -> Iterator[ReplayBatch]:
        n = self.n_tickers
        pending_rows: List[np.ndarray] = []
        pending_ts: List[float] = []
        pending_raw: List[str] = []
        last_epoch = 0.0
        for ts_list, matrix in self.warehouse.iter_row_chunks(
                self.start_ts, self.end_ts, self.chunk):
            if self.row_transform is not None:
                feats = np.asarray(
                    self.row_transform(matrix), np.float32)
            else:
                feats = matrix.astype(np.float32)
                if (self.n_features is not None
                        and feats.shape[1] != self.n_features):
                    raise ValueError(
                        f"landed row width {feats.shape[1]} != "
                        f"n_features {self.n_features} — pass "
                        "row_transform to map landed columns to "
                        "feature rows")
            for i in range(feats.shape[0]):
                last_epoch = parse_epoch(ts_list[i], last_epoch)
                pending_rows.append(feats[i])
                pending_ts.append(last_epoch)
                pending_raw.append(str(ts_list[i]))
                if len(pending_rows) == n:
                    # row j drives ticker j % n, and full rounds consume
                    # exactly n rows — every round is tickers 0..n-1
                    yield ReplayBatch(
                        virtual_ts=max(pending_ts),
                        tickers=np.arange(n, dtype=np.int32),
                        rows=np.stack(pending_rows),
                        timestamps=tuple(pending_raw),
                    )
                    pending_rows, pending_ts, pending_raw = [], [], []
        if pending_rows:
            yield ReplayBatch(
                virtual_ts=max(pending_ts),
                tickers=np.arange(len(pending_rows), dtype=np.int32),
                rows=np.stack(pending_rows),
                timestamps=tuple(pending_raw),
            )
