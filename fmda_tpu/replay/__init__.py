"""Fleet-scale historical replay (docs/replay.md).

Streams warehoused (or seeded synthetic) history for N tickers through
the *unmodified* FleetGateway/SessionPool serving path at max speed — a
**deterministic virtual clock** advances with the rows themselves, so
the only speed limit is the pipeline, and the same row sequence produces
bit-identical probabilities whether it arrives as a cadence-paced live
feed or a full-throttle backfill.  The identity gate is the foundation:
every backtest run through :class:`ReplayDriver` is simultaneously an
end-to-end benchmark of the serving tier and a bit-exact replica of what
live serving would have published.

Wall-clock reads are banned from this package's pacing and ordering
paths by the ``virtual-clock`` analysis rule (annotated telemetry sites
excepted) — determinism is checked, not hoped for.
"""

from fmda_tpu.replay.driver import ReplayDriver
from fmda_tpu.replay.history import (
    ReplayBatch,
    SyntheticHistory,
    WarehouseHistory,
)
from fmda_tpu.replay.reference import run_live_reference

__all__ = [
    "ReplayBatch",
    "ReplayDriver",
    "SyntheticHistory",
    "WarehouseHistory",
    "run_live_reference",
]
