"""The replay driver: virtual-clock max-speed backfill through the
live serving path (docs/replay.md).

One :class:`ReplayDriver` run is the whole story of the tentpole: read
a history source round by round, coalesce each round into the existing
columnar tick block (``stream/codec.pack_ticks`` — optionally
round-tripped through the binary or JSON wire dialect, so a backfill
exercises the exact bytes a fleet link would carry), feed it to the
**unmodified** gateway ``submit``/``pump`` surface, and force-flush —
no linger, no cadence, no wall-clock pacing.  The virtual clock is the
rows' own timestamps; the host clock appears only at annotated
telemetry sites (rows/s), never in pacing or ordering — the
``virtual-clock`` lint rule checks exactly that.

The driver speaks the same duck-typed gateway surface as
:func:`fmda_tpu.runtime.loadgen.run_fleet_load`: a solo in-process
:class:`~fmda_tpu.runtime.gateway.FleetGateway` (codec round-trip
applied here, mirroring what a fleet worker decodes) or a
:class:`~fmda_tpu.fleet.router.FleetRouter` fronting the spawned
topology (the router coalesces into blocks itself — same path, one
layer down).
"""

from __future__ import annotations

import time
from datetime import datetime, timezone
from typing import Dict, List, Optional

import numpy as np

from fmda_tpu.runtime.loadgen import FleetLoadConfig, assign_tenants
from fmda_tpu.stream import codec


def open_replay_sessions(
    gateway,
    source,
    *,
    tenant_classes: tuple = (),
    tenant_weights: tuple = (),
    seed: int = 0,
) -> List[str]:
    """Open one gateway session per source ticker — loadgen's naming
    (``T0000``…) and, when a tenant mix is configured, loadgen's own
    :func:`~fmda_tpu.runtime.loadgen.assign_tenants` over the ticker
    universe, so QoS/capacity A/Bs run against replay load exactly as
    they run against synthetic load.  Shared by the replay driver and
    the cadence-paced live reference (identical admission is half of
    the identity gate)."""
    n = source.n_tickers
    session_ids = [f"T{i:04d}" for i in range(n)]
    tenants = assign_tenants(
        FleetLoadConfig(
            n_sessions=n, tenant_classes=tuple(tenant_classes),
            tenant_weights=tuple(tenant_weights)),
        np.random.default_rng(seed))
    norms = getattr(source, "norms", None)
    for i, sid in enumerate(session_ids):
        norm = norms[i] if norms is not None else None
        if tenants is None:
            gateway.open_session(sid, norm)
        else:
            gateway.open_session(sid, norm, tenant=tenants[i])
    return session_ids


class ReplayDriver:
    """Drive one backfill through a gateway at max speed.

    ``wire_dialect`` (solo gateways only): ``None`` hands decoded
    blocks straight over; ``"binary"``/``"json"`` round-trips every
    block through that wire dialect first — the bit-identity tests run
    both, because a backfill's bytes must decode to the same floats a
    live fleet link delivers.  ``collect`` keeps every
    :class:`~fmda_tpu.runtime.gateway.FleetResult` on ``.results`` for
    identity comparison (off for long backfills — it is O(rows)
    memory).
    """

    def __init__(
        self,
        gateway,
        source,
        *,
        tenant_classes: tuple = (),
        tenant_weights: tuple = (),
        seed: int = 0,
        wire_dialect: Optional[str] = None,
        collect: bool = False,
        on_round=None,
        quality=None,
    ) -> None:
        if wire_dialect not in (None, "binary", "json"):
            raise ValueError(
                f"wire_dialect must be None, 'binary' or 'json', "
                f"got {wire_dialect!r}")
        self.gateway = gateway
        self.source = source
        self.tenant_classes = tuple(tenant_classes)
        self.tenant_weights = tuple(tenant_weights)
        self.seed = seed
        self.wire_dialect = wire_dialect
        self.collect = collect
        self.on_round = on_round
        #: optional fmda_tpu.obs.quality.QualityEvaluator: every served
        #: result is captured for label join (keyed by its row's
        #: warehouse timestamp), and the join runs on the VIRTUAL clock
        #: — cadence-gated off the tick path, deterministic in replay
        self.quality = quality
        self.results: List = []
        #: per-ticker virtual timestamp of the last dispatched row
        self._ticker_ts: Optional[np.ndarray] = None
        #: (session, seq) -> (timestamp string, feature row) for results
        #: still in flight; popped as results land (bounded by inflight)
        self._quality_keys: Dict = {}
        self._watermark = 0.0

    # -- progress observability (obs gauges; `status` renders these) -----

    def _publish_progress(self, rows: int, wall_s: float) -> None:
        m = self.gateway.metrics
        m.gauge("replay_rows_per_s",
                rows / wall_s if wall_s > 0 else 0.0)
        m.gauge("replay_virtual_watermark", self._watermark)
        if self._ticker_ts is not None:
            seen = self._ticker_ts[self._ticker_ts > 0.0]
            lag = (self._watermark - float(seen.min())) if seen.size else 0.0
            m.gauge("replay_max_ticker_lag_s", lag)

    # -- the backfill loop ----------------------------------------------

    def run(self) -> Dict:
        gateway = self.gateway
        source = self.source
        pool = getattr(gateway, "pool", None)
        session_ids = open_replay_sessions(
            gateway, source, tenant_classes=self.tenant_classes,
            tenant_weights=self.tenant_weights, seed=self.seed)
        self._ticker_ts = np.zeros(len(session_ids), np.float64)
        seqs = [0] * len(session_ids)
        binary = self.wire_dialect == "binary"

        m = gateway.metrics
        m.gauge("replay_active", 1.0)
        submitted = 0
        served = 0
        rounds = 0
        virtual_start: Optional[float] = None
        # telemetry only — rows/s against the host clock; the virtual
        # clock below never reads it
        # lint: ignore[virtual-clock] wall time measures throughput telemetry, never pacing/ordering
        t0 = time.perf_counter()
        try:
            for batch in source:
                if virtual_start is None:
                    virtual_start = batch.virtual_ts
                self._watermark = max(self._watermark, batch.virtual_ts)
                msgs = []
                for k, ti in enumerate(batch.tickers):
                    ti = int(ti)
                    msgs.append({
                        "kind": "tick",
                        "session": session_ids[ti],
                        "row": batch.rows[k],
                        "seq": seqs[ti],
                    })
                    if self.quality is not None:
                        ts = (batch.timestamps[k] if batch.timestamps
                              else _virtual_ts_str(batch.virtual_ts))
                        self._quality_keys[
                            (session_ids[ti], seqs[ti])] = (
                                ts, batch.rows[k])
                    seqs[ti] += 1
                    self._ticker_ts[ti] = batch.virtual_ts
                if pool is not None and len(msgs) >= codec.MIN_BLOCK_TICKS:
                    # solo gateway: coalesce the round into ONE columnar
                    # block — the same bytes a fleet worker would decode
                    wire_msgs = [codec.pack_ticks(msgs)]
                else:
                    wire_msgs = msgs
                if self.wire_dialect is not None:
                    wire_msgs = [
                        codec.decode_payload(
                            codec.encode_payload(w, binary=binary))[0]
                        for w in wire_msgs]
                for w in wire_msgs:
                    if w.get("kind") == "tick_block":
                        ticks = codec.iter_ticks(w)
                    else:
                        ticks = [(w["session"], w["row"], w["seq"], None)]
                    for sid, row, _seq, _trace in ticks:
                        while gateway.saturated:
                            # well-behaved producer under backpressure:
                            # drain instead of racing the shedder; the
                            # yield lets a multi-host router's bus
                            # threads run — backpressure, not pacing
                            drained = gateway.pump(force=True)
                            served += self._keep(drained)
                            if not drained and gateway.saturated:
                                # lint: ignore[virtual-clock] GIL yield under router backpressure — the virtual clock never reads it
                                time.sleep(0.002)
                        gateway.submit(sid, np.asarray(row))
                        submitted += 1
                served += self._keep(gateway.pump(force=True))
                rounds += 1
                m.count("replay_rows", len(msgs))
                if rounds % 32 == 0:
                    # lint: ignore[virtual-clock] telemetry read for the rows/s gauge only
                    now = time.perf_counter()
                    self._publish_progress(submitted, now - t0)
                if self.on_round is not None:
                    self.on_round(rounds - 1)
                if self.quality is not None:
                    # the join cadence rides the VIRTUAL clock — the
                    # same rows produce the same join/expiry schedule
                    # on every replay, no wall-clock involved
                    self.quality.maybe_join(now=batch.virtual_ts)
            served += self._keep(gateway.drain())
        finally:
            m.gauge("replay_active", 0.0)
        # lint: ignore[virtual-clock] telemetry read for the final throughput summary only
        wall_s = time.perf_counter() - t0
        self._publish_progress(submitted, wall_s)

        summary = gateway.metrics.summary()
        watermark = self._watermark
        seen = self._ticker_ts[self._ticker_ts > 0.0]
        out = {
            "sessions": len(session_ids),
            "rounds": rounds,
            "rows_replayed": submitted,
            "ticks_served": served,
            "wall_s": round(wall_s, 3),
            "rows_per_s": round(submitted / wall_s, 1) if wall_s > 0
            else None,
            "ticks_per_s": round(served / wall_s, 1) if wall_s > 0
            else None,
            "virtual_start_epoch": virtual_start,
            "virtual_watermark_epoch": watermark,
            "virtual_span_s": round(watermark - virtual_start, 3)
            if virtual_start is not None else 0.0,
            "max_ticker_lag_s": round(
                watermark - float(seen.min()), 3) if seen.size else 0.0,
            "compile_count": pool.compile_count if pool is not None
            else None,
            "wire_dialect": self.wire_dialect,
            **summary,
        }
        return out

    def _keep(self, results) -> int:
        if self.collect and results:
            self.results.extend(results)
        if self.quality is not None and results:
            for r in results:
                key = self._quality_keys.pop((r.session_id, r.seq), None)
                if key is None:
                    continue  # pre-attach or replayed-duplicate result
                ts, row = key
                self.quality.capture(
                    r.session_id, ts, r.probabilities,
                    weights_version=getattr(r, "weights_version", None),
                    features=row)
        return len(results)


def _virtual_ts_str(virtual_ts: float) -> str:
    """Virtual epoch -> warehouse-format timestamp string (a pure
    conversion of replay data, not a clock read) — synthetic sources
    get join keys in the same space warehouse rows use."""
    return datetime.fromtimestamp(
        virtual_ts, tz=timezone.utc).strftime("%Y-%m-%d %H:%M:%S")
