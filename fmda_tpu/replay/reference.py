"""The cadence-paced live reference loop — replay's A/B baseline.

Serves the *same* history source through the *same* gateway surface as
:class:`~fmda_tpu.replay.driver.ReplayDriver`, but the way a live feed
would: each round arrives on a wall-clock cadence, rows are submitted
per-tick (no backfill coalescing), and flushes ride the batcher's own
ready/linger logic.  The bench phase races the two — replay must beat
this loop by a wide margin, because the cadence is exactly what replay
deletes — and the identity tests compare their published probabilities
byte for byte (lockstep ``duty=1.0`` sources force identical flush
composition, so float32 reduction order matches and equality is exact).

This module is the one place in ``fmda_tpu.replay`` allowed to touch
the host clock ON PURPOSE: pacing a live simulation is its job.  Every
site carries the ``virtual-clock`` lint hatch saying so.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from fmda_tpu.replay.driver import open_replay_sessions


def run_live_reference(
    gateway,
    source,
    *,
    cadence_s: float = 0.0,
    tenant_classes: tuple = (),
    tenant_weights: tuple = (),
    seed: int = 0,
    collect: bool = False,
) -> Dict:
    """Serve ``source`` live-style: one round per ``cadence_s`` of wall
    time (0 = as fast as per-tick submission goes — still slower than
    replay's coalesced blocks), forced flush per round so composition
    matches replay's round-per-flush and bit-identity holds.  Returns
    the run summary; with ``collect`` the per-tick results ride on the
    ``"results"`` key."""
    session_ids = open_replay_sessions(
        gateway, source, tenant_classes=tenant_classes,
        tenant_weights=tenant_weights, seed=seed)
    pool = getattr(gateway, "pool", None)
    results: List = []

    def keep(batch) -> int:
        if collect and batch:
            results.extend(batch)
        return len(batch)

    submitted = 0
    served = 0
    rounds = 0
    # lint: ignore[virtual-clock] live reference loop — wall-clock pacing IS the baseline being measured
    t0 = time.perf_counter()
    next_due = t0
    for batch in source:
        if cadence_s > 0.0:
            # lint: ignore[virtual-clock] live reference loop — paces rounds at the live cadence
            now = time.perf_counter()
            if now < next_due:
                # lint: ignore[virtual-clock] live reference loop — sleeps to the cadence, like a live feed
                time.sleep(next_due - now)
            next_due = max(next_due + cadence_s, now)
        for k, ti in enumerate(batch.tickers):
            sid = session_ids[int(ti)]
            while gateway.saturated:
                drained = gateway.pump(force=True)
                served += keep(drained)
                if not drained and gateway.saturated:
                    # lint: ignore[virtual-clock] live reference loop — GIL yield under backpressure
                    time.sleep(0.002)
            gateway.submit(sid, batch.rows[k])
            submitted += 1
        served += keep(gateway.pump(force=True))
        rounds += 1
    served += keep(gateway.drain())
    # lint: ignore[virtual-clock] telemetry read for the throughput summary
    wall_s = time.perf_counter() - t0

    summary = gateway.metrics.summary()
    out: Dict = {
        "sessions": len(session_ids),
        "rounds": rounds,
        "ticks_submitted": submitted,
        "ticks_served": served,
        "cadence_s": cadence_s,
        "wall_s": round(wall_s, 3),
        "ticks_per_s": round(served / wall_s, 1) if wall_s > 0 else None,
        "compile_count": pool.compile_count if pool is not None else None,
        **summary,
    }
    if collect:
        out["results"] = results
    return out
