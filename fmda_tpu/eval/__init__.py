"""Model evaluation: the offline/online metric seam + drift + shadow.

One numpy metric vocabulary (:mod:`fmda_tpu.eval.metrics`) shared by
the offline trainer reports and the online label-join evaluator
(:class:`fmda_tpu.obs.quality.QualityEvaluator`), a PSI drift monitor
against training-time reference profiles (:mod:`fmda_tpu.eval.drift`),
and the hot-swap quality guardrail (:mod:`fmda_tpu.eval.shadow`) that
shadow-scores a candidate checkpoint against the incumbent over recent
warehoused history before `broadcast_hot_swap` will land it.

``metrics`` and ``drift`` are numpy-only (importable from jax-free
router/CLI roles); ``shadow`` imports jax at use time (it builds a
serving stack).
"""

from fmda_tpu.eval.drift import (
    DriftMonitor,
    build_profile,
    load_profile,
    profile_path_for,
    psi,
    save_profile,
)
from fmda_tpu.eval.metrics import (
    StreamingCounts,
    batch_counts,
    threshold_probs,
)

__all__ = [
    "DriftMonitor",
    "StreamingCounts",
    "batch_counts",
    "build_profile",
    "load_profile",
    "profile_path_for",
    "psi",
    "save_profile",
    "threshold_probs",
]
