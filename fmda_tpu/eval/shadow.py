"""Hot-swap quality guardrail: shadow-score a candidate checkpoint.

``FleetRouter.broadcast_hot_swap(require_eval=...)`` refuses a
checkpoint that would regress serving quality — but the router is a
jax-free role, so the scoring lives here: :class:`ShadowEvaluator`
replays **recent warehoused history** (the PR-17 replay plumbing:
:class:`~fmda_tpu.replay.WarehouseHistory` through an unmodified solo
:class:`~fmda_tpu.runtime.gateway.FleetGateway`) under the incumbent
and the candidate parameter trees, label-joins both prediction streams
against the warehouse's materialized targets with the shared eval
vocabulary, and passes the candidate iff

    candidate_accuracy + swap_margin >= incumbent_accuracy

Both sides replay the *same* deterministic source with the same
sessions, so the joinable subset is identical — the comparison is
apples to apples by construction.  A warehouse with no joinable
history (too young, targets not yet final) cannot refuse: the verdict
is a pass with ``"scored": false`` — blocking every swap on an empty
warehouse would deadlock a fresh deployment.

Imports jax at construction time (it builds serving stacks); construct
it in a worker-side or CLI role and hand the router only the callable.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

__all__ = ["ShadowEvaluator"]


class ShadowEvaluator:
    """Callable guardrail for ``broadcast_hot_swap(require_eval=...)``.

    ``gate(params)`` (also ``__call__``) returns ``(ok, detail)``;
    the incumbent's score is computed once, lazily, and reused across
    candidate evaluations (the incumbent does not change between
    refusals).
    """

    def __init__(
        self,
        incumbent_params,
        *,
        model_config,
        warehouse,
        quality_config=None,
        max_lead: Optional[int] = None,
        window: int = 30,
        n_tickers: Optional[int] = None,
        seed: int = 0,
        row_transform=None,
    ) -> None:
        from fmda_tpu.config import FeatureConfig, QualityConfig

        self.incumbent_params = incumbent_params
        self.model_config = model_config
        self.warehouse = warehouse
        self.cfg = quality_config or QualityConfig()
        self.max_lead = (int(max_lead) if max_lead is not None
                         else FeatureConfig().max_lead)
        self.window = int(window)
        self.n_tickers = int(n_tickers if n_tickers is not None
                             else self.cfg.swap_eval_sessions)
        self.seed = int(seed)
        # zero-arg FACTORY (e.g. the bound warehouse.joined_row_transform
        # method): each replay needs a fresh stateful mapper, and gate()
        # replays twice (incumbent + candidate)
        self.row_transform = row_transform
        self._incumbent_score: Optional[Dict] = None

    # -- one side's replay + join -------------------------------------------

    def score(self, params) -> Dict:
        """Replay recent history under ``params``; return the joined
        streaming-metric summary (``{"joined": 0}`` when no history has
        materialized targets yet)."""
        import dataclasses

        from fmda_tpu.obs.quality import QualityEvaluator
        from fmda_tpu.replay import ReplayDriver, WarehouseHistory
        from fmda_tpu.runtime import BatcherConfig, FleetGateway, SessionPool

        model_cfg = dataclasses.replace(
            self.model_config, dropout=0.0, use_pallas=False)
        rows_wanted = (self.cfg.swap_eval_rounds * self.n_tickers
                       + self.max_lead)
        recent = self.warehouse.recent_timestamps(rows_wanted)
        start_ts = recent[-1] if recent else None
        source = WarehouseHistory(
            self.warehouse, self.n_tickers,
            n_features=model_cfg.n_features, start_ts=start_ts,
            row_transform=(self.row_transform()
                           if self.row_transform is not None else None))
        pool = SessionPool(model_cfg, params, capacity=self.n_tickers,
                           window=self.window)
        gateway = FleetGateway(
            pool, None,
            batcher_config=BatcherConfig(
                bucket_sizes=(self.n_tickers,), max_linger_s=0.0))
        # the shadow run must expire nothing: one final join settles
        # every capture whose targets are final, the rest stay pending
        eval_cfg = dataclasses.replace(
            self.cfg, capture_capacity=max(
                self.cfg.capture_capacity,
                self.cfg.swap_eval_rounds * self.n_tickers + 1))
        evaluator = QualityEvaluator(
            eval_cfg, warehouse=self.warehouse, max_lead=self.max_lead)
        driver = ReplayDriver(
            gateway, source, seed=self.seed, quality=evaluator)
        driver.run()
        evaluator.join()
        summary = evaluator.summary()
        out = dict(summary["overall"])
        out["joined"] = summary["conservation"]["joined"]
        return out

    # -- the gate ------------------------------------------------------------

    def gate(self, params) -> Tuple[bool, Dict]:
        if self._incumbent_score is None:
            self._incumbent_score = self.score(self.incumbent_params)
        incumbent = self._incumbent_score
        candidate = self.score(params)
        detail: Dict = {
            "margin": self.cfg.swap_margin,
            "joined": candidate["joined"],
            "incumbent_accuracy": incumbent["subset_accuracy"],
            "candidate_accuracy": candidate["subset_accuracy"],
        }
        if not candidate["joined"] or not incumbent["joined"]:
            detail["scored"] = False
            return True, detail
        detail["scored"] = True
        ok = (candidate["subset_accuracy"] + self.cfg.swap_margin
              >= incumbent["subset_accuracy"])
        return ok, detail

    __call__ = gate
