"""Feature/prediction drift against a training-time reference profile.

At train time the CLI persists a **reference profile** beside the
checkpoint (``quality_profile.json``): per-feature quantile bin edges
with the training distribution's bin frequencies, plus the training
targets' per-label positive rates.  At serve time a :class:`DriftMonitor`
digitizes the live feature rows into the same bins and scores the
divergence as **PSI** (population stability index) per feature; the
published prediction stream is scored the same way against the label
rates (each label a two-bin positive/negative distribution).

PSI conventions (the usual credit-scoring thresholds the docs quote):
< 0.1 stable, 0.1-0.25 moderate shift, > 0.25 action required — the
``quality_drift`` SLO objective defaults its bound to 0.25.

The profile format is JSON-stable and versioned (``profile_version``):

```
{"profile_version": 1, "n_features": F, "bins": B,
 "edges": [[...B-1 inner edges...] x F], "freqs": [[...B...] x F],
 "label_rates": [L], "columns": [...], "n_rows": N}
```

numpy-only; jax-free (the monitor runs in router/CLI roles).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

PROFILE_VERSION = 1
PROFILE_FILENAME = "quality_profile.json"

#: smoothing floor so empty bins never divide by / log zero
_EPS = 1e-4


def build_profile(
    rows: np.ndarray,
    targets: Optional[np.ndarray] = None,
    *,
    bins: int = 10,
    columns: Optional[Sequence[str]] = None,
) -> Dict[str, object]:
    """Quantile-bin reference profile from training-time feature rows."""
    rows = np.atleast_2d(np.asarray(rows, np.float64))
    if rows.shape[0] < 2:
        raise ValueError(f"need >= 2 reference rows, got {rows.shape[0]}")
    if bins < 2:
        raise ValueError(f"need >= 2 bins, got {bins}")
    edges: List[List[float]] = []
    freqs: List[List[float]] = []
    qs = np.linspace(0.0, 1.0, bins + 1)[1:-1]
    for j in range(rows.shape[1]):
        col = rows[:, j]
        inner = np.unique(np.quantile(col, qs))
        counts = np.histogram(col, np.concatenate(
            ([-np.inf], inner, [np.inf])))[0]
        freq = counts / max(1, col.size)
        edges.append([float(x) for x in inner])
        freqs.append([float(x) for x in freq])
    label_rates: List[float] = []
    if targets is not None:
        t = np.atleast_2d(np.asarray(targets, np.float64))
        label_rates = [float(x) for x in np.clip(
            t.mean(axis=0), _EPS, 1.0 - _EPS)]
    return {
        "profile_version": PROFILE_VERSION,
        "n_features": int(rows.shape[1]),
        "bins": int(bins),
        "edges": edges,
        "freqs": freqs,
        "label_rates": label_rates,
        "columns": list(columns) if columns is not None else [],
        "n_rows": int(rows.shape[0]),
    }


def save_profile(path: str, profile: Dict[str, object]) -> str:
    """Write the profile JSON; returns the path written."""
    directory = os.path.dirname(os.path.abspath(path))
    if directory:
        os.makedirs(directory, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(profile, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


def load_profile(path: str) -> Dict[str, object]:
    with open(path, "r", encoding="utf-8") as fh:
        profile = json.load(fh)
    version = profile.get("profile_version")
    if version != PROFILE_VERSION:
        raise ValueError(
            f"unsupported quality profile version {version!r} at {path} "
            f"(expected {PROFILE_VERSION})")
    return profile


def profile_path_for(checkpoint_path: str) -> str:
    """The profile's well-known location beside a checkpoint directory."""
    return os.path.join(checkpoint_path, PROFILE_FILENAME)


def psi(ref_freq: np.ndarray, cur_freq: np.ndarray) -> float:
    """Population stability index between two discrete distributions."""
    ref = np.clip(np.asarray(ref_freq, np.float64), _EPS, None)
    cur = np.clip(np.asarray(cur_freq, np.float64), _EPS, None)
    ref = ref / ref.sum()
    cur = cur / cur.sum()
    return float(np.sum((cur - ref) * np.log(cur / ref)))


class DriftMonitor:
    """Streaming PSI of live features/predictions vs the reference.

    ``observe_features`` digitizes each served row into the profile's
    quantile bins; ``observe_predictions`` tallies thresholded label
    positives.  ``scores()`` is None until ``min_samples`` feature rows
    have been observed — drift over a handful of rows is noise, and the
    SLO objective treats a None score as "never reported".
    """

    def __init__(self, profile: Dict[str, object], *,
                 min_samples: int = 64) -> None:
        self.profile = profile
        self.min_samples = int(min_samples)
        n_features = int(profile["n_features"])
        bins = int(profile["bins"])
        self._edges = [np.asarray(e, np.float64) for e in profile["edges"]]
        self._ref = [np.asarray(f, np.float64) for f in profile["freqs"]]
        # observed bin counts use one row per feature; edge list length
        # can be < bins-1 when training quantiles collapsed (constant
        # features), so each feature gets its own bin count
        self._counts = [np.zeros(len(e) + 1, np.int64) for e in self._edges]
        self._rows = 0
        rates = profile.get("label_rates") or []
        self._label_rates = np.asarray(rates, np.float64)
        self._pred_pos = np.zeros(len(rates), np.int64)
        self._preds = 0
        del n_features, bins

    # -- accumulation --------------------------------------------------------

    def observe_features(self, rows: np.ndarray) -> None:
        rows = np.atleast_2d(np.asarray(rows, np.float64))
        if rows.shape[1] != len(self._edges):
            raise ValueError(
                f"row width {rows.shape[1]} != profile n_features "
                f"{len(self._edges)}")
        for j, edges in enumerate(self._edges):
            idx = np.searchsorted(edges, rows[:, j], side="right")
            np.add.at(self._counts[j], idx, 1)
        self._rows += rows.shape[0]

    def observe_predictions(self, pred: np.ndarray) -> None:
        if not self._label_rates.size:
            return
        pred = np.atleast_2d(np.asarray(pred, bool))
        if pred.shape[1] != self._label_rates.size:
            return
        self._pred_pos += np.sum(pred, axis=0)
        self._preds += pred.shape[0]

    # -- scoring -------------------------------------------------------------

    @property
    def n_rows(self) -> int:
        return self._rows

    def feature_scores(self) -> Optional[np.ndarray]:
        if self._rows < self.min_samples:
            return None
        return np.asarray([
            psi(ref, counts / self._rows)
            for ref, counts in zip(self._ref, self._counts)
        ], np.float64)

    def prediction_scores(self) -> Optional[np.ndarray]:
        if not self._preds or not self._label_rates.size:
            return None
        if self._preds < self.min_samples:
            return None
        rate = self._pred_pos / self._preds
        return np.asarray([
            psi(np.asarray([r, 1.0 - r]), np.asarray([c, 1.0 - c]))
            for r, c in zip(self._label_rates, rate)
        ], np.float64)

    def scores(self) -> Optional[Dict[str, object]]:
        feats = self.feature_scores()
        if feats is None:
            return None
        preds = self.prediction_scores()
        worst = float(np.max(feats)) if feats.size else 0.0
        if preds is not None and preds.size:
            worst = max(worst, float(np.max(preds)))
        return {
            "max_psi": worst,
            "feature_psi": [float(x) for x in feats],
            "prediction_psi": (
                [float(x) for x in preds] if preds is not None else None),
            "rows": self._rows,
        }
