"""Host-side streaming metric vocabulary shared by trainer and server.

One numpy implementation of the paper's multi-label metrics — subset
accuracy, Hamming loss, per-label F-beta, per-label 2x2 confusion — used
by *both* sides of the offline/online seam:

- offline: :mod:`fmda_tpu.train.reports` renders end-of-run tables from
  a :class:`StreamingCounts` folded over eval batches;
- online: :class:`fmda_tpu.obs.quality.QualityEvaluator` folds the same
  counters incrementally as label joins complete, per weights_version.

Semantics are pinned to :mod:`fmda_tpu.ops.metrics` (itself pinned to
sklearn): exact-match ratio, mean wrong-label fraction, F-beta with the
0/0 -> 0 convention, confusion laid out ``[[tn, fp], [fn, tp]]``.  The
parity test (tests/test_eval_metrics.py) asserts streaming == batch ==
the jnp reference on identical inputs — the streaming decomposition is
exact, not approximate, because every metric here is a ratio of sums.

One deliberate difference from ``ops.metrics``: the serving tier
publishes **probabilities** (sigmoid already applied by the session
pool), so :func:`threshold_probs` compares them to the threshold
directly instead of re-applying a sigmoid.

numpy-only; importable from jax-free roles (router, CLI status).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def threshold_probs(probs: np.ndarray, threshold: float = 0.5) -> np.ndarray:
    """Published probabilities -> boolean label predictions."""
    return np.asarray(probs, np.float32) > float(threshold)


def _safe_div(num, den):
    num = np.asarray(num, np.float64)
    den = np.asarray(den, np.float64)
    return np.where(den > 0, num / np.where(den > 0, den, 1.0), 0.0)


class StreamingCounts:
    """Exact streaming decomposition of the batch metrics.

    Accumulates sufficient statistics (examples, exact matches, wrong
    label slots, per-label tp/fp/fn/tn) so that every derived metric
    equals the batch computation over the concatenation of all updates.
    """

    __slots__ = ("n_labels", "n", "exact", "wrong", "tp", "fp", "fn", "tn")

    def __init__(self, n_labels: int) -> None:
        if n_labels <= 0:
            raise ValueError(f"n_labels must be positive, got {n_labels}")
        self.n_labels = int(n_labels)
        self.n = 0
        self.exact = 0
        self.wrong = 0  # wrong label slots, over n * n_labels total
        self.tp = np.zeros(n_labels, np.int64)
        self.fp = np.zeros(n_labels, np.int64)
        self.fn = np.zeros(n_labels, np.int64)
        self.tn = np.zeros(n_labels, np.int64)

    # -- accumulation --------------------------------------------------------

    def update(self, pred: np.ndarray, target: np.ndarray) -> None:
        """Fold a batch of boolean (B, n_labels) predictions/targets."""
        pred = np.atleast_2d(np.asarray(pred, bool))
        target = np.atleast_2d(np.asarray(target, bool))
        if pred.shape != target.shape or pred.shape[1] != self.n_labels:
            raise ValueError(
                f"shape mismatch: pred {pred.shape} target {target.shape} "
                f"n_labels {self.n_labels}")
        eq = pred == target
        self.n += pred.shape[0]
        self.exact += int(np.sum(np.all(eq, axis=1)))
        self.wrong += int(np.sum(~eq))
        self.tp += np.sum(pred & target, axis=0)
        self.fp += np.sum(pred & ~target, axis=0)
        self.fn += np.sum(~pred & target, axis=0)
        self.tn += np.sum(~pred & ~target, axis=0)

    def merge(self, other: "StreamingCounts") -> None:
        if other.n_labels != self.n_labels:
            raise ValueError("cannot merge counts with different n_labels")
        self.n += other.n
        self.exact += other.exact
        self.wrong += other.wrong
        self.tp += other.tp
        self.fp += other.fp
        self.fn += other.fn
        self.tn += other.tn

    # -- derived metrics -----------------------------------------------------

    @property
    def subset_accuracy(self) -> float:
        return self.exact / self.n if self.n else 0.0

    @property
    def hamming_loss(self) -> float:
        return self.wrong / (self.n * self.n_labels) if self.n else 0.0

    def fbeta(self, beta: float = 0.5) -> np.ndarray:
        """Per-label F-beta, 0/0 -> 0 like the jnp/sklearn reference."""
        precision = _safe_div(self.tp, self.tp + self.fp)
        recall = _safe_div(self.tp, self.tp + self.fn)
        b2 = float(beta) * float(beta)
        return np.asarray(_safe_div(
            (1.0 + b2) * precision * recall, b2 * precision + recall),
            np.float64)

    def confusion(self) -> np.ndarray:
        """(n_labels, 2, 2) int64 laid out [[tn, fp], [fn, tp]]."""
        return np.stack([
            np.stack([self.tn, self.fp], axis=-1),
            np.stack([self.fn, self.tp], axis=-1),
        ], axis=-2)

    def summary(self, beta: float = 0.5) -> Dict[str, object]:
        return {
            "n": self.n,
            "subset_accuracy": self.subset_accuracy,
            "hamming_loss": self.hamming_loss,
            "fbeta": [float(x) for x in self.fbeta(beta)],
        }


def batch_counts(
    probs: np.ndarray,
    target: np.ndarray,
    *,
    threshold: float = 0.5,
    n_labels: Optional[int] = None,
) -> StreamingCounts:
    """One-shot batch fold: probabilities + boolean targets -> counts."""
    probs = np.atleast_2d(np.asarray(probs, np.float32))
    counts = StreamingCounts(n_labels or probs.shape[1])
    counts.update(threshold_probs(probs, threshold),
                  np.atleast_2d(np.asarray(target)).astype(bool))
    return counts
