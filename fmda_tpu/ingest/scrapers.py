"""Web scrapers: economic calendar, VIX spot, COT reports.

The reference runs each scraper as a forked billiard process hosting a
Scrapy/Twisted reactor with its own Kafka producer
(economic_indicators_spider.py:212-264 and siblings) — heavyweight
machinery to work around ``ReactorNotRestartable``.  Here each scraper is a
plain object: fetch page(s) through the injectable transport, parse with
the stdlib DOM, publish to the bus.  No subprocesses, no reactors.

Parsing targets the same page structures the reference's xpaths select:

- Investing.com economic calendar rows (``tr[id*=eventRowId]`` with
  ``data-event-datetime``, country in ``td/span/@title``, importance in
  ``data-img_key``, actual/previous/forecast cells —
  economic_indicators_spider.py:146-199);
- cnbc.com VIX quote (``span.last.original`` — vix_spider.py:85);
- tradingster.com COT index -> report tables (Asset Manager / Leveraged
  Funds / Managed Money rows — cot_reports_spider.py:103-156).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
import os
import re
from typing import Dict, List, Optional, Sequence

from fmda_tpu.config import FeatureConfig
from fmda_tpu.ingest.htmldom import Element, parse_html
from fmda_tpu.ingest.transport import Transport, live_transport
from fmda_tpu.utils.jsonutils import to_number
from fmda_tpu.utils.timeutils import TS_FORMAT

log = logging.getLogger("fmda_tpu.ingest")


class SentItemsRegistry:
    """Dedup registry of already-published calendar items.

    The reference keeps a pickle (``items.pickle``) rewritten by every
    spider run and reset per session (producer.py:108-109,
    economic_indicators_spider.py:42-48,67-96).  Same semantics, JSON file,
    explicit API.
    """

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path
        self._seen: Dict[str, bool] = {}
        if path and os.path.exists(path):
            with open(path) as fh:
                self._seen = json.load(fh)

    @staticmethod
    def _key(schedule_dt: str, event: str) -> str:
        return f"{schedule_dt}|{event}"

    def is_new(self, schedule_dt: str, event: str) -> bool:
        return self._key(schedule_dt, event) not in self._seen

    def mark_sent(self, schedule_dt: str, event: str) -> None:
        self._seen[self._key(schedule_dt, event)] = True
        if self.path:
            with open(self.path, "w") as fh:
                json.dump(self._seen, fh)

    def reset(self) -> None:
        self._seen = {}
        if self.path:
            with open(self.path, "w") as fh:
                json.dump(self._seen, fh)


def _clean_metric(raw: Optional[str]) -> Optional[str]:
    if raw is None:
        return None
    return raw.strip().strip("%MBK ")


class EconomicCalendarScraper:
    """Scrapes released economic indicators and merges them into the
    zero-filled template message (config.py:58-65 semantics)."""

    URL = "https://www.investing.com/economic-calendar/"

    def __init__(
        self,
        features: FeatureConfig,
        countries: Sequence[str] = ("United States",),
        importance: Sequence[str] = ("1", "2", "3"),
        transport: Optional[Transport] = None,
        registry: Optional[SentItemsRegistry] = None,
    ) -> None:
        self.features = features
        self.countries = tuple(countries)
        self.importance = tuple("bull" + i for i in importance)
        self.transport = transport or live_transport()
        self.registry = registry or SentItemsRegistry()

    def parse(self, html: str, current_dt: _dt.datetime) -> List[Dict]:
        """Extract released (past, matching) indicator items from the page."""
        root = parse_html(html)
        items: List[Dict] = []
        for row in root.find_all("tr"):
            if "eventRowId" not in (row.attrs.get("id") or ""):
                continue
            dt_str = row.attrs.get("data-event-datetime")
            if not dt_str:
                continue
            event_dt = _dt.datetime.strptime(dt_str, "%Y/%m/%d %H:%M:%S")
            if current_dt < event_dt:
                continue  # only events that already released

            country_el = row.find("span", title="")
            country = None
            for span in row.find_all("span"):
                if "title" in span.attrs:
                    country = span.attrs["title"]
                    break
            importance_el = None
            for td in row.find_all("td"):
                if "data-img_key" in td.attrs:
                    importance_el = td.attrs["data-img_key"]
                    break
            if country not in self.countries or importance_el not in self.importance:
                continue

            event_cell = row.find("td", class_="event")
            if event_cell is None:
                continue
            link = event_cell.find("a")
            event_name = (link.text if link else event_cell.text).strip(" \r\n\t")
            # strip trailing period qualifiers like "(Jan)"
            m = re.findall(r"(.*?)(?=.\([a-zA-Z]{3}\))", event_name)
            if m:
                event_name = m[0].strip()
            if event_name not in self.features.event_list:
                continue

            actual = previous = forecast = None
            for td in row.find_all("td"):
                td_id = td.attrs.get("id") or ""
                if "eventActual" in td_id:
                    actual = _clean_metric(td.own_text)
                elif "eventPrevious" in td_id:
                    span = td.find("span")
                    previous = _clean_metric(span.text if span else td.text)
                elif "eventForecast" in td_id:
                    forecast = _clean_metric(td.own_text)
            if not actual or actual == "\xa0":
                continue  # not yet released

            actual_f = float(actual)
            prev_diff = float(previous) - actual_f if previous and previous != "\xa0" else 0.0
            forc_diff = (
                float(forecast) - actual_f if forecast and forecast != "\xa0" else None
            )
            items.append(
                {
                    "Timestamp": current_dt.strftime(TS_FORMAT),
                    "Schedule_datetime": dt_str,
                    "Event": event_name.replace(" ", "_"),
                    event_name.replace(" ", "_"): {
                        "Actual": actual_f,
                        "Prev_actual_diff": prev_diff,
                        "Forc_actual_diff": forc_diff,
                    },
                }
            )
        return items

    def scrape(self, current_dt: _dt.datetime) -> Dict:
        """Fetch + parse + dedup; returns ONE merged template message (new
        items replace zeros; everything else stays 0 —
        economic_indicators_spider.py:67-96)."""
        html = self.transport.get(self.URL).decode("utf-8", "replace")
        items = self.parse(html, current_dt)
        message = self.features.empty_ind_message()
        message["Timestamp"] = current_dt.strftime(TS_FORMAT)
        for item in items:
            if not self.registry.is_new(item["Schedule_datetime"], item["Event"]):
                continue
            self.registry.mark_sent(item["Schedule_datetime"], item["Event"])
            event_key = item["Event"]
            payload = dict(item[event_key])
            if payload.get("Forc_actual_diff") is None:
                payload["Forc_actual_diff"] = 0
            message[event_key] = payload
        return message


class VIXScraper:
    """Spot VIX from cnbc.com (vix_spider.py:85)."""

    URL = "https://www.cnbc.com/quotes/?symbol=.VIX"

    def __init__(self, transport: Optional[Transport] = None) -> None:
        self.transport = transport or live_transport()

    def parse(self, html: str) -> float:
        root = parse_html(html)
        span = root.find("span", class_="last")
        if span is None:
            raise ValueError("VIX quote element not found")
        return float(span.text.replace(",", "").strip())

    def scrape(self, current_dt: _dt.datetime) -> Dict:
        html = self.transport.get(self.URL).decode("utf-8", "replace")
        return {
            "VIX": self.parse(html),
            "Timestamp": current_dt.strftime(TS_FORMAT),
        }


class COTScraper:
    """Commitment-of-Traders positioning, two-hop crawl
    (cot_reports_spider.py:103-156)."""

    INDEX_URL = "https://www.tradingster.com/cot"

    def __init__(
        self,
        report_subject: str,
        transport: Optional[Transport] = None,
        index_url: Optional[str] = None,
    ) -> None:
        self.report_subject = report_subject
        self.transport = transport or live_transport()
        self.index_url = index_url or self.INDEX_URL

    def find_report_url(self, index_html: str) -> Optional[str]:
        root = parse_html(index_html)
        for row in root.find_all("tr"):
            cells = row.find_all("td")
            if not cells:
                continue
            if cells[0].text.strip() != self.report_subject:
                continue
            if len(cells) >= 3:
                link = cells[2].find("a")
                if link is not None and "href" in link.attrs:
                    return link.attrs["href"]
        return None

    def parse_report(self, html: str, current_dt: _dt.datetime) -> Dict:
        root = parse_html(html)
        message: Dict = {"Timestamp": current_dt.strftime(TS_FORMAT)}
        for row in root.find_all("tr"):
            strong = row.find("strong")
            if strong is None:
                continue
            name = strong.text.strip(" /")
            if not any(g in name for g in ("Asset Manager", "Leveraged", "Managed Money")):
                continue
            group = name.split()[0]
            cells = row.find_all("td")
            if len(cells) < 6:
                continue

            def cell_value(cell: Element) -> str:
                return cell.own_text.strip().strip(" %").replace(",", "")

            def cell_change(cell: Element) -> str:
                span = cell.find("span")
                return (span.text if span else "0").replace(",", "").strip()

            message[group] = {
                f"{group}_long_pos": to_number(cell_value(cells[1])),
                f"{group}_long_pos_change": to_number(cell_change(cells[1])),
                f"{group}_long_open_int": to_number(cell_value(cells[2])),
                f"{group}_short_pos": to_number(cell_value(cells[4])),
                f"{group}_short_pos_change": to_number(cell_change(cells[4])),
                f"{group}_short_open_int": to_number(cell_value(cells[5])),
            }
        return message

    def scrape(self, current_dt: _dt.datetime) -> Optional[Dict]:
        index_html = self.transport.get(self.index_url).decode("utf-8", "replace")
        report_url = self.find_report_url(index_html)
        if report_url is None:
            log.warning("COT report for %r not found", self.report_subject)
            return None
        if report_url.startswith("/"):
            from urllib.parse import urljoin

            report_url = urljoin(self.index_url, report_url)
        report_html = self.transport.get(report_url).decode("utf-8", "replace")
        return self.parse_report(report_html, current_dt)
