"""HTTP transport abstraction with record/replay.

The reference talks to the outside world through ``requests.get`` scattered
in clients (getMarketData.py:105/188/255) and through live Scrapy crawls —
none of it testable offline.  Here every network touch goes through a
:class:`Transport`, so the whole acquisition layer runs against recorded
fixtures in tests and air-gapped environments (SURVEY.md §4 golden-replay
strategy).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time as _time
from typing import Dict, List, Optional, Protocol

from fmda_tpu.obs.registry import default_registry
from fmda_tpu.obs.trace import default_tracer

log = logging.getLogger("fmda_tpu.ingest")

#: The ingest-layer metric vocabulary, in one place so the scrape
#: surface can pre-declare every series at zero (Observability.track_app
#: iterates these; a transport adding a metric must add its name here).
INGEST_COUNTER_NAMES = (
    "ingest_requests_total",
    "ingest_request_failures_total",
    "ingest_retries_total",
    "ingest_ratelimit_waits_total",
    "ingest_ratelimit_wait_seconds_total",
    "ingest_circuit_open_total",
    "ingest_circuit_shortcircuit_total",
)
INGEST_HISTOGRAM_NAMES = ("ingest_request_seconds",)


class TransportError(Exception):
    """Network failure or non-2xx response.

    ``status`` carries the HTTP status when one was received (None for
    connection-level failures); ``retry_after_s`` carries a parsed
    ``Retry-After`` header in seconds when the server sent one — the
    retry layer honors it on 429/503 instead of guessing."""

    def __init__(
        self,
        message: str,
        *,
        status: Optional[int] = None,
        retry_after_s: Optional[float] = None,
    ) -> None:
        super().__init__(message)
        self.status = status
        self.retry_after_s = retry_after_s


def _parse_retry_after(value) -> Optional[float]:
    """Seconds form of a ``Retry-After`` header value (the HTTP-date
    form is rare on rate limiters and a wrong clock would turn it into
    a pathological sleep — unparseable values are simply ignored)."""
    if value is None:
        return None
    try:
        out = float(str(value).strip())
    except ValueError:
        return None
    return out if out >= 0 else None


def _url_host(url: str) -> str:
    from urllib.parse import urlparse

    return urlparse(url).netloc or url


class Transport(Protocol):
    def get(self, url: str, headers: Optional[Dict[str, str]] = None) -> bytes:
        """Fetch a URL; returns the response body, raises TransportError."""
        ...


class UrllibTransport:
    """Live stdlib transport (no third-party HTTP dependency).

    Every request reports through the observability plane: request
    latency histogram + request/failure counters (``metrics`` overrides
    the process-default registry — tests isolate with their own).
    """

    def __init__(
        self,
        timeout_s: float = 20.0,
        user_agent: str = "fmda-tpu/0.1",
        *,
        metrics=None,
    ):
        self.timeout_s = timeout_s
        self.user_agent = user_agent
        reg = metrics if metrics is not None else default_registry()
        self._m_requests = reg.counter("ingest_requests_total")
        self._m_failures = reg.counter("ingest_request_failures_total")
        self._m_latency = reg.histogram("ingest_request_seconds")
        self._tracer = default_tracer()

    def get(self, url: str, headers: Optional[Dict[str, str]] = None) -> bytes:
        import urllib.error
        import urllib.request

        req_headers = {"User-Agent": self.user_agent}
        if headers:
            req_headers.update(headers)
        request = urllib.request.Request(url, headers=req_headers)
        self._m_requests.inc()
        t0 = _time.perf_counter()
        try:
            # span() is the shared no-op singleton when tracing is off or
            # no trace is active (e.g. a one-shot fetch outside a tick)
            with self._tracer.span("http_get", "ingest"):
                with urllib.request.urlopen(
                        request, timeout=self.timeout_s) as resp:
                    return resp.read()
        except urllib.error.HTTPError as e:  # pragma: no cover - live only
            # carry the status + Retry-After so the retry layer can obey
            # a rate limiter / recovering feed instead of hammering it
            self._m_failures.inc()
            retry_after = _parse_retry_after(
                e.headers.get("Retry-After") if e.headers else None)
            raise TransportError(
                f"GET {url} failed: {e}",
                status=int(e.code), retry_after_s=retry_after) from e
        except urllib.error.URLError as e:  # pragma: no cover - live only
            self._m_failures.inc()
            raise TransportError(f"GET {url} failed: {e}") from e
        except Exception:  # pragma: no cover - live only (e.g. a body
            # read dying mid-stream raises IncompleteRead, not URLError;
            # count it so failure-rate dashboards see the outage, but
            # keep the exception itself untranslated as before)
            self._m_failures.inc()
            raise
        finally:
            self._m_latency.observe(_time.perf_counter() - t0)


class ReplayTransport:
    """Serve responses from recorded (url-pattern -> body) fixtures.

    A fixture value may be one body, or a *sequence* of bodies replayed in
    request order (a live session hits the same URL repeatedly with
    evolving responses — the sequential form reproduces the whole day;
    after the recorded responses run out, the last one repeats).
    """

    def __init__(self, fixtures: Dict[str, object]) -> None:
        #: regex pattern -> body or list of bodies; exact strings work too
        #: (re.escape not required for urls without regex metacharacters).
        def coerce(v) -> List[bytes]:
            if isinstance(v, (list, tuple)):
                if not v:
                    raise ValueError(
                        "empty fixture sequence (a url with zero recorded "
                        "bodies can never be served)"
                    )
                return [b if isinstance(b, bytes) else str(b).encode()
                        for b in v]
            return [v if isinstance(v, bytes) else str(v).encode()]

        self.fixtures = {k: coerce(v) for k, v in fixtures.items()}
        self._cursor: Dict[str, int] = {}
        self.requests: List[str] = []

    def _serve(self, key: str) -> bytes:
        return _serve_sequential(self.fixtures, self._cursor, key)

    def get(self, url: str, headers: Optional[Dict[str, str]] = None) -> bytes:
        self.requests.append(url)
        if url in self.fixtures:
            return self._serve(url)
        for pattern in self.fixtures:
            if re.search(pattern, url):
                return self._serve(pattern)
        raise TransportError(f"no fixture for {url}")


def _serve_sequential(
    bodies_map: Dict[str, List[bytes]], cursor: Dict[str, int], key: str
) -> bytes:
    """Shared sequential-replay semantics: bodies in recorded order, the
    last one repeating once exhausted."""
    bodies = bodies_map[key]
    i = cursor.get(key, 0)
    cursor[key] = i + 1
    return bodies[min(i, len(bodies) - 1)]


def _mask_credentials(url: str) -> str:
    return re.sub(r"(token|apikey)=[^&]+", r"\1=*", url)


class SessionReplayTransport:
    """Replay a recorded session with credentials masked out of the URL
    match, so fixtures recorded with real tokens serve clients constructed
    with placeholders.  Exact (masked) URL matching — recorded keys are
    literal URLs full of regex metacharacters, so the pattern matching of
    :class:`ReplayTransport` does not apply.  Unmatched requests are
    remembered in :attr:`misses` so a replay under a mismatched config
    (different feeds/cadence than recorded) can be diagnosed."""

    def __init__(self, fixtures: Dict[str, List[bytes]]) -> None:
        self._bodies: Dict[str, List[bytes]] = {}
        for url, bodies in fixtures.items():
            if not bodies:
                raise ValueError(f"empty fixture sequence for {url}")
            self._bodies.setdefault(_mask_credentials(url), []).extend(
                b if isinstance(b, bytes) else str(b).encode()
                for b in (bodies if isinstance(bodies, (list, tuple))
                          else [bodies])
            )
        self._cursor: Dict[str, int] = {}
        self.misses: List[str] = []

    def get(self, url: str, headers: Optional[Dict[str, str]] = None) -> bytes:
        key = _mask_credentials(url)
        if key not in self._bodies:
            self.misses.append(key)
            raise TransportError(f"no recorded response for {url}")
        return _serve_sequential(self._bodies, self._cursor, key)


class RetryTransport:
    """Retry-with-backoff wrapper (SURVEY.md §5: the reference retries only
    once, with a fixed 15 s sleep, and only in serving — here any transport
    gets exponential-backoff retries with per-attempt logging).

    Backoff uses **full jitter** (delay drawn uniformly from
    ``[0, backoff_s * 2^attempt]``): the session drivers all tick on the
    same cadence, so un-jittered backoff retries every feed's clients in
    lockstep against a recovering host — the classic thundering-herd
    shape.  ``jitter=False`` restores the deterministic schedule (and
    ``rng`` injects a seeded source for tests).  A 429/503 response
    carrying ``Retry-After`` overrides the computed delay — the server
    knows its own recovery better than our schedule — capped at the
    schedule's largest backoff (``backoff_s * 2^(attempts-1)``) so a
    pathological header can never park the cadence loop.
    """

    def __init__(
        self,
        inner: Transport,
        attempts: int = 3,
        backoff_s: float = 1.0,
        sleep_fn=None,
        *,
        jitter: bool = True,
        rng=None,
        metrics=None,
    ) -> None:
        import random
        import time

        self.inner = inner
        self.attempts = attempts
        self.backoff_s = backoff_s
        self.sleep_fn = sleep_fn or time.sleep
        self.jitter = jitter
        self._rng = rng if rng is not None else random.Random()
        reg = metrics if metrics is not None else default_registry()
        self._m_retries = reg.counter("ingest_retries_total")

    def _delay(self, attempt: int, error: TransportError) -> float:
        cap = self.backoff_s * (2 ** attempt)
        if (error.status in (429, 503)
                and error.retry_after_s is not None):
            budget = self.backoff_s * (2 ** (self.attempts - 1))
            return min(error.retry_after_s, budget)
        return self._rng.uniform(0.0, cap) if self.jitter else cap

    def get(self, url: str, headers: Optional[Dict[str, str]] = None) -> bytes:
        last: Optional[Exception] = None
        for attempt in range(self.attempts):
            try:
                return self.inner.get(url, headers)
            except TransportError as e:
                last = e
                if attempt < self.attempts - 1:
                    delay = self._delay(attempt, e)
                    log.warning(
                        "GET %s failed (attempt %d/%d): %s; retrying in %.1fs",
                        url, attempt + 1, self.attempts, e, delay,
                    )
                    self._m_retries.inc()
                    self.sleep_fn(delay)
        raise TransportError(
            f"GET {url} failed after {self.attempts} attempts"
        ) from last


class CircuitOpenError(TransportError):
    """Short-circuited request: the host's breaker is open (the feed has
    been failing consecutively and its probe timer has not elapsed)."""


class CircuitBreakerTransport:
    """Per-host circuit breaker (docs/chaos.md "Data-plane faults").

    The hardened transport stack bounds one GET at ~69 s worst case
    (attempts × timeout + backoff) — survivable once, but a *dead* feed
    pays that wall on every cadence tick, starving the other feeds' slot
    in the tick loop.  The breaker makes a dead host fail in
    microseconds instead: ``failure_threshold`` consecutive failures
    trip the host **open** (counted, logged); while open every request
    short-circuits with :class:`CircuitOpenError` (a ``TransportError``
    — the session driver's per-feed isolation handles it unchanged);
    after ``reset_timeout_s`` the next request is let through as a
    **half-open probe** — success closes the breaker, failure re-opens
    it for another timer period.  State is per *host*, so one dead feed
    never opens the breaker for the rest.
    """

    def __init__(
        self,
        inner: Transport,
        *,
        failure_threshold: int = 3,
        reset_timeout_s: float = 120.0,
        clock=None,
        metrics=None,
    ) -> None:
        import time

        self.inner = inner
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        #: host -> {"failures", "state", "opened_at"} where state is
        #: "closed" | "open" | "probe" (one half-open probe in flight)
        self._hosts: Dict[str, Dict[str, object]] = {}
        reg = metrics if metrics is not None else default_registry()
        self._m_trips = reg.counter("ingest_circuit_open_total")
        self._m_short = reg.counter("ingest_circuit_shortcircuit_total")

    def state(self, url_or_host: str) -> str:
        """Current breaker state for a host (monitoring/tests)."""
        host = _url_host(url_or_host)
        with self._lock:
            entry = self._hosts.get(host)
            return str(entry["state"]) if entry else "closed"

    def _admit(self, host: str) -> None:
        """Decide whether this request may pass (raises when open)."""
        with self._lock:
            entry = self._hosts.get(host)
            if entry is None or entry["state"] == "closed":
                return
            if entry["state"] == "open" and (
                    self.clock() - entry["opened_at"]
                    >= self.reset_timeout_s):
                # timer elapsed: this request becomes the half-open probe
                entry["state"] = "probe"
                log.warning(
                    "circuit for %s half-open: probing with this request",
                    host)
                return
            # open (timer running) or another probe already in flight
            self._m_short.inc()
            raise CircuitOpenError(
                f"circuit open for {host}: {entry['failures']} consecutive "
                f"failures; next probe in <= {self.reset_timeout_s:.0f}s")

    def _record(self, host: str, ok: bool) -> None:
        with self._lock:
            entry = self._hosts.setdefault(
                host, {"failures": 0, "state": "closed", "opened_at": 0.0})
            if ok:
                if entry["state"] != "closed" or entry["failures"]:
                    log.warning("circuit for %s closed (probe succeeded)",
                                host)
                entry.update(failures=0, state="closed")
                return
            entry["failures"] = int(entry["failures"]) + 1
            tripped = (entry["state"] == "probe"
                       or entry["failures"] >= self.failure_threshold)
            if tripped and entry["state"] != "open":
                entry.update(state="open", opened_at=self.clock())
                self._m_trips.inc()
                log.warning(
                    "circuit for %s OPEN after %d consecutive failure(s); "
                    "probing again in %.0fs", host, entry["failures"],
                    self.reset_timeout_s)

    def get(self, url: str, headers: Optional[Dict[str, str]] = None) -> bytes:
        host = _url_host(url)
        self._admit(host)
        try:
            body = self.inner.get(url, headers)
        except TransportError:
            self._record(host, ok=False)
            raise
        self._record(host, ok=True)
        return body


#: Process-wide per-host last-request map shared by every
#: :class:`RateLimitTransport` on the real clock — two components each
#: defaulting to ``live_transport()`` against the same host are jointly
#: spaced, matching the reference's *global* scrapy AUTOTHROTTLE /
#: DOWNLOAD_DELAY semantics rather than per-client throttling.
_SHARED_LAST: Dict[str, float] = {}
_SHARED_LAST_LOCK = threading.Lock()


class RateLimitTransport:
    """Per-host request spacing (round-3 verdict: the reference rides
    scrapy's AUTOTHROTTLE/DOWNLOAD_DELAY machinery,
    economic_indicators_spider.py:212-255 settings; the replay-first
    design needs its own).  Requests to the same host are spaced at
    least ``min_interval_s`` apart — different hosts never block each
    other, so one slow feed cannot starve the rest of a tick.

    Instances on the real clock share one process-wide per-host map
    under a lock (round-4 advice: every client/scraper constructs its
    own ``live_transport()``, so per-instance state would not jointly
    space them, and a threaded driver needs the lock anyway).  Tests
    that inject a ``clock`` get private state so fake time never mixes
    with real-clock entries.

    Shared-state semantics (``_SHARED_LAST``): the map is global
    throttle state — it is never pruned, and instances with *different*
    ``min_interval_s`` against the same host interact (each request
    stamps the host's slot, so the next requester waits by its OWN
    interval from whoever went last — matching the reference's global
    scrapy AUTOTHROTTLE rather than per-client budgets).  Tests that
    touch real-clock instances must call :meth:`_reset_shared_state`
    (e.g. in a ``finally:``) so entries never leak across tests.
    """

    @staticmethod
    def _reset_shared_state() -> None:
        """Clear the process-wide per-host throttle map (test hygiene)."""
        with _SHARED_LAST_LOCK:
            _SHARED_LAST.clear()

    def __init__(
        self,
        inner: Transport,
        min_interval_s: float = 1.0,
        *,
        clock=None,
        sleep_fn=None,
        shared: Optional[bool] = None,
        metrics=None,
    ) -> None:
        import time

        self.inner = inner
        self.min_interval_s = min_interval_s
        if shared is None:
            shared = clock is None
        self.clock = clock or time.monotonic
        self.sleep_fn = sleep_fn or time.sleep
        reg = metrics if metrics is not None else default_registry()
        self._m_waits = reg.counter("ingest_ratelimit_waits_total")
        self._m_wait_s = reg.counter("ingest_ratelimit_wait_seconds_total")
        if shared:
            self._last = _SHARED_LAST
            self._lock = _SHARED_LAST_LOCK
        else:
            self._last: Dict[str, float] = {}
            self._lock = threading.Lock()

    @staticmethod
    def _host(url: str) -> str:
        from urllib.parse import urlparse

        return urlparse(url).netloc or url

    def get(self, url: str, headers: Optional[Dict[str, str]] = None) -> bytes:
        host = self._host(url)
        # claim-then-sleep loop: the slot timestamp is written under the
        # lock, the sleep happens outside it (a 1 s wait must not block
        # other hosts' requests through the shared map), and the claim is
        # re-checked after sleeping in case another thread took it.  The
        # iteration bound only guards against a test double whose
        # sleep_fn never advances its clock.
        for _ in range(1000):
            with self._lock:
                now = self.clock()
                last = self._last.get(host)
                wait = (
                    0.0 if last is None
                    else self.min_interval_s - (now - last)
                )
                if wait <= 0:
                    self._last[host] = now
                    break
            self._m_waits.inc()
            self._m_wait_s.inc(wait)
            self.sleep_fn(wait)
        else:
            with self._lock:
                self._last[host] = self.clock()
        return self.inner.get(url, headers)


def live_transport(
    timeout_s: float = 20.0,
    user_agent: str = "fmda-tpu/0.1",
    *,
    attempts: int = 3,
    backoff_s: float = 1.0,
    min_interval_s: float = 1.0,
    breaker_threshold: int = 3,
    breaker_reset_s: float = 120.0,
) -> Transport:
    """The hardened default for live operation: stdlib HTTP behind
    per-host rate limiting behind jittered exponential-backoff retries
    behind a per-host circuit breaker.

    Worst-case wall per GET is bounded (attempts x timeout plus up to
    ``backoff_s * (2^attempts - 1)`` of sleep — ~69 s at the defaults),
    so a dead feed degrades to a logged :class:`TransportError` the
    session driver isolates per feed (ingest/session.py), never a stuck
    tick loop — and after ``breaker_threshold`` consecutive dead ticks
    the breaker stops paying even that wall: the host fails instantly
    until its half-open probe succeeds.  Clients and scrapers construct
    this when not handed an explicit transport (tests inject
    replay/recording transports).
    """
    return CircuitBreakerTransport(
        RetryTransport(
            RateLimitTransport(
                UrllibTransport(timeout_s, user_agent),
                min_interval_s=min_interval_s,
            ),
            attempts=attempts,
            backoff_s=backoff_s,
        ),
        failure_threshold=breaker_threshold,
        reset_timeout_s=breaker_reset_s,
    )


class RecordingTransport:
    """Wrap a live transport and persist every response for later replay.

    Every response is kept, *in request order per URL* — a live session
    hits the same endpoints each tick with evolving bodies, and replaying
    the full sequence through :class:`ReplayTransport` reproduces the
    whole day.  Bodies are stored base64-encoded so binary/gzip responses
    survive the round-trip bit-exact.  The fixture file is rewritten every
    ``flush_every`` requests (and on :meth:`flush`/``close``/context exit),
    so a crash mid-session loses at most the last ``flush_every - 1``
    responses, not the whole recording.
    """

    def __init__(
        self, inner: Transport, path: str, flush_every: int = 25
    ) -> None:
        self.inner = inner
        self.path = path
        self.flush_every = max(1, flush_every)
        self.recorded: Dict[str, List[bytes]] = {}
        self._since_flush = 0

    def get(self, url: str, headers: Optional[Dict[str, str]] = None) -> bytes:
        body = self.inner.get(url, headers)
        self.recorded.setdefault(url, []).append(body)
        self._since_flush += 1
        if self._since_flush >= self.flush_every:
            self.flush()
        return body

    def flush(self) -> None:
        # atomic tmp+replace: a crash inside a flush must never destroy
        # the previously flushed recording (the whole point of flushing
        # periodically). Full rewrite per flush is fine at session scale
        # (~400 requests/day at the reference's 5-min cadence).
        import base64

        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(
                {
                    u: [base64.b64encode(b).decode("ascii") for b in bodies]
                    for u, bodies in self.recorded.items()
                },
                fh,
            )
        os.replace(tmp, self.path)
        self._since_flush = 0

    close = flush

    def __enter__(self) -> "RecordingTransport":
        return self

    def __exit__(self, *exc) -> None:
        self.flush()

    @staticmethod
    def load_fixtures(path: str) -> Dict[str, List[bytes]]:
        """Read a recorded fixture file back into ReplayTransport form.

        Accepts both the sequential format this class writes and the
        legacy one-body-per-url form.
        """
        import base64

        with open(path) as fh:
            raw = json.load(fh)
        return {
            u: (
                [base64.b64decode(x) for x in s]
                if isinstance(s, list)
                else [base64.b64decode(s)]
            )
            for u, s in raw.items()
        }
