from fmda_tpu.ingest.transport import (
    RateLimitTransport,
    RecordingTransport,
    SessionReplayTransport,
    ReplayTransport,
    RetryTransport,
    Transport,
    UrllibTransport,
    live_transport,
)
from fmda_tpu.ingest.clients import AlphaVantageClient, IEXClient, TradierCalendarClient
from fmda_tpu.ingest.scrapers import (
    COTScraper,
    EconomicCalendarScraper,
    VIXScraper,
)
from fmda_tpu.ingest.session import SessionDriver

__all__ = [
    "Transport",
    "UrllibTransport",
    "ReplayTransport",
    "RecordingTransport",
    "SessionReplayTransport",
    "RetryTransport",
    "RateLimitTransport",
    "live_transport",
    "IEXClient",
    "AlphaVantageClient",
    "TradierCalendarClient",
    "EconomicCalendarScraper",
    "VIXScraper",
    "COTScraper",
    "SessionDriver",
]
