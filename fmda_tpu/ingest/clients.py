"""Market-data API clients (IEX DEEP, Alpha Vantage, Tradier calendar).

Behavioral parity with ``getMarketData.py`` over the injectable transport:
the DEEP book is reshaped into per-level ``bids_i``/``asks_i`` dicts
(getMarketData.py:117-127), Alpha Vantage responses are reduced to the
latest bar with sanitised keys and a staleness warning — delayed data is
accepted, not dropped (getMarketData.py:208-216) — and the Tradier market
calendar gates the session (getMarketData.py:251-257).
"""

from __future__ import annotations

import datetime as _dt
import json
import logging
from typing import Dict, List, Optional

from fmda_tpu.ingest.transport import Transport, live_transport
from fmda_tpu.utils.jsonutils import change_keys, values_to_numbers
from fmda_tpu.utils.timeutils import TS_FORMAT

log = logging.getLogger("fmda_tpu.ingest")


class IEXClient:
    """IEX Cloud client; only the DEEP book endpoint is needed for parity."""

    def __init__(
        self,
        token: str,
        transport: Optional[Transport] = None,
        base_url: str = "https://cloud.iexapis.com/v1",
    ) -> None:
        self.token = token
        self.transport = transport or live_transport()
        self.base_url = base_url

    def get_deep_book(self, symbol: str, timestamp: _dt.datetime) -> Dict:
        """Order-book snapshot -> flat bus message keyed bids_i/asks_i."""
        url = (
            f"{self.base_url}/deep/book?symbols={symbol}&"
            f"token={self.token}&format=json"
        )
        raw = json.loads(self.transport.get(url))
        message: Dict = {"Timestamp": timestamp.strftime(TS_FORMAT)}
        # response shape: {SYMBOL: {"bids": [{price, size}...], "asks": [...]}}
        book = raw.get(symbol.upper()) or raw.get(symbol) or {}
        for i, level in enumerate(book.get("bids", [])):
            message[f"bids_{i}"] = {
                f"bid_{i}": level.get("price"),
                f"bid_{i}_size": level.get("size"),
            }
        for i, level in enumerate(book.get("asks", [])):
            message[f"asks_{i}"] = {
                f"ask_{i}": level.get("price"),
                f"ask_{i}_size": level.get("size"),
            }
        return message


class AlphaVantageClient:
    """Alpha Vantage intraday client (stocks + FX)."""

    def __init__(
        self,
        token: str,
        transport: Optional[Transport] = None,
        base_url: str = "https://www.alphavantage.co/query",
        staleness_warn_s: int = 4 * 60,
    ) -> None:
        self.token = token
        self.transport = transport or live_transport()
        self.base_url = base_url
        self.staleness_warn_s = staleness_warn_s

    def _url(self, function: str, symbol: str, interval: Optional[str]) -> str:
        if function.startswith("FX_"):
            from_sym, to_sym = symbol[:3], symbol[3:]
            url = (
                f"{self.base_url}?function={function}&from_symbol={from_sym}"
                f"&to_symbol={to_sym}"
            )
        else:
            url = f"{self.base_url}?function={function}&symbol={symbol}"
        if interval:
            url += f"&interval={interval}"
        return url + f"&apikey={self.token}&datatype=json"

    def get_latest_bar(
        self,
        symbol: str,
        timestamp: _dt.datetime,
        function: str = "TIME_SERIES_INTRADAY",
        interval: str = "5min",
    ) -> Dict:
        """Latest OHLCV bar with sanitised keys and the ingestion timestamp.

        Delayed bars are *accepted* with a warning — the reference prefers a
        fractional bar over a gap (getMarketData.py:208-216).
        """
        raw = json.loads(self.transport.get(self._url(function, symbol, interval)))
        if not raw:
            raise ValueError("Alpha Vantage returned an empty response")
        if "Error Message" in raw:
            raise ValueError(raw["Error Message"])
        series_keys = [k for k in raw if k != "Meta Data"]
        if not series_keys:
            raise ValueError(f"no time series in response: {list(raw)}")
        series = raw[series_keys[0]]
        last_dt_str = max(series)  # keys are 'YYYY-MM-DD HH:MM:SS'
        last_dt = _dt.datetime.strptime(last_dt_str, TS_FORMAT)
        if last_dt < timestamp.replace(tzinfo=None) - _dt.timedelta(
            seconds=self.staleness_warn_s
        ):
            log.warning(
                "RETURNED DATA IS DELAYED (bar %s vs now %s) — using anyway",
                last_dt_str, timestamp.strftime(TS_FORMAT),
            )
        bar = change_keys(series[last_dt_str], ". ", "_")
        bar = values_to_numbers(bar)
        bar["Timestamp"] = timestamp.strftime(TS_FORMAT)
        return bar


class TradierCalendarClient:
    """Market calendar for session gating (getMarketData.py:251-257)."""

    def __init__(
        self,
        token: str,
        transport: Optional[Transport] = None,
        base_url: str = "https://api.tradier.com/v1",
    ) -> None:
        self.token = token
        self.transport = transport or live_transport()
        self.base_url = base_url

    def get_market_calendar(self) -> List[Dict]:
        body = self.transport.get(
            f"{self.base_url}/markets/calendar",
            headers={
                "Authorization": f"Bearer {self.token}",
                "Accept": "application/json",
            },
        )
        return json.loads(body)["calendar"]["days"]["day"]
