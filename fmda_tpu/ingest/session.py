"""Ingestion session driver: market gating + cadence loop -> bus.

The role of ``producer.py``: every ``freq`` seconds while the market is
open, pull the order book and OHLCV bar, run the three scrapers, and
publish everything onto the bus topics.  Differences from the reference,
by design:

- no module-level side effects (producer.py starts a session at import,
  :258-263) — sessions are objects you construct and run;
- clock and sleep are injectable, so a whole trading day replays in
  milliseconds in tests;
- scrapers run in-process through transports (no billiard forks);
- per-source failures are isolated: one feed erroring logs a warning and
  the tick continues (the reference's try wraps the whole loop body,
  producer.py:113-157, so one bad feed kills the entire tick).
"""

from __future__ import annotations

import datetime as _dt
import logging
import time as _time
from typing import Callable, Dict, Optional

from fmda_tpu.config import (
    SessionConfig,
    TOPIC_COT,
    TOPIC_DEEP,
    TOPIC_IND,
    TOPIC_VIX,
    TOPIC_VOLUME,
)
from fmda_tpu.chaos.inject import default_chaos
from fmda_tpu.ingest.clients import AlphaVantageClient, IEXClient, TradierCalendarClient
from fmda_tpu.ingest.scrapers import COTScraper, EconomicCalendarScraper, VIXScraper
from fmda_tpu.obs.trace import default_tracer
from fmda_tpu.stream.bus import MessageBus
from fmda_tpu.utils.timeutils import forex_market_hours, get_timezone, stock_market_hours

log = logging.getLogger("fmda_tpu.ingest")

#: chaos injection singleton, captured once at import: ``feed:<topic>``
#: points let a fault plan take one feed down for a window — the
#: existing per-feed isolation absorbs the raise, and the engine's
#: degraded-mode join keeps rows flowing (docs/chaos.md)
_CHAOS = default_chaos()


class SessionDriver:
    """One trading day's acquisition session."""

    def __init__(
        self,
        bus: MessageBus,
        config: SessionConfig,
        *,
        iex: Optional[IEXClient] = None,
        alpha_vantage: Optional[AlphaVantageClient] = None,
        calendar: Optional[TradierCalendarClient] = None,
        indicator_scraper: Optional[EconomicCalendarScraper] = None,
        vix_scraper: Optional[VIXScraper] = None,
        cot_scraper: Optional[COTScraper] = None,
        now_fn: Optional[Callable[[], _dt.datetime]] = None,
        sleep_fn: Callable[[float], None] = _time.sleep,
    ) -> None:
        self.bus = bus
        self.config = config
        self.iex = iex
        self.alpha_vantage = alpha_vantage
        self.calendar = calendar
        self.indicator_scraper = indicator_scraper
        self.vix_scraper = vix_scraper
        self.cot_scraper = cot_scraper
        tz = get_timezone(config.timezone)
        self.now_fn = now_fn or (lambda: _dt.datetime.now(tz).replace(tzinfo=None))
        self.sleep_fn = sleep_fn
        self.ticks = 0
        self._tracer = default_tracer()

    # -- market gating (producer.py:212-243) ---------------------------------

    def market_hours_today(self) -> Optional[Dict[str, _dt.datetime]]:
        """Today's market window, or None if closed."""
        now = self.now_fn()
        if self.config.source == "IEX":
            if self.calendar is None:
                raise ValueError("stock sessions need a calendar client")
            days = self.calendar.get_market_calendar()
            today = now.date().strftime("%Y-%m-%d")
            match = [d for d in days if d.get("date") == today]
            if not match or match[0].get("status") != "open":
                log.warning("market closed today (%s)", today)
                return None
            return stock_market_hours(now, match[0])
        return forex_market_hours(now)

    # -- one tick (the intraday_data loop body, producer.py:111-150) ---------

    def run_tick(self) -> Dict[str, bool]:
        """Fetch + publish every enabled feed once; returns per-feed success.

        When tracing is enabled (and the tick is sampled), the whole tick
        runs inside a ``session_tick`` root span: every transport GET
        becomes a child span, and every feed message published here
        carries the tick's trace context in-band — the engine, warehouse
        land, and serving stitch their stages into the same trace
        (docs/observability.md, "Tracing a tick").
        """
        with self._tracer.root("session_tick", "ingest"):
            return self._run_tick()

    def _run_tick(self) -> Dict[str, bool]:
        now = self.now_fn()
        results: Dict[str, bool] = {}

        def attempt(name: str, fn: Callable[[], Optional[Dict]], topic: str) -> None:
            try:
                if _CHAOS.enabled:
                    # an injected feed outage is a failed fetch: the
                    # except below counts it like any dead endpoint
                    _CHAOS.check("feed:" + topic)
                message = fn()
                if message is not None:
                    self.bus.publish(topic, message)
                    results[name] = True
                else:
                    results[name] = False
            except Exception as e:  # noqa: BLE001 — feed isolation
                log.warning("%s feed failed this tick: %s", name, e)
                results[name] = False

        if self.iex is not None:
            attempt(
                "deep",
                lambda: self.iex.get_deep_book(self.config.symbol, now),
                TOPIC_DEEP,
            )
        if self.alpha_vantage is not None:
            interval = f"{self.config.freq_s // 60:d}min"
            if interval in ("1min", "5min", "15min", "30min", "60min"):
                attempt(
                    "volume",
                    lambda: self.alpha_vantage.get_latest_bar(
                        self.config.symbol.upper(), now, interval=interval
                    ),
                    TOPIC_VOLUME,
                )
            else:
                log.warning("%r interval is not supported", interval)
        if self.indicator_scraper is not None:
            attempt("ind", lambda: self.indicator_scraper.scrape(now), TOPIC_IND)
        if self.cot_scraper is not None:
            attempt("cot", lambda: self.cot_scraper.scrape(now), TOPIC_COT)
        if self.vix_scraper is not None:
            attempt("vix", lambda: self.vix_scraper.scrape(now), TOPIC_VIX)

        self.ticks += 1
        return results

    # -- the session loop ------------------------------------------------------

    def run_session(self, max_ticks: Optional[int] = None) -> int:
        """Tick every ``freq_s`` seconds while the market is open; returns
        the number of ticks executed."""
        hours = self.market_hours_today()
        if hours is None:
            return 0
        if self.indicator_scraper is not None:
            # fresh dedup registry per session (producer.py:108-109)
            self.indicator_scraper.registry.reset()
        executed = 0
        while True:
            now = self.now_fn()
            if not (hours["market_start"] <= now <= hours["market_end"]):
                log.warning("market closed at %s; session over", now)
                break
            start = _time.perf_counter()
            self.run_tick()
            executed += 1
            if max_ticks is not None and executed >= max_ticks:
                break
            elapsed = _time.perf_counter() - start
            self.sleep_fn(max(self.config.freq_s - elapsed, 0.0))
        return executed
