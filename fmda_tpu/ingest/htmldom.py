"""Minimal DOM built on stdlib html.parser — the scrapers' xpath stand-in.

The reference scrapes with Scrapy/Twisted xpath selectors
(economic_indicators_spider.py:144-199, vix_spider.py:85,
cot_reports_spider.py:103-156).  The framework's scrapers need only a tiny
subset: find elements by tag/attribute, read text, walk children — small
enough to implement over ``html.parser`` with zero dependencies.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from html.parser import HTMLParser
from typing import Dict, Iterator, List, Optional

_VOID_TAGS = {
    "area", "base", "br", "col", "embed", "hr", "img", "input",
    "link", "meta", "param", "source", "track", "wbr",
}


@dataclass
class Element:
    tag: str
    attrs: Dict[str, str] = field(default_factory=dict)
    children: List["Element"] = field(default_factory=list)
    texts: List[str] = field(default_factory=list)
    parent: Optional["Element"] = None

    def iter(self) -> Iterator["Element"]:
        yield self
        for child in self.children:
            yield from child.iter()

    def find_all(self, tag: str, **attrs: str) -> List["Element"]:
        """All descendants with this tag whose attributes contain the given
        values (class matching is token-wise, like CSS)."""
        out = []
        for el in self.iter():
            if el is self or el.tag != tag:
                continue
            ok = True
            for key, want in attrs.items():
                key = key.rstrip("_")  # allow class_=
                have = el.attrs.get(key)
                if have is None:
                    ok = False
                elif key == "class":
                    if want not in have.split() and want != have:
                        ok = False
                elif want not in have:
                    ok = False
            if ok:
                out.append(el)
        return out

    def find(self, tag: str, **attrs: str) -> Optional["Element"]:
        found = self.find_all(tag, **attrs)
        return found[0] if found else None

    @property
    def text(self) -> str:
        """All descendant text, concatenated (xpath ``string()``)."""
        parts = list(self.texts)
        for child in self.children:
            parts.append(child.text)
        return "".join(parts)

    @property
    def own_text(self) -> str:
        """Direct text nodes only (xpath ``text()``)."""
        return "".join(self.texts)


class _TreeBuilder(HTMLParser):
    def __init__(self) -> None:
        super().__init__(convert_charrefs=True)
        self.root = Element("__root__")
        self._stack = [self.root]

    def handle_starttag(self, tag, attrs):
        el = Element(tag, dict(attrs), parent=self._stack[-1])
        self._stack[-1].children.append(el)
        if tag not in _VOID_TAGS:
            self._stack.append(el)

    def handle_startendtag(self, tag, attrs):
        el = Element(tag, dict(attrs), parent=self._stack[-1])
        self._stack[-1].children.append(el)

    def handle_endtag(self, tag):
        # close the nearest matching open tag (tolerates sloppy HTML)
        for i in range(len(self._stack) - 1, 0, -1):
            if self._stack[i].tag == tag:
                del self._stack[i:]
                break

    def handle_data(self, data):
        if data:
            self._stack[-1].texts.append(data)


def parse_html(html: str) -> Element:
    builder = _TreeBuilder()
    builder.feed(html if isinstance(html, str) else html.decode("utf-8", "replace"))
    return builder.root
