"""Long-context training step over a (dp, sp) mesh (north-star config 3).

Builds the full training step — sequence-parallel forward, weighted BCE,
gradients, global-norm clip, Adam — jitted over the mesh: batch sharded on
``dp``, the window's time axis sharded on ``sp`` (seq_len=1024-class
windows never materialise on one device), params/optimizer replicated.
Gradients all-reduce over ICI automatically; the recurrent carry crosses sp
shards inside :func:`fmda_tpu.parallel.seq_parallel.sp_gru_scan`.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from fmda_tpu.config import ModelConfig
from fmda_tpu.parallel.mesh import batch_sharding, replicated_sharding, sequence_sharding
from fmda_tpu.parallel.seq_parallel import make_sp_forward
from fmda_tpu.train.losses import weighted_bce_with_logits

log = logging.getLogger("fmda_tpu.parallel")


def make_sp_train_step(
    mesh: jax.sharding.Mesh,
    model_cfg: ModelConfig,
    seq_len: int,
    optimizer: optax.GradientTransformation,
    *,
    weight: Optional[jax.Array] = None,
    pos_weight: Optional[jax.Array] = None,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
    n_microbatches: int = 1,
    flash_interpret: bool = False,
):
    """Returns ``step(params, opt_state, x, y) -> (params, opt_state, loss)``
    jitted over the mesh.  ``n_microbatches > 1`` runs the bubble-filling
    pipelined recurrence (per-dp-shard batch must be divisible by it).

    ``model_cfg.cell`` picks the sequence core: the GRU's staged/pipelined
    carry-handoff scan, or (``"attn"``) the temporal transformer whose
    attention runs as a K/V ring (fmda_tpu.parallel.ring_attention) —
    same mesh, same shardings, different collective program.

    Note: every sp forward is the *deterministic* apply —
    ``model_cfg.dropout`` is ignored during sp training (all cells; the
    single-device trainer is the dropout-regularised path).  Set
    dropout=0 in sp configs to make that explicit."""
    if model_cfg.dropout:
        log.warning(
            "sp training runs the deterministic forward; "
            "ModelConfig.dropout=%.2f is ignored", model_cfg.dropout)
    if model_cfg.cell == "attn":
        from fmda_tpu.parallel.ring_attention import make_attn_sp_forward

        if n_microbatches != 1:
            raise ValueError(
                "n_microbatches applies only to the recurrent cells: the "
                "ring-attention program has no pipeline bubble to fill")
        # flash_interpret runs the ring's fused-kernel fold in interpret
        # mode — CPU-mesh tests exercise the REAL pod program (remat +
        # shard_map + kernel custom-vjp) without hardware
        forward = make_attn_sp_forward(
            mesh, model_cfg, seq_len, dp_axis=dp_axis, sp_axis=sp_axis,
            flash_interpret=flash_interpret)
    elif model_cfg.cell == "gru":
        forward = make_sp_forward(
            mesh, model_cfg, seq_len, dp_axis=dp_axis, sp_axis=sp_axis,
            n_microbatches=n_microbatches,
        )
    else:
        # loud dispatch (fmda_tpu.ops.dispatch): this used to be a bare
        # `else` that routed ANY non-attn cell — lstm, ssm, a future
        # family — into the GRU carry-handoff scan, which at best crashes
        # on the sibling's param shapes and at worst runs wrong math
        raise ValueError(
            "sequence-parallel training implements cell='gru' (the "
            "staged carry-handoff scan) and cell='attn' (the K/V ring); "
            f"got ModelConfig.cell={model_cfg.cell!r} — train lstm on "
            "the dp-only path and ssm in its parallel scan mode "
            "(fmda_tpu.train.Trainer)")
    if model_cfg.remat:
        # long-context windows: recompute the forward in the backward pass
        # instead of keeping every per-step hidden alive (HBM is the
        # constraint at seq_len=1024-class windows, SURVEY §5)
        forward = jax.checkpoint(forward)

    # donate params + optimizer state (the single-device Trainer's step
    # donates too): the updated tree reuses the old buffers instead of
    # holding both alive across the update — on long-context configs the
    # Adam moments are the largest replicated tree in HBM.  x/y are NOT
    # donated (callers step the same batch repeatedly).
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, x, y):
        def loss_fn(p):
            logits = forward(p, x)
            return weighted_bce_with_logits(
                logits, y, weight=weight, pos_weight=pos_weight
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state_new = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state_new, loss

    return step


def place_fresh_copy(tree, sharding):
    """Copy-before-place for trees the train step will DONATE.

    ``jax.device_put`` may alias its input when the placement already
    matches — donating an alias would silently delete the caller's
    original tree (e.g. a params0 reused to init several step variants),
    surfacing later as "deleted buffer" errors.  Shared by the
    single-host and multi-host input-placement helpers so neither can
    drift back to the aliasing bug (ADVICE r5).
    """
    return jax.device_put(
        jax.tree.map(lambda a: jnp.array(a, copy=True), tree), sharding)


def shard_train_inputs(
    mesh: jax.sharding.Mesh,
    x,
    y,
    params,
    opt_state,
    *,
    dp_axis: str = "dp",
    sp_axis: str = "sp",
) -> Tuple:
    """Place (x, y, params, opt_state) with the step's expected shardings.

    The returned params/opt_state are fresh copies
    (:func:`place_fresh_copy`): the train step DONATES them, so handing
    back an alias of the caller's tree would consume it on first call.
    """
    x = jax.device_put(
        jnp.asarray(x), sequence_sharding(mesh, dp_axis, sp_axis))
    y = jax.device_put(jnp.asarray(y), batch_sharding(mesh, dp_axis))
    replicated = replicated_sharding(mesh)
    return (x, y, place_fresh_copy(params, replicated),
            place_fresh_copy(opt_state, replicated))
